"""The in-memory MVCC storage engine.

Re-design of the reference's InMemoryStorage
(/root/reference/src/storage/v2/inmemory/storage.hpp:109): optimistic MVCC
with undo-delta chains (mvcc.py), commit serialization under an engine lock,
abort via reverse-undo, and epoch-style GC that truncates delta chains older
than the oldest active transaction. Two storage modes:

  IN_MEMORY_TRANSACTIONAL — full MVCC (default)
  IN_MEMORY_ANALYTICAL    — no MVCC/WAL, direct mutation, bulk-load fast path

TPU-first twist: the engine keeps a monotonically bumped `topology_version`
so the device CSR snapshot cache (memgraph_tpu.ops.csr) knows when graph
topology changed and a re-export is needed.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Callable, Iterator, Optional

from ..exceptions import ConstraintViolation, SerializationError, StorageError
from ..utils.ids import NameIdMapper
from ..utils.locks import tracked_lock
from ..utils.sanitize import (mvcc_event, shared_field, shared_read,
                              shared_write)
from .common import (TRANSACTION_ID_START, Gid, IsolationLevel, StorageMode,
                     View)
from .constraints import Constraints
from .delta import CommitInfo, DeltaAction
from .indexes import Indices
from .mvcc import (materialize_edge, materialize_vertex, prepare_for_write,
                   push_delta)
from .objects import (ADJ_INDEX_THRESHOLD, Edge, Vertex, adj_map_add,
                      adj_map_build, adj_map_remove)

log = logging.getLogger(__name__)


class ChangeLogUnknowable:
    """Typed "unknowable" verdict from :meth:`Storage.changes_between`.

    The bounded change log cannot always answer a (v_from, v_to] query:
    the deque may have wrapped past v_from (``reason="log_wrapped"``), a
    bump may not have recorded its gids (``reason="untracked_bump"``),
    or the log may be empty for a non-empty range. Consumers MUST
    branch on this explicitly (falsy, so ``if changed:`` treats it like
    an unusable delta) and fall back to a full rebuild — silently
    treating it as "no changes" would serve stale data.
    """

    __slots__ = ("reason", "oldest_logged_version")

    def __init__(self, reason: str, oldest_logged_version: int) -> None:
        self.reason = reason
        self.oldest_logged_version = oldest_logged_version

    def __bool__(self) -> bool:
        return False

    def __repr__(self) -> str:
        return (f"ChangeLogUnknowable({self.reason!r}, "
                f"oldest_logged_version={self.oldest_logged_version})")


@dataclass
class StorageConfig:
    storage_mode: StorageMode = StorageMode.IN_MEMORY_TRANSACTIONAL
    isolation_level: IsolationLevel = IsolationLevel.SNAPSHOT_ISOLATION
    gc_interval_sec: float = 30.0
    durability_dir: Optional[str] = None
    wal_enabled: bool = False
    # WAL v2 segments rotate at this size; old segments are pruned once
    # the newest snapshot covers them (reference: --storage-wal-file-size-kib)
    wal_segment_size: int = 64 * 1024 * 1024
    snapshot_on_exit: bool = False
    properties_on_edges: bool = True
    snapshot_retention_count: int = 3
    # skip the delta/WAL record when a SET writes the identical value
    # (reference: --storage-delta-on-identical-property-update)
    delta_on_identical_property_update: bool = True
    # auto-create label / edge-type indexes for labels and types first
    # touched by a commit (reference: --storage-automatic-*-index-
    # creation-enabled)
    automatic_label_index: bool = False
    automatic_edge_type_index: bool = False
    # run a GC cycle after every committing transaction instead of only
    # on the periodic timer (reference: --storage-gc-aggressive)
    gc_aggressive: bool = False
    # continue with whatever recovered instead of failing startup when
    # durability files are damaged (reference:
    # --storage-allow-recovery-failure)
    allow_recovery_failure: bool = False


@dataclass
class BatchInsert:
    """One batch_insert() call's created objects, recorded on the owning
    transaction so commit can emit a single columnar BATCH_INSERT WAL
    record instead of one record per object."""
    vertices: list = field(default_factory=list)
    edges: list = field(default_factory=list)


class _Namer:
    """Adapter giving constraints readable names in error messages."""

    def __init__(self, storage: "InMemoryStorage") -> None:
        self._s = storage

    def label(self, label_id: int) -> str:
        return self._s.label_mapper.id_to_name(label_id)

    def prop(self, prop_id: int) -> str:
        return self._s.property_mapper.id_to_name(prop_id)


class Transaction:
    __slots__ = ("id", "start_ts", "commit_info", "deltas", "isolation",
                 "storage", "touched_vertices", "touched_edges", "commit_ts",
                 "topology_snapshot", "batches", "edge_prop_endpoint_gids",
                 "stream_offsets")

    def __init__(self, txn_id: int, start_ts: int, isolation: IsolationLevel,
                 storage: "InMemoryStorage") -> None:
        self.id = txn_id
        self.start_ts = start_ts
        self.commit_info = CommitInfo(txn_id)
        self.deltas = []
        self.isolation = isolation
        self.storage = storage
        self.touched_vertices: dict[int, Vertex] = {}
        self.touched_edges: dict[int, Edge] = {}
        self.commit_ts: Optional[int] = None   # set at commit
        self.topology_snapshot = 0             # set by _begin_transaction
        self.batches = None  # list[BatchInsert] once batch_insert is used
        # endpoint gids of edges touched WITHOUT their vertices entering
        # touched_vertices (only _edge_set_property) — lets the commit/abort
        # topology bump skip re-walking every touched edge's endpoints
        self.edge_prop_endpoint_gids = None
        # stream name -> source position, WAL-framed inside THIS commit
        # (exactly-once boundary for streaming ingestion)
        self.stream_offsets = None

    def effective_start_ts(self) -> int:
        # Once committed, the transaction's snapshot ADVANCES to its commit
        # ts: accessors returned to the client (RETURN n materialized after
        # stream exhaustion) must see the transaction's own committed state
        # — commit rewrote the deltas' timestamps to commit_ts, so the
        # own-write (ts == txn_id) rule no longer identifies them
        # (reference: storage/v2/mvcc.hpp:37-64 visibility rules).
        if self.commit_ts is not None:
            return self.commit_ts
        if self.isolation is IsolationLevel.SNAPSHOT_ISOLATION:
            return self.start_ts
        # READ_COMMITTED / READ_UNCOMMITTED see the latest committed state
        return self.storage.latest_commit_ts()


class VertexAccessor:
    """Transactional view over one vertex. Cheap to construct."""

    __slots__ = ("vertex", "_acc")

    def __init__(self, vertex: Vertex, acc: "Accessor") -> None:
        self.vertex = vertex
        self._acc = acc

    # --- identity -----------------------------------------------------------

    @property
    def gid(self) -> Gid:
        return self.vertex.gid

    def __eq__(self, other):
        # gid equality, not object identity: the disk mode can re-load a
        # fresh object for the same gid; gids are never reused
        return isinstance(other, VertexAccessor) and \
            other.vertex.gid == self.vertex.gid

    def __hash__(self):
        return hash(("v", self.vertex.gid))

    # --- reads --------------------------------------------------------------

    def _state(self, view: View, need_edges: bool = True):
        return self._acc._vertex_state(self.vertex, view, need_edges)

    def is_visible(self, view: View = View.OLD) -> bool:
        st = self._state(view, need_edges=False)
        return st.exists and not st.deleted

    def labels(self, view: View = View.NEW) -> list[int]:
        return sorted(self._state(view, need_edges=False).labels)

    def has_label(self, label_id: int, view: View = View.NEW) -> bool:
        return label_id in self._state(view, need_edges=False).labels

    def properties(self, view: View = View.NEW) -> dict[int, object]:
        return dict(self._state(view, need_edges=False).properties)

    def get_property(self, prop_id: int, view: View = View.NEW):
        value = self._state(view, need_edges=False).properties.get(prop_id)
        mvcc_event("read", txn=self._acc.txn.id, gid=self.vertex.gid,
                   prop=prop_id, value=value)
        return value

    def in_edges(self, view: View = View.NEW, edge_types=None,
                 from_vertex=None) -> list["EdgeAccessor"]:
        if from_vertex is not None:
            entries = self._acc._neighbor_entries(
                self.vertex, "in", from_vertex.vertex.gid, view)
            if entries is not None:
                return self._filter_entries(entries, view, edge_types, None)
        st = self._state(view)
        out = []
        for (etype, other, edge) in st.in_edges:
            if edge_types is not None and etype not in edge_types:
                continue
            if from_vertex is not None and \
                    other.gid != from_vertex.vertex.gid:
                continue
            ea = EdgeAccessor(edge, self._acc)
            if ea.is_visible(view) and self._acc._fg_edge_ok(ea, view):
                out.append(ea)
        return out

    def out_edges(self, view: View = View.NEW, edge_types=None,
                  to_vertex=None) -> list["EdgeAccessor"]:
        if to_vertex is not None:
            entries = self._acc._neighbor_entries(
                self.vertex, "out", to_vertex.vertex.gid, view)
            if entries is not None:
                return self._filter_entries(entries, view, edge_types, None)
        st = self._state(view)
        out = []
        for (etype, other, edge) in st.out_edges:
            if edge_types is not None and etype not in edge_types:
                continue
            if to_vertex is not None and other.gid != to_vertex.vertex.gid:
                continue
            ea = EdgeAccessor(edge, self._acc)
            if ea.is_visible(view) and self._acc._fg_edge_ok(ea, view):
                out.append(ea)
        return out

    def _filter_entries(self, entries, view, edge_types, _unused):
        out = []
        for (etype, _other, edge) in entries:
            if edge_types is not None and etype not in edge_types:
                continue
            ea = EdgeAccessor(edge, self._acc)
            if ea.is_visible(view) and self._acc._fg_edge_ok(ea, view):
                out.append(ea)
        return out

    def in_degree(self, view: View = View.NEW) -> int:
        return len(self.in_edges(view))

    def out_degree(self, view: View = View.NEW) -> int:
        return len(self.out_edges(view))

    # --- writes -------------------------------------------------------------

    def add_label(self, label_id: int) -> bool:
        return self._acc._vertex_add_label(self.vertex, label_id)

    def remove_label(self, label_id: int) -> bool:
        return self._acc._vertex_remove_label(self.vertex, label_id)

    def set_property(self, prop_id: int, value) -> object:
        return self._acc._vertex_set_property(self.vertex, prop_id, value)


class EdgeAccessor:
    __slots__ = ("edge", "_acc")

    def __init__(self, edge: Edge, acc: "Accessor") -> None:
        self.edge = edge
        self._acc = acc

    @property
    def gid(self) -> Gid:
        return self.edge.gid

    @property
    def edge_type(self) -> int:
        return self.edge.edge_type

    def __eq__(self, other):
        return isinstance(other, EdgeAccessor) and \
            other.edge.gid == self.edge.gid

    def __hash__(self):
        return hash(("e", self.edge.gid))

    def from_vertex(self) -> VertexAccessor:
        return VertexAccessor(self.edge.from_vertex, self._acc)

    def to_vertex(self) -> VertexAccessor:
        return VertexAccessor(self.edge.to_vertex, self._acc)

    def _state(self, view: View):
        return self._acc._edge_state(self.edge, view)

    def is_visible(self, view: View = View.OLD) -> bool:
        st = self._state(view)
        return st.exists and not st.deleted

    def properties(self, view: View = View.NEW) -> dict[int, object]:
        return dict(self._state(view).properties)

    def get_property(self, prop_id: int, view: View = View.NEW):
        value = self._state(view).properties.get(prop_id)
        mvcc_event("read", txn=self._acc.txn.id, gid=("e", self.edge.gid),
                   prop=prop_id, value=value)
        return value

    def set_property(self, prop_id: int, value) -> object:
        return self._acc._edge_set_property(self.edge, prop_id, value)


class Accessor:
    """One transaction's handle on the storage (reference: Storage::Accessor).

    Usable as a context manager; __exit__ aborts if not committed.
    """

    fine_grained = None  # optional FgStorageView (auth/fine_grained.py)

    def __init__(self, storage: "InMemoryStorage",
                 isolation: IsolationLevel) -> None:
        from ..observability import trace as mgtrace
        self.storage = storage
        with mgtrace.span("mvcc.begin") as sp:
            self.txn = storage._begin_transaction(isolation)
            if sp:
                sp.set(txn_id=self.txn.id,
                       isolation=str(isolation.value))
        self._finished = False
        self._analytical = storage.config.storage_mode is StorageMode.IN_MEMORY_ANALYTICAL
        # what this reader's MVCC snapshot corresponds to: commits AFTER
        # this accessor began are invisible to it, so version-keyed caches
        # built through it must key on THIS, not the live version
        # (vector-index delta maintenance, NOTES_ROUND2 hole #2).
        # Captured by _begin_transaction under the engine lock, atomically
        # with the snapshot timestamp.
        self.topology_snapshot = self.txn.topology_snapshot

    # --- lifecycle ----------------------------------------------------------

    def __enter__(self) -> "Accessor":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if not self._finished:
            self.abort()

    def commit(self) -> None:
        from ..observability import trace as mgtrace
        if self._finished:
            raise StorageError("transaction already finished")
        try:
            with mgtrace.span("mvcc.commit") as sp:
                commit_ts = self.storage._commit(self.txn)
                if sp:
                    sp.set(txn_id=self.txn.id, commit_ts=commit_ts)
        except Exception:
            # constraint violation etc. → roll back so objects aren't left owned
            self.storage._abort(self.txn)
            self._finished = True
            raise
        self._finished = True
        self._auto_create_indexes()
        # hooks run strictly after the commit is final: a failing hook must
        # never trigger rollback of already-visible data
        for hook in self.storage.on_commit_hooks:
            hook(self.txn, commit_ts)

    def _auto_create_indexes(self) -> None:
        """--storage-automatic-*-index-creation-enabled: index any label /
        edge type this commit touched that has no index yet (reference:
        flags/general.cpp; runs post-commit so the build scans committed
        state)."""
        cfg = self.storage.config
        if cfg.automatic_label_index:
            idx = self.storage.indices.label
            for v in self.txn.touched_vertices.values():
                for lid in v.labels:
                    if not idx.has(lid):
                        self.storage.create_label_index(lid)
        if cfg.automatic_edge_type_index:
            idx = self.storage.indices.edge_type
            for e in self.txn.touched_edges.values():
                if not idx.has(e.edge_type):
                    self.storage.create_edge_type_index(e.edge_type)

    def stage_stream_offset(self, name: str, position) -> None:
        """Stage a stream's source position into THIS transaction: the
        offset becomes a WAL record inside the same commit frame as the
        batch's data, making it the exactly-once boundary (replayed on
        recovery, shipped over replication)."""
        if self._finished:
            raise StorageError("transaction already finished")
        if self.txn.stream_offsets is None:
            self.txn.stream_offsets = {}
        self.txn.stream_offsets[name] = position

    def abort(self) -> None:
        if self._finished:
            return
        self.storage._abort(self.txn)
        self._finished = True

    def periodic_commit(self) -> None:
        """Commit and immediately re-begin on the SAME accessor object
        (reference: InMemoryStorage::Accessor::PeriodicCommit). Every
        live VertexAccessor/EdgeAccessor and in-flight scan iterator
        dereferences this accessor dynamically, so they all migrate to
        the fresh transaction — writes after the boundary land in the
        new transaction instead of stamping deltas onto a finished one."""
        isolation = self.txn.isolation
        self.commit()
        self.txn = self.storage._begin_transaction(isolation)
        self.topology_snapshot = self.txn.topology_snapshot
        self._finished = False

    # --- object creation / deletion -----------------------------------------

    def create_vertex(self, gid: Optional[Gid] = None) -> VertexAccessor:
        storage = self.storage
        with storage._gid_lock:
            shared_write(storage, "_next_vertex_gid")
            if gid is None:
                gid = storage._next_vertex_gid
                storage._next_vertex_gid += 1
            else:
                if gid in storage._vertices:
                    raise StorageError(f"vertex with gid {gid} already exists")
                storage._next_vertex_gid = max(storage._next_vertex_gid, gid + 1)
            # publish under the SAME lock as the uniqueness check: two
            # concurrent explicit-gid creates could both pass the check
            # and the loser's vertex silently vanished (check-then-act,
            # MG007 pattern — mgsan sweep). The undo delta goes on BEFORE
            # publication so a concurrent scanner never sees the vertex
            # as committed.
            vertex = Vertex(gid)
            if not self._analytical:
                push_delta(vertex, self.txn, DeltaAction.DELETE_OBJECT,
                           None)
            storage._vertices[gid] = vertex
        self.txn.touched_vertices[gid] = vertex
        if self._analytical:
            # analytical commits skip the commit-time bump; transactional
            # per-op bumps would only flood the bounded change log (a
            # 30k-op transaction would wrap it) — the commit-time bump
            # logs the txn's full touched set in ONE entry (r19 mgdelta)
            storage._bump_topology({gid})
        return VertexAccessor(vertex, self)

    def delete_vertex(self, va: VertexAccessor, detach: bool = False):
        """Delete a vertex; with detach=True also deletes incident edges.

        Returns (deleted_vertex_accessor, deleted_edge_accessors) or raises.
        """
        if self.fine_grained is not None:
            self.fine_grained.check_vertex_delete(va.vertex.labels)
        vertex = va.vertex
        deleted_edges: list[EdgeAccessor] = []
        with vertex.lock:
            if not self._analytical:
                prepare_for_write(vertex, self.txn)
            if vertex.deleted:
                return None, []
            in_list = list(vertex.in_edges)
            out_list = list(vertex.out_edges)
        if in_list or out_list:
            if not detach:
                raise StorageError(
                    "Vertex has edges and cannot be deleted without DETACH")
            for (etype, other, edge) in out_list:
                ea = EdgeAccessor(edge, self)
                if ea.is_visible(View.NEW):
                    self.delete_edge(ea)
                    deleted_edges.append(ea)
            for (etype, other, edge) in in_list:
                ea = EdgeAccessor(edge, self)
                if ea.is_visible(View.NEW):
                    self.delete_edge(ea)
                    deleted_edges.append(ea)
        with vertex.lock:
            if not self._analytical:
                prepare_for_write(vertex, self.txn)
                push_delta(vertex, self.txn, DeltaAction.RECREATE_OBJECT, None)
            vertex.deleted = True
        self.txn.touched_vertices[vertex.gid] = vertex
        if self._analytical:
            self.storage._bump_topology({vertex.gid})
        return va, deleted_edges

    def create_edge(self, from_va: VertexAccessor, to_va: VertexAccessor,
                    edge_type: int, gid: Optional[Gid] = None) -> EdgeAccessor:
        if self.fine_grained is not None:
            self.fine_grained.check_edge_create_delete(edge_type)
        storage = self.storage
        from_v, to_v = from_va.vertex, to_va.vertex
        # the gid lock is held across validation AND publication: the old
        # check-then-publish split let two explicit-gid creates both pass
        # the uniqueness check and silently drop one edge (check-then-act,
        # MG007 pattern — mgsan sweep). Ordering stays gid_lock ->
        # Vertex.lock everywhere; no path takes the gid lock under a
        # vertex lock.
        with storage._gid_lock:
            shared_write(storage, "_next_edge_gid")
            if gid is None:
                gid = storage._next_edge_gid
                storage._next_edge_gid += 1
            else:
                if gid in storage._edges:
                    raise StorageError(f"edge with gid {gid} already exists")
                storage._next_edge_gid = max(storage._next_edge_gid, gid + 1)
            edge = Edge(gid, edge_type, from_v, to_v)

            # lock both endpoints in gid order to avoid deadlock
            first, second = (from_v, to_v) if from_v.gid <= to_v.gid \
                else (to_v, from_v)
            first.lock.acquire()
            if second is not first:
                second.lock.acquire()
            try:
                if not self._analytical:
                    prepare_for_write(from_v, self.txn)
                    if to_v is not from_v:
                        prepare_for_write(to_v, self.txn)
                if from_v.deleted or to_v.deleted:
                    raise StorageError(
                        "cannot create edge on a deleted vertex")
                out_entry = (edge_type, to_v, edge)
                in_entry = (edge_type, from_v, edge)
                if not self._analytical:
                    push_delta(edge, self.txn, DeltaAction.DELETE_OBJECT,
                               None)
                    push_delta(from_v, self.txn,
                               DeltaAction.REMOVE_OUT_EDGE, out_entry)
                    push_delta(to_v, self.txn, DeltaAction.REMOVE_IN_EDGE,
                               in_entry)
                from_v.out_edges.append(out_entry)
                to_v.in_edges.append(in_entry)
                adj_map_add(from_v, "out", out_entry)
                adj_map_add(to_v, "in", in_entry)
            finally:
                if second is not first:
                    second.lock.release()
                first.lock.release()
            storage._edges[gid] = edge
        storage.indices.edge_type.add(edge)
        self.txn.touched_edges[gid] = edge
        self.txn.touched_vertices[from_v.gid] = from_v
        self.txn.touched_vertices[to_v.gid] = to_v
        if self._analytical:
            storage._bump_topology({from_v.gid, to_v.gid})
        return EdgeAccessor(edge, self)

    def delete_edge(self, ea: EdgeAccessor):
        if self.fine_grained is not None:
            self.fine_grained.check_edge_create_delete(ea.edge.edge_type)
        edge = ea.edge
        from_v, to_v = edge.from_vertex, edge.to_vertex
        with edge.lock:
            if not self._analytical:
                prepare_for_write(edge, self.txn)
            if edge.deleted:
                return None
            if not self._analytical:
                push_delta(edge, self.txn, DeltaAction.RECREATE_OBJECT, None)
            edge.deleted = True
        out_entry = (edge.edge_type, to_v, edge)
        in_entry = (edge.edge_type, from_v, edge)
        with from_v.lock:
            if not self._analytical:
                prepare_for_write(from_v, self.txn)
                push_delta(from_v, self.txn, DeltaAction.ADD_OUT_EDGE, out_entry)
            try:
                from_v.out_edges.remove(out_entry)
            except ValueError:
                pass
            adj_map_remove(from_v, "out", out_entry)
        with to_v.lock:
            if not self._analytical:
                prepare_for_write(to_v, self.txn)
                push_delta(to_v, self.txn, DeltaAction.ADD_IN_EDGE, in_entry)
            try:
                to_v.in_edges.remove(in_entry)
            except ValueError:
                pass
            adj_map_remove(to_v, "in", in_entry)
        self.txn.touched_edges[edge.gid] = edge
        self.txn.touched_vertices[from_v.gid] = from_v
        self.txn.touched_vertices[to_v.gid] = to_v
        if self._analytical:
            self.storage._bump_topology({from_v.gid, to_v.gid})
        return ea

    # --- bulk-write fast lane ----------------------------------------------

    def batch_insert(self, vertices=(), edges=()):
        """Bulk-create vertices and edges with per-batch (not per-row)
        overhead: one gid-counter reservation, one undo delta per object
        (plus one bulk adjacency undo per pre-existing endpoint), deferred
        bulk-merged index maintenance, and a single change-log bump. The
        batch stays one MVCC transaction: invisible to other readers until
        commit, fully undone by abort, and encoded as one BATCH_INSERT
        WAL/replication record at commit.

        vertices: sequence of (label_ids, props) — label_ids an iterable of
          label ids, props a dict[prop_id, value] (ownership transfers).
        edges: sequence of (edge_type_id, from_ref, to_ref, props) — a ref
          is an int index into this call's `vertices`, or a Vertex /
          VertexAccessor for a pre-existing endpoint.

        Returns (new_vertices, new_edges) as raw storage objects.
        """
        import numpy as np
        storage = self.storage
        txn = self.txn
        analytical = self._analytical
        vertices = list(vertices)
        edges = list(edges)
        nv, ne = len(vertices), len(edges)
        if not nv and not ne:
            return [], []
        fg = self.fine_grained
        if fg is not None:
            seen_sets: set = set()
            for labels, _props in vertices:
                t = tuple(labels)
                if t not in seen_sets:
                    seen_sets.add(t)
                    for lid in t:
                        fg.check_label_modify(lid)
                    fg.check_vertex_update(set(t))
            seen_types: set = set()
            for etype, _f, _t, _p in edges:
                if etype not in seen_types:
                    seen_types.add(etype)
                    fg.check_edge_create_delete(etype)

        # (a) vectorized gid allocation: one counter reservation per batch
        with storage._gid_lock:
            shared_write(storage, "_next_vertex_gid")
            v_base = storage._next_vertex_gid
            storage._next_vertex_gid += nv
            e_base = storage._next_edge_gid
            storage._next_edge_gid += ne
        v_gids = np.arange(v_base, v_base + nv, dtype=np.int64).tolist()

        from .delta import Delta
        commit_info = txn.commit_info
        deltas = txn.deltas
        _DELETE = DeltaAction.DELETE_OBJECT

        new_vertices: list[Vertex] = []
        append_vertex = new_vertices.append
        for gid, (labels, props) in zip(v_gids, vertices):
            v = Vertex(gid)
            if labels:
                v.labels = set(labels)
            if props:
                v.properties = props if isinstance(props, dict) \
                    else dict(props)
            if not analytical:
                d = Delta(_DELETE, None, commit_info, None, v)
                v.delta = d
                deltas.append(d)
            append_vertex(v)

        props_on_edges = storage.config.properties_on_edges
        new_edges: list[Edge] = []
        append_edge = new_edges.append
        # pre-existing endpoints: entries grouped per vertex (object-keyed,
        # identity hash) so each gets ONE lock round + ONE bulk undo delta
        # for the whole batch
        pending_in: dict[Vertex, list] = {}
        pending_out: dict[Vertex, list] = {}
        egid = e_base
        for etype, fref, tref, props in edges:
            from_new = type(fref) is int
            to_new = type(tref) is int
            from_v = new_vertices[fref] if from_new else \
                (fref.vertex if type(fref) is VertexAccessor else fref)
            to_v = new_vertices[tref] if to_new else \
                (tref.vertex if type(tref) is VertexAccessor else tref)
            edge = Edge(egid, etype, from_v, to_v)
            egid += 1
            if props:
                if not props_on_edges:
                    raise StorageError("properties on edges are disabled")
                edge.properties = props if isinstance(props, dict) \
                    else dict(props)
            if not analytical:
                d = Delta(_DELETE, None, commit_info, None, edge)
                edge.delta = d
                deltas.append(d)
            out_entry = (etype, to_v, edge)
            in_entry = (etype, from_v, edge)
            if from_new:
                # unpublished: no lock, no adjacency undo needed — the
                # vertex's own DELETE_OBJECT undo covers its whole state
                from_v.out_edges.append(out_entry)
                if from_v.adj_out is not None:
                    adj_map_add(from_v, "out", out_entry)
            else:
                group = pending_out.get(from_v)
                if group is None:
                    group = pending_out[from_v] = []
                group.append(out_entry)
            if to_new:
                to_v.in_edges.append(in_entry)
                if to_v.adj_in is not None:
                    adj_map_add(to_v, "in", in_entry)
            else:
                group = pending_in.get(to_v)
                if group is None:
                    group = pending_in[to_v] = []
                group.append(in_entry)
            append_edge(edge)

        # (e) amortized supernode bookkeeping: one lock round + one bulk
        # undo per pre-existing endpoint per direction, however many edges
        # it gained
        touched_v = txn.touched_vertices
        changed = {v.gid for v in new_vertices}
        changed_add = changed.add
        _IN_BULK = DeltaAction.REMOVE_IN_EDGES_BULK
        _OUT_BULK = DeltaAction.REMOVE_OUT_EDGES_BULK
        for side, bulk_action, pending in (
                ("in", _IN_BULK, pending_in),
                ("out", _OUT_BULK, pending_out)):
            is_in = side == "in"
            for v, entries in pending.items():
                lock = v.lock
                lock.acquire()
                try:
                    if not analytical:
                        prepare_for_write(v, txn)
                    if v.deleted:
                        raise StorageError(
                            "cannot create edge on a deleted vertex")
                    if not analytical:
                        d = Delta(bulk_action, tuple(entries), commit_info,
                                  v.delta, v)
                        v.delta = d
                        deltas.append(d)
                    if is_in:
                        v.in_edges.extend(entries)
                        if v.adj_in is not None:
                            for entry in entries:
                                adj_map_add(v, "in", entry)
                    else:
                        v.out_edges.extend(entries)
                        if v.adj_out is not None:
                            for entry in entries:
                                adj_map_add(v, "out", entry)
                finally:
                    lock.release()
                gid = v.gid
                touched_v[gid] = v
                changed_add(gid)

        # publish
        storage._vertices.update(zip(v_gids, new_vertices))
        storage._edges.update((e.gid, e) for e in new_edges)

        # (c) deferred index maintenance: one sorted bulk-merge per index
        if new_vertices:
            per_label: dict[int, list] = {}
            for v in new_vertices:
                for lid in v.labels:
                    per_label.setdefault(lid, []).append(v)
            for lid, group in per_label.items():
                storage.indices.label.bulk_add(lid, group)
            storage.indices.label_property.bulk_add(new_vertices)
        if new_edges:
            storage.indices.edge_type.bulk_add(new_edges)

        txn.touched_vertices.update((v.gid, v) for v in new_vertices)
        txn.touched_edges.update((e.gid, e) for e in new_edges)
        if not analytical:
            if txn.batches is None:
                txn.batches = []
            txn.batches.append(BatchInsert(new_vertices, new_edges))

        # (d) one change-log record per batch (gids collected while hot
        # in the loops above); transactional batches are covered by the
        # commit-time bump (every gid is in touched_vertices), so only
        # analytical mode needs the immediate record (r19 mgdelta)
        if analytical:
            storage._bump_topology(changed)

        if nv + ne >= 1024:
            # bulk-load pacing: graph objects are long-lived by
            # construction, but CPython's cyclic GC rescans every one of
            # them on each gen-2 collection — at millions of objects the
            # scans ate >50% of ingest wall time (measured r6). Freeze the
            # current heap into the permanent generation; collect_garbage()
            # unfreezes before sweeping so deleted vertex<->edge cycles
            # stay reclaimable.
            import gc as _gc
            _gc.freeze()
        return new_vertices, new_edges

    # --- vertex mutations (called through VertexAccessor) -------------------

    def _vertex_add_label(self, vertex: Vertex, label_id: int) -> bool:
        if self.fine_grained is not None:
            self.fine_grained.check_label_modify(label_id)
        with vertex.lock:
            if not self._analytical:
                prepare_for_write(vertex, self.txn)
            if vertex.deleted:
                raise StorageError("cannot modify a deleted vertex")
            if label_id in vertex.labels:
                return False
            if not self._analytical:
                push_delta(vertex, self.txn, DeltaAction.REMOVE_LABEL, label_id)
            vertex.labels.add(label_id)
        self.storage.indices.label.add(label_id, vertex)
        self.storage.indices.label_property.update_on_change(vertex)
        self.txn.touched_vertices[vertex.gid] = vertex
        if self._analytical:
            # analytical commits skip the commit-time bump; invalidate
            # device/columnar snapshot caches per write instead
            self.storage._bump_topology({vertex.gid})
        return True

    def _vertex_remove_label(self, vertex: Vertex, label_id: int) -> bool:
        if self.fine_grained is not None:
            self.fine_grained.check_label_modify(label_id)
        with vertex.lock:
            if not self._analytical:
                prepare_for_write(vertex, self.txn)
            if vertex.deleted:
                raise StorageError("cannot modify a deleted vertex")
            if label_id not in vertex.labels:
                return False
            if not self._analytical:
                push_delta(vertex, self.txn, DeltaAction.ADD_LABEL, label_id)
            vertex.labels.discard(label_id)
        self.storage.indices.label_property.update_on_change(vertex)
        self.txn.touched_vertices[vertex.gid] = vertex
        if self._analytical:
            self.storage._bump_topology({vertex.gid})
        return True

    def _vertex_set_property(self, vertex: Vertex, prop_id: int, value):
        if self.fine_grained is not None:
            self.fine_grained.check_vertex_update(vertex.labels)
        with vertex.lock:
            if not self._analytical:
                prepare_for_write(vertex, self.txn)
            if vertex.deleted:
                raise StorageError("cannot modify a deleted vertex")
            old = vertex.properties.get(prop_id)
            if not self.storage.config.delta_on_identical_property_update \
                    and old == value and type(old) is type(value) \
                    and value is not None:
                return old      # identical rewrite: no delta, no WAL
            if not self._analytical:
                push_delta(vertex, self.txn, DeltaAction.SET_PROPERTY,
                           (prop_id, old))
            if value is None:
                vertex.properties.pop(prop_id, None)
            else:
                vertex.properties[prop_id] = value
        mvcc_event("write", txn=self.txn.id, gid=vertex.gid, prop=prop_id,
                   value=value)
        self.storage.indices.label_property.update_on_change(vertex)
        self.txn.touched_vertices[vertex.gid] = vertex
        if self._analytical:
            self.storage._bump_topology({vertex.gid})
        return old

    def _edge_set_property(self, edge: Edge, prop_id: int, value):
        if self.fine_grained is not None:
            self.fine_grained.check_edge_update(edge.edge_type)
        if not self.storage.config.properties_on_edges:
            raise StorageError("properties on edges are disabled")
        with edge.lock:
            if not self._analytical:
                prepare_for_write(edge, self.txn)
            if edge.deleted:
                raise StorageError("cannot modify a deleted edge")
            old = edge.properties.get(prop_id)
            if not self._analytical:
                push_delta(edge, self.txn, DeltaAction.SET_PROPERTY,
                           (prop_id, old))
            if value is None:
                edge.properties.pop(prop_id, None)
            else:
                edge.properties[prop_id] = value
        mvcc_event("write", txn=self.txn.id, gid=("e", edge.gid),
                   prop=prop_id, value=value)
        self.txn.touched_edges[edge.gid] = edge
        eps = self.txn.edge_prop_endpoint_gids
        if eps is None:
            eps = self.txn.edge_prop_endpoint_gids = set()
        eps.add(edge.from_vertex.gid)
        eps.add(edge.to_vertex.gid)
        if self._analytical:
            self.storage._bump_topology(
                {edge.from_vertex.gid, edge.to_vertex.gid})
        return old

    # --- reads --------------------------------------------------------------

    def _vertex_state(self, vertex: Vertex, view: View,
                      need_edges: bool = True):
        txn = self.txn
        if (txn.isolation is IsolationLevel.READ_UNCOMMITTED
                or self._analytical):
            from .delta import MaterializedState
            with vertex.lock:
                return MaterializedState(
                    exists=True, deleted=vertex.deleted,
                    labels=set(vertex.labels),
                    properties=dict(vertex.properties),
                    in_edges=list(vertex.in_edges) if need_edges else [],
                    out_edges=list(vertex.out_edges) if need_edges else [])
        return materialize_vertex(vertex, txn, view, need_edges)

    def _neighbor_entries(self, vertex: Vertex, side: str, other_gid: int,
                          view: View):
        """Supernode fast path for bound-endpoint edge lookups: candidate
        adjacency entries between `vertex` and `other_gid`, or None when the
        caller must fall back to the full materialize-and-scan.

        Only valid when the reader's view of the vertex equals its live
        fields (state_is_current): then the live adjacency map is
        authoritative and the O(degree) state copy is skipped. Each
        returned entry's edge still gets the normal per-edge visibility
        check, so an invisible concurrent edge never leaks through."""
        from .mvcc import state_is_current
        live = vertex.in_edges if side == "in" else vertex.out_edges
        if len(live) < ADJ_INDEX_THRESHOLD:
            return None
        with vertex.lock:
            if not (self._analytical
                    or self.txn.isolation is IsolationLevel.READ_UNCOMMITTED
                    or state_is_current(vertex, self.txn, view)):
                return None
            adj = vertex.adj_in if side == "in" else vertex.adj_out
            if adj is None:
                adj = adj_map_build(vertex, side)
            return list(adj.get(other_gid, ()))

    def _edge_state(self, edge: Edge, view: View):
        txn = self.txn
        if (txn.isolation is IsolationLevel.READ_UNCOMMITTED
                or self._analytical):
            from .delta import MaterializedState
            with edge.lock:
                return MaterializedState(
                    exists=True, deleted=edge.deleted,
                    properties=dict(edge.properties))
        return materialize_edge(edge, txn, view)

    def find_vertex(self, gid: Gid, view: View = View.NEW
                    ) -> Optional[VertexAccessor]:
        vertex = self.storage._vertices.get(gid)
        if vertex is None:
            return None
        va = VertexAccessor(vertex, self)
        if not va.is_visible(view):
            return None
        return va if self._fg_vertex_ok(va, view) else None

    def find_edge(self, gid: Gid, view: View = View.NEW) -> Optional[EdgeAccessor]:
        edge = self.storage._edges.get(gid)
        if edge is None:
            return None
        ea = EdgeAccessor(edge, self)
        if not ea.is_visible(view):
            return None
        return ea if self._fg_edge_ok(ea, view) else None

    def _fg_vertex_ok(self, va: "VertexAccessor", view: View) -> bool:
        fg = self.fine_grained
        return fg is None or fg.can_read_vertex(
            va._state(view, need_edges=False).labels)

    def _fg_edge_ok(self, ea: "EdgeAccessor", view: View) -> bool:
        fg = self.fine_grained
        if fg is None:
            return True
        if not fg.can_read_edge(ea.edge.edge_type):
            return False
        return fg.can_read_vertex(
            ea.from_vertex()._state(view, need_edges=False).labels) and \
            fg.can_read_vertex(
                ea.to_vertex()._state(view, need_edges=False).labels)

    def vertices(self, view: View = View.OLD) -> Iterator[VertexAccessor]:
        for vertex in list(self.storage._vertices.values()):
            va = VertexAccessor(vertex, self)
            if va.is_visible(view) and self._fg_vertex_ok(va, view):
                yield va

    def edges(self, view: View = View.OLD) -> Iterator[EdgeAccessor]:
        for edge in list(self.storage._edges.values()):
            ea = EdgeAccessor(edge, self)
            if ea.is_visible(view) and self._fg_edge_ok(ea, view):
                yield ea

    def vertices_by_label(self, label_id: int,
                          view: View = View.OLD) -> Iterator[VertexAccessor]:
        candidates = self.storage.indices.label.candidates(label_id)
        if candidates is None:
            # no index: full scan filter (planner avoids this when possible)
            for va in self.vertices(view):
                if va.has_label(label_id, view):
                    yield va
            return
        fg = self.fine_grained
        served = 0
        try:
            for vertex in candidates:
                st = self._vertex_state(vertex, view, need_edges=False)
                if not st.exists or st.deleted or label_id not in st.labels:
                    continue
                if fg is not None and not fg.can_read_vertex(st.labels):
                    continue
                served += 1
                yield VertexAccessor(vertex, self)
        finally:
            # mgstat: one usage record per index-served scan (flushed on
            # abandon too — LIMIT still accounts what it consumed)
            self.storage.indices.label.note_usage(label_id, served)

    def vertices_by_label_property_value(self, label_id: int,
                                         prop_ids: tuple[int, ...], values,
                                         view: View = View.OLD):
        candidates = self.storage.indices.label_property.candidates_equal(
            label_id, prop_ids, values)
        if candidates is None:
            for va in self.vertices_by_label(label_id, view):
                props = va.properties(view)
                if all(props.get(p) == v and props.get(p) is not None
                       for p, v in zip(prop_ids, values)):
                    yield va
            return
        fg = self.fine_grained
        served = 0
        try:
            for vertex in candidates:
                # one props-only materialization covers visibility, label,
                # auth, and value revalidation (was four walks per candidate)
                st = self._vertex_state(vertex, view, need_edges=False)
                if not st.exists or st.deleted or label_id not in st.labels:
                    continue
                if fg is not None and not fg.can_read_vertex(st.labels):
                    continue
                props = st.properties
                if all(props.get(p) == v for p, v in zip(prop_ids, values)):
                    served += 1
                    yield VertexAccessor(vertex, self)
        finally:
            self.storage.indices.label_property.note_usage(
                label_id, prop_ids, served)

    def vertices_by_label_property_range(self, label_id: int,
                                         prop_ids: tuple[int, ...],
                                         lower=None, upper=None,
                                         lower_inclusive=True,
                                         upper_inclusive=True,
                                         view: View = View.OLD):
        from .ordering import order_key
        candidates = self.storage.indices.label_property.candidates_range(
            label_id, prop_ids, lower, upper, lower_inclusive, upper_inclusive)
        index_served = candidates is not None
        if candidates is None:
            candidates = []
            for va in self.vertices_by_label(label_id, view):
                candidates.append(va.vertex)
        seen: set[int] = set()  # add-only index can hold several keys per gid
        served = 0
        try:
            for vertex in candidates:
                if vertex.gid in seen:
                    continue
                seen.add(vertex.gid)
                va = VertexAccessor(vertex, self)
                if not va.is_visible(view) or not va.has_label(label_id,
                                                               view):
                    continue
                if not self._fg_vertex_ok(va, view):
                    continue
                val = va.get_property(prop_ids[0], view)
                if val is None:
                    continue
                k = order_key(val)
                if lower is not None:
                    lk = order_key(lower)
                    if k < lk or (k == lk and not lower_inclusive):
                        continue
                if upper is not None:
                    uk = order_key(upper)
                    if k > uk or (k == uk and not upper_inclusive):
                        continue
                served += 1
                yield va
        finally:
            if index_served:
                self.storage.indices.label_property.note_usage(
                    label_id, prop_ids, served)

    def edges_by_type(self, edge_type_id: int,
                      view: View = View.OLD) -> Iterator[EdgeAccessor]:
        candidates = self.storage.indices.edge_type.candidates(edge_type_id)
        if candidates is None:
            for ea in self.edges(view):
                if ea.edge_type == edge_type_id:
                    yield ea
            return
        served = 0
        try:
            for edge in candidates:
                ea = EdgeAccessor(edge, self)
                if ea.is_visible(view) and self._fg_edge_ok(ea, view):
                    served += 1
                    yield ea
        finally:
            self.storage.indices.edge_type.note_usage(edge_type_id, served)

    # --- counts for the planner ---------------------------------------------

    def approx_vertex_count(self, label_id=None, prop_ids=None) -> int:
        if label_id is None:
            return len(self.storage._vertices)
        if prop_ids is None:
            if self.storage.indices.label.has(label_id):
                return self.storage.indices.label.approx_count(label_id)
            return len(self.storage._vertices)
        return self.storage.indices.label_property.approx_count(label_id, prop_ids)

    def approx_edge_count(self) -> int:
        return len(self.storage._edges)


class InMemoryStorage:
    """The storage engine. Owns objects, indexes, constraints, mappers."""

    # the planner's bulk-write fast lane (query/plan/bulk.py) only routes
    # through batch_insert() on engines that declare support — subclasses
    # with their own persistence model (disk storage) opt out
    supports_batch_insert = True

    def __init__(self, config: Optional[StorageConfig] = None) -> None:
        self.config = config or StorageConfig()
        self.label_mapper = NameIdMapper()
        self.property_mapper = NameIdMapper()
        self.edge_type_mapper = NameIdMapper()
        self.indices = Indices()
        self.constraints = Constraints()
        self.namer = _Namer(self)

        self._vertices: dict[Gid, Vertex] = {}
        self._edges: dict[Gid, Edge] = {}
        self._next_vertex_gid = 0
        self._next_edge_gid = 0
        self._gid_lock = tracked_lock("Storage._gid_lock")

        self._timestamp = 1  # commit timestamps; 0 reserved
        self._next_txn_id = TRANSACTION_ID_START + 1
        self._engine_lock = tracked_lock("Storage._engine_lock")
        self._active_txns: dict[int, Transaction] = {}
        # frame shipping order: sequence assigned under the engine lock,
        # consumers invoked strictly in sequence order (replicas must see
        # commits in commit-timestamp order)
        self._ship_cond = threading.Condition()
        self._next_ship_seq = 0
        self._frame_seq = 0

        self._topology_version = 0
        # bounded (version, frozenset(gids)|None) log backing
        # changes_between(); 1024 entries cover bursts of small commits
        from collections import deque
        self._change_log = deque(maxlen=1024)
        # monotone low-water mark: the version of the OLDEST entry the
        # log still holds. deque(maxlen=) drops entries silently, so wrap
        # detection must not depend on what happens to be retained —
        # changes_between answers (v_from, v_to] iff v_from + 1 >=
        # _oldest_logged_version, and returns a typed ChangeLogUnknowable
        # otherwise instead of a silently-partial delta.
        self._oldest_logged_version = 1
        self._change_log_lock = tracked_lock("Storage._change_log_lock")
        # mgsan shared-state declarations (MG006/MG007 + race detector):
        # gid counters under _gid_lock, engine bookkeeping under
        # _engine_lock, change log under _change_log_lock. The object
        # maps (_vertices/_edges) and per-object delta chains are
        # deliberately NOT declared: they synchronize through per-object
        # plain locks + GIL-atomic dict publication, and their
        # correctness is witnessed end-to-end by the MVCC isolation
        # checker instead of field annotations.
        shared_field(self, "_next_vertex_gid", "_next_edge_gid",
                     "_timestamp", "_next_txn_id", "_active_txns",
                     "_topology_version", "_change_log",
                     "_oldest_logged_version")
        # durability wiring: receives (frame_bytes, commit_ts) under the
        # engine lock, BEFORE the visibility flip (write-ahead ordering)
        self.wal_sink: Optional[Callable] = None
        # 2PC vote stage: run under the engine lock BEFORE the WAL write and
        # visibility flip; raising aborts the commit (STRICT_SYNC replicas)
        self.pre_commit_hooks: list[Callable] = []
        # replication etc.: receive the same (frame_bytes, commit_ts) after
        # the commit is visible (outside the engine lock)
        self.frame_consumers: list[Callable] = []
        self.on_commit_hooks: list[Callable] = []  # triggers (txn, commit_ts)
        # called with commit_ts when a commit fails AFTER the 2PC vote
        # succeeded (e.g. wal_sink raised) — lets replication send
        # finalize('abort') so replicas don't orphan prepared frames
        self.commit_abort_hooks: list[Callable] = []
        # stream name -> last durably-committed source position; written
        # by committing stream transactions, restored by recovery
        # (snapshot section + OP_STREAM_OFFSET replay) and by replication
        self.stream_offsets: dict[str, object] = {}

    # --- transactions -------------------------------------------------------

    def access(self, isolation: Optional[IsolationLevel] = None) -> Accessor:
        if getattr(self, "suspended", False):
            # a session that kept its USE DATABASE reference across a
            # SUSPEND must fail loudly, not write into an orphaned store
            raise StorageError(
                "this database is suspended; RESUME it first")
        return Accessor(self, isolation or self.config.isolation_level)

    def _begin_transaction(self, isolation: IsolationLevel) -> Transaction:
        with self._engine_lock:
            # gate + registration must be ATOMIC: a check outside this
            # lock could let a transaction slip past the suspend drain.
            # _suspend_internal lets the suspend flow's own snapshot
            # reader through after the drain completed.
            if getattr(self, "suspended", False) and                     not getattr(self, "_suspend_internal", False):
                raise StorageError(
                    "this database is suspended; RESUME it first")
            shared_write(self, "_next_txn_id")
            txn_id = self._next_txn_id
            self._next_txn_id += 1
            start_ts = self._timestamp
            txn = Transaction(txn_id, start_ts, isolation, self)
            self._active_txns[txn_id] = txn
            mvcc_event("begin", txn=txn_id, start_ts=start_ts)
            # captured under the SAME lock as the commit-side visibility
            # flip + bump, so an accessor's MVCC snapshot and its
            # topology snapshot can never disagree (version-keyed caches
            # would otherwise cache wrong data under this version)
            txn.topology_snapshot = self._topology_version
            return txn

    def latest_commit_ts(self) -> int:
        # single GIL-atomic int read; a stale value only makes a replica
        # lag gauge or catch-up decision conservative, never wrong
        return self._timestamp  # mglint: disable=MG006 — lock-free monotonic read is the contract

    def _check_db_memory_limit(self, txn: "Transaction") -> None:
        """Tenant-profile `storage_limit` (per-DB arena cap, reference:
        memory/db_arena.cpp): refuse GROWING commits once the database's
        estimated footprint exceeds it. Transactions that create no
        objects (deletes, label/property updates) always pass — an
        over-limit database must stay recoverable in-band via DETACH
        DELETE. The O(sample) estimate is recomputed at most every 5s
        and immediately when the limit value changes; writes inside
        that staleness window are admitted (sampling estimator, not an
        allocator hook — documented deviation)."""
        fn = getattr(self, "memory_limit_fn", None)
        if fn is None:
            return
        limit = fn()
        if not limit:
            return
        # growing = the txn created vertices/edges (their undo action
        # is DELETE_OBJECT); delete-only / update-only txns pass
        if not any(d.action is DeltaAction.DELETE_OBJECT
                   for d in txn.deltas):
            return
        import time as _time
        now = _time.monotonic()
        cached = getattr(self, "_arena_estimate", None)
        if cached is None or now - cached[0] > 5.0 or cached[2] != limit:
            cached = (now, self.memory_usage_estimate(), limit)
            self._arena_estimate = cached
        if cached[1] > limit:
            raise StorageError(
                f"database memory limit exceeded: ~{cached[1]:,} bytes "
                f"used, storage_limit {limit:,} (tenant profile)")

    def _commit(self, txn: Transaction) -> int:
        storage_mode = self.config.storage_mode
        if storage_mode is StorageMode.IN_MEMORY_ANALYTICAL or \
                not (txn.deltas or txn.stream_offsets):
            with self._engine_lock:
                self._active_txns.pop(txn.id, None)
                mvcc_event("commit", txn=txn.id, commit_ts=None, ro=True)
                # commit_ts stays None: a no-delta txn has no own writes to
                # expose, and advancing would leak later commits into a
                # read-only SI transaction's retained accessors
                return self._timestamp
        self._check_db_memory_limit(txn)

        # existence + type + unique constraints all walk the touched set —
        # skipped (and never materialized) when none are defined: bulk
        # commits touch hundreds of thousands of vertices
        constrained = bool(self.constraints.existence._constraints
                           or self.constraints.type._constraints
                           or self.constraints.unique._maps)
        touched = list(txn.touched_vertices.values()) if constrained else ()
        if self.constraints.existence._constraints or \
                self.constraints.type._constraints:
            for v in touched:
                if not v.deleted:
                    self.constraints.existence.validate_vertex(
                        v.labels, v.properties, self.namer)
                    self.constraints.type.validate_vertex(
                        v.labels, v.properties, self.namer)

        frame = None
        ship_seq = None
        with self._engine_lock:
            registrations = self.constraints.unique.validate_commit(
                touched, self.namer)
            shared_write(self, "_timestamp")
            self._timestamp += 1
            commit_ts = self._timestamp
            if self.wal_sink is not None or self.frame_consumers \
                    or self.pre_commit_hooks:
                # encode ONCE under the lock: object fields hold exactly this
                # transaction's final state here (no later writer can have
                # touched them yet — they'd need the lock to commit)
                from .durability.wal import encode_txn_ops
                frame = encode_txn_ops(self, txn, commit_ts)
                for hook in self.pre_commit_hooks:
                    # 2PC vote: a raise here aborts the commit before any
                    # durability or visibility effect
                    hook(frame, commit_ts)
                if self.wal_sink is not None:
                    try:
                        self.wal_sink(frame, commit_ts)
                    except BaseException:
                        # the vote already succeeded: tell prepared replicas
                        # to drop the pending frame, or it is orphaned forever
                        for hook in self.commit_abort_hooks:
                            try:
                                hook(commit_ts)
                            except Exception:
                                log.exception(
                                    "commit abort hook failed for ts %d",
                                    commit_ts)
                        raise
                if self.frame_consumers:
                    ship_seq = self._frame_seq
                    self._frame_seq += 1
            # visibility flip: all the txn's deltas share this CommitInfo
            txn.commit_info.timestamp = commit_ts
            txn.commit_ts = commit_ts
            self.constraints.unique.apply_registrations(registrations)
            self._active_txns.pop(txn.id, None)
            # committed state changed → device snapshot caches must
            # re-export. INSIDE the engine lock: the bump must be atomic
            # with the visibility flip relative to _begin_transaction's
            # (start_ts, topology_snapshot) capture, or a reader could
            # key a cache entry at a version whose data it cannot see
            # edge-property commits must invalidate both endpoints too: the
            # delta-refresh path diffs edges of CHANGED nodes (r5 review).
            # Every OTHER edge-touching path already put its endpoints in
            # touched_vertices, so only _edge_set_property's endpoint set
            # needs unioning — not a walk over every touched edge (r6).
            changed = set(txn.touched_vertices)
            if txn.edge_prop_endpoint_gids:
                changed |= txn.edge_prop_endpoint_gids
            self._bump_topology(changed)
            if txn.stream_offsets:
                # the offsets are durable (WAL-framed above) — publish
                # them atomically with the commit's visibility flip
                self.stream_offsets.update(txn.stream_offsets)
            mvcc_event("commit", txn=txn.id, commit_ts=commit_ts)
        if ship_seq is not None:
            # strict shipping order across concurrent committers
            with self._ship_cond:
                while self._next_ship_seq != ship_seq:
                    self._ship_cond.wait()
            try:
                for consumer in self.frame_consumers:
                    consumer(frame, commit_ts)
            finally:
                with self._ship_cond:
                    self._next_ship_seq = ship_seq + 1
                    self._ship_cond.notify_all()
        if txn.batches:
            self._retire_batch_deltas(txn, commit_ts)
        if self.config.gc_aggressive:
            # eager delta reclamation after every commit
            # (reference: --storage-gc-aggressive)
            self.collect_garbage()
        return commit_ts

    def _retire_batch_deltas(self, txn: Transaction, commit_ts: int) -> None:
        """Eagerly sever the undo deltas of a committed bulk insert when no
        active transaction's snapshot predates the commit — the same rule
        GC's truncate applies, hit at the moment it is cheapest. A bulk
        load otherwise accumulates one delta per inserted object until the
        next GC cycle (millions of objects whose refcount cycles through
        obj.delta ↔ delta.obj), which measurably poisons cache locality at
        the 5M-edge scale."""
        if self.oldest_active_start_ts() <= commit_ts:
            return     # a concurrent reader may still need the undos
        ci = txn.commit_info
        for batch in txn.batches:
            for obj in batch.vertices:
                d = obj.delta
                if d is not None and d.commit_info is ci and d.next is None:
                    with obj.lock:
                        if obj.delta is d and d.next is None:
                            obj.delta = None
            for obj in batch.edges:
                d = obj.delta
                if d is not None and d.commit_info is ci and d.next is None:
                    with obj.lock:
                        if obj.delta is d and d.next is None:
                            obj.delta = None

    def _abort(self, txn: Transaction) -> None:
        # undo in reverse; our deltas are contiguous at each object's head
        mvcc_event("abort", txn=txn.id)
        from .delta import DeltaAction as A
        for delta in reversed(txn.deltas):
            obj = delta.obj
            with obj.lock:
                a = delta.action
                if a is A.DELETE_OBJECT:
                    obj.deleted = True  # created in this txn → now dead, GC removes
                elif a is A.RECREATE_OBJECT:
                    obj.deleted = False
                elif a is A.ADD_LABEL:
                    obj.labels.add(delta.payload)
                elif a is A.REMOVE_LABEL:
                    obj.labels.discard(delta.payload)
                elif a is A.SET_PROPERTY:
                    pid, prev = delta.payload
                    if prev is None:
                        obj.properties.pop(pid, None)
                    else:
                        obj.properties[pid] = prev
                elif a is A.ADD_IN_EDGE:
                    obj.in_edges.append(delta.payload)
                elif a is A.REMOVE_IN_EDGE:
                    try:
                        obj.in_edges.remove(delta.payload)
                    except ValueError:
                        pass
                elif a is A.ADD_OUT_EDGE:
                    obj.out_edges.append(delta.payload)
                elif a is A.REMOVE_OUT_EDGE:
                    try:
                        obj.out_edges.remove(delta.payload)
                    except ValueError:
                        pass
                elif a is A.REMOVE_IN_EDGES_BULK:
                    drop = set(delta.payload)
                    obj.in_edges = [e for e in obj.in_edges if e not in drop]
                elif a is A.REMOVE_OUT_EDGES_BULK:
                    drop = set(delta.payload)
                    obj.out_edges = [e for e in obj.out_edges
                                     if e not in drop]
                assert obj.delta is delta, "abort: delta chain corrupted"
                obj.delta = delta.next
        for v in txn.touched_vertices.values():
            # the undo loop rewrote adjacency lists directly; drop any lazy
            # adjacency maps so they rebuild from the restored lists
            v.adj_in = None
            v.adj_out = None
            self.indices.label_property.update_on_change(v)
        with self._engine_lock:
            self._active_txns.pop(txn.id, None)
        changed = set(txn.touched_vertices)
        if txn.edge_prop_endpoint_gids:
            changed |= txn.edge_prop_endpoint_gids
        self._bump_topology(changed)

    # --- GC -----------------------------------------------------------------

    def oldest_active_start_ts(self) -> int:
        with self._engine_lock:
            if not self._active_txns:
                return self._timestamp + 1
            return min(t.start_ts for t in self._active_txns.values())

    def collect_garbage(self) -> dict:
        """Truncate delta chains invisible to every active txn; drop dead objects.

        Reference analog: InMemoryStorage::CollectGarbage
        (inmemory/storage.cpp:573) + skip-list GC.
        """
        oldest = self.oldest_active_start_ts()
        stats = {"deltas_freed": 0, "vertices_freed": 0, "edges_freed": 0}
        # bulk ingest freezes the heap (batch_insert) so cyclic GC stops
        # rescanning live graph objects; thaw here so the vertex<->edge
        # reference cycles of objects THIS sweep drops become collectable
        import gc as _gc
        _gc.unfreeze()

        def truncate(obj) -> None:
            with obj.lock:
                delta = obj.delta
                prev = None
                while delta is not None:
                    ts = delta.commit_info.timestamp
                    if ts < TRANSACTION_ID_START and ts < oldest:
                        # this and everything older is invisible to all readers
                        n = 0
                        d = delta
                        while d is not None:
                            n += 1
                            d = d.next
                        stats["deltas_freed"] += n
                        if prev is None:
                            obj.delta = None
                        else:
                            prev.next = None
                        return
                    prev = delta
                    delta = delta.next

        dead_vertices = []
        for gid, v in list(self._vertices.items()):
            truncate(v)
            if v.deleted and v.delta is None:
                dead_vertices.append((gid, v))
        dead_edges = []
        for gid, e in list(self._edges.items()):
            truncate(e)
            if e.deleted and e.delta is None:
                dead_edges.append((gid, e))

        for gid, v in dead_vertices:
            for label_id in list(v.labels):
                self.indices.label.remove_entry(label_id, v)
            self.indices.label_property.remove_entry(v)
            self._vertices.pop(gid, None)
            stats["vertices_freed"] += 1
        for gid, e in dead_edges:
            self.indices.edge_type.remove_entry(e)
            self._edges.pop(gid, None)
            stats["edges_freed"] += 1
        stats["index_entries_swept"] = (self.indices.label.sweep()
                                        + self.indices.label_property.sweep())
        return stats

    # --- schema operations (run outside transactions, like the reference's
    #     unique-accessor index/constraint DDL) ------------------------------

    def create_label_index(self, label_id: int,
                           background: bool = False):
        """background=True returns immediately with the index populating
        on a worker thread (reference: async_indexer.cpp); queries during
        the build fall back to full scans — correct, just unindexed —
        until the returned ready event fires."""
        if background:
            # materialized lazily AFTER the bucket registers (concurrent
            # writers' add() must have a bucket to land in), as a list
            # (the live dict view would race commits)
            return self.indices.label.create_in_background(
                label_id, lambda: list(self._vertices.values()))
        self.indices.label.create(label_id, self._vertices.values())
        return None

    def create_label_property_index(self, label_id: int,
                                    prop_ids: tuple[int, ...]) -> None:
        self.indices.label_property.create(label_id, prop_ids,
                                           self._vertices.values())

    def create_edge_type_index(self, edge_type_id: int) -> None:
        self.indices.edge_type.create(edge_type_id, self._edges.values())

    def create_existence_constraint(self, label_id: int, prop_id: int) -> None:
        self.constraints.existence.create(label_id, prop_id,
                                          self._vertices.values(), self.namer)

    def create_unique_constraint(self, label_id: int,
                                 prop_ids: tuple[int, ...]) -> None:
        self.constraints.unique.create(label_id, prop_ids,
                                       self._vertices.values(), self.namer)

    def create_type_constraint(self, label_id: int, prop_id: int,
                               type_name: str) -> None:
        self.constraints.type.create(label_id, prop_id, type_name,
                                     self._vertices.values(), self.namer)

    # --- TPU snapshot cache signal ------------------------------------------

    def _bump_topology(self, changed_gids=None) -> None:
        """Bump the cache-invalidation version. changed_gids: vertex gids
        whose visible state may differ across the bump (None = unknown —
        consumers must fully rebuild). The bounded change log lets
        version-keyed caches (vector index) refresh O(delta) instead of
        O(n): every mutation path funnels here, INCLUDING replica WAL
        apply and recovery, so deltas are never silently missed
        (NOTES_ROUND2 hole #1)."""
        with self._change_log_lock:
            shared_write(self, "_change_log")
            self._topology_version += 1
            if len(self._change_log) == self._change_log.maxlen:
                # the append below silently drops the oldest entry —
                # advance the monotone low-water mark FIRST so wrap
                # detection never depends on the retained entries
                shared_write(self, "_oldest_logged_version")
                self._oldest_logged_version = self._change_log[0][0] + 1
            self._change_log.append(
                (self._topology_version,
                 frozenset(changed_gids) if changed_gids is not None
                 else None))

    @property
    def topology_version(self) -> int:
        # same contract as latest_commit_ts: monotonic int, stale reads
        # only cause an extra cache refresh
        return self._topology_version  # mglint: disable=MG006 — lock-free monotonic read is the contract

    @property
    def oldest_logged_version(self) -> int:
        """Monotone low-water mark of the bounded change log: the oldest
        version changes_between can still reach back PAST (a query with
        ``v_from + 1 < oldest_logged_version`` is unknowable)."""
        return self._oldest_logged_version  # mglint: disable=MG006 — lock-free monotonic read is the contract

    def changes_between(self, v_from: int, v_to: int):
        """Union of vertex gids changed in versions (v_from, v_to], or a
        falsy :class:`ChangeLogUnknowable` when the log cannot answer
        (the deque wrapped past v_from, or a bump in the range didn't
        record its gids). Consumers must handle the unknowable verdict
        explicitly and fall back to a full rebuild."""
        if v_from == v_to:
            return frozenset()
        with self._change_log_lock:
            shared_read(self, "_change_log")
            entries = list(self._change_log)
            shared_read(self, "_oldest_logged_version")
            oldest = self._oldest_logged_version
        if v_from + 1 < oldest or not entries:
            # log no longer reaches back to v_from (or never logged the
            # range at all) — detected via the monotone low-water mark,
            # not the retained entries, so a wrapped deque can never
            # produce a silently-partial delta
            return ChangeLogUnknowable("log_wrapped", oldest)
        out: set = set()
        for version, gids in entries:
            if version <= v_from or version > v_to:
                continue
            if gids is None:
                return ChangeLogUnknowable("untracked_bump", oldest)
            out |= gids
        return frozenset(out)

    # --- info ---------------------------------------------------------------

    def memory_usage_estimate(self) -> int:
        """Approximate live bytes held by THIS database's graph objects.

        Behavioral counterpart of the reference's per-DB arena
        accounting (memory/db_arena.cpp:204-283 — jemalloc arenas per
        database); CPython has no per-object arena hooks, so this
        samples up to 512 vertices/edges, deep-sizes them
        (object + labels + property keys/values + adjacency tuples),
        and scales by the population. O(sample), computed on demand."""
        import sys
        from itertools import islice

        def deep(obj) -> int:
            n = sys.getsizeof(obj)
            if isinstance(obj, dict):
                n += sum(deep(k) + deep(v) for k, v in obj.items())
            elif isinstance(obj, (list, tuple, set, frozenset)):
                n += sum(deep(x) for x in obj)
            return n

        def sample_total(pop: dict, size_fn) -> int:
            # snapshot the values list first: concurrent commits/GC
            # mutate these dicts (same defense as the GC sweep)
            values = list(pop.values())
            count = len(values)
            if count == 0:
                return 0
            sample = list(islice(values, 512))
            return int(sum(size_fn(o) for o in sample)
                       / len(sample) * count)

        v_bytes = sample_total(self._vertices, lambda v: (
            sys.getsizeof(v) + deep(v.labels) + deep(v.properties)
            + sys.getsizeof(v.in_edges) + sys.getsizeof(v.out_edges)
            + 72 * (len(v.in_edges) + len(v.out_edges))))
        e_bytes = sample_total(self._edges, lambda e: (
            sys.getsizeof(e) + deep(e.properties)))
        return v_bytes + e_bytes

    def info(self) -> dict:
        from ..utils.memory_tracker import GLOBAL
        import resource
        rss_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return {
            "vertex_count": len(self._vertices),
            "edge_count": len(self._edges),
            "average_degree": (2 * len(self._edges) / len(self._vertices)
                               if self._vertices else 0.0),
            "storage_mode": self.config.storage_mode.value,
            "isolation_level": self.config.isolation_level.value,
            # tracked query-materialization memory + process peak RSS
            # (reference: utils/memory_tracker.cpp counters in storage info)
            "memory_tracked": GLOBAL.current,
            "peak_memory_tracked": GLOBAL.peak,
            "peak_memory_res": rss_kb * 1024,
            "memory_limit": GLOBAL.limit,
            # per-DB arena estimate (reference: memory/db_arena.cpp)
            "memory_usage_db_estimate": self.memory_usage_estimate(),
        }
