"""Total ordering over heterogeneous property values.

Cypher ORDER BY and range index scans need a total order across mixed types.
The order follows the reference's TypedValue comparison / openCypher
orderability: by type class first (null sorts last ascending), then within
type. Used by both the label+property index (range scans) and the query
executor's OrderBy.
"""

from __future__ import annotations

import math

from ..utils.point import Point
from ..utils.temporal import (Date, Duration, LocalDateTime, LocalTime,
                              ZonedDateTime)

# type-class ranks; numerics share a rank so 1 < 1.5 < 2 interleave
_RANK_MAP = 0
_RANK_NODE = 1
_RANK_EDGE = 2
_RANK_LIST = 3
_RANK_PATH = 4
_RANK_STRING = 5
_RANK_BOOL = 6
_RANK_NUMBER = 7
_RANK_DATE = 8
_RANK_LOCAL_TIME = 9
_RANK_LOCAL_DATETIME = 10
_RANK_ZONED_DATETIME = 11
_RANK_DURATION = 12
_RANK_POINT = 13
_RANK_BYTES = 14
_RANK_ENUM = 15
_RANK_NULL = 16  # null sorts last in ascending order (openCypher)


def order_key(v):
    """Map a value to a tuple that sorts per openCypher orderability."""
    if v is None:
        return (_RANK_NULL,)
    if isinstance(v, bool):  # bool before int check (bool subclasses int)
        return (_RANK_BOOL, v)
    if isinstance(v, int):
        return (_RANK_NUMBER, v)
    if isinstance(v, float):
        if math.isnan(v):
            return (_RANK_NUMBER, math.inf, 1)  # NaN sorts after +inf
        return (_RANK_NUMBER, v)
    if isinstance(v, str):
        return (_RANK_STRING, v)
    if isinstance(v, (list, tuple)):
        return (_RANK_LIST, tuple(order_key(x) for x in v))
    if isinstance(v, dict):
        return (_RANK_MAP,
                tuple(sorted((k, order_key(val)) for k, val in v.items())))
    if isinstance(v, Date):
        return (_RANK_DATE, v.d.toordinal())
    if isinstance(v, LocalTime):
        return (_RANK_LOCAL_TIME, v._micros())
    if isinstance(v, LocalDateTime):
        return (_RANK_LOCAL_DATETIME, v.timestamp_micros())
    if isinstance(v, ZonedDateTime):
        return (_RANK_ZONED_DATETIME, v.timestamp_micros())
    if isinstance(v, Duration):
        return (_RANK_DURATION, v.micros)
    if isinstance(v, Point):
        return (_RANK_POINT, v.crs.value, v.x, v.y, v.z if v.z is not None else 0.0)
    if isinstance(v, bytes):
        return (_RANK_BYTES, v)
    from .enums import EnumValue
    if isinstance(v, EnumValue):
        return (_RANK_ENUM, v.enum_name, v.position)
    # graph objects (VertexAccessor/EdgeAccessor/Path) order by identity ids
    gid = getattr(v, "gid", None)
    if gid is not None:
        return (_RANK_NODE, gid)
    return (_RANK_PATH, id(v))
