"""Shared storage enums and id types.

Capability map: View/IsolationLevel/StorageMode mirror the reference's
storage/v2/{view.hpp,isolation_level.hpp,storage_mode.hpp} semantics.
"""

from __future__ import annotations

import enum

# Transaction ids live far above commit timestamps so a single integer field
# can say "uncommitted, owned by txn X" vs "committed at T". Same trick as the
# reference's kTransactionInitialId (storage/v2/transaction.hpp).
TRANSACTION_ID_START = 1 << 62

Gid = int  # global ids are dense non-negative ints, assigned per object kind


class View(enum.Enum):
    """Which state a reader wants within a transaction."""
    OLD = 0   # state at transaction start (ignores own uncommitted changes)
    NEW = 1   # state including own uncommitted changes


class IsolationLevel(enum.Enum):
    SNAPSHOT_ISOLATION = "SNAPSHOT_ISOLATION"
    READ_COMMITTED = "READ_COMMITTED"
    READ_UNCOMMITTED = "READ_UNCOMMITTED"


class StorageMode(enum.Enum):
    IN_MEMORY_TRANSACTIONAL = "IN_MEMORY_TRANSACTIONAL"
    IN_MEMORY_ANALYTICAL = "IN_MEMORY_ANALYTICAL"
    ON_DISK_TRANSACTIONAL = "ON_DISK_TRANSACTIONAL"
