"""Cypher enum types.

Counterpart of the reference's enum support (storage/v2/enum_store.hpp;
grammar MemgraphCypher.g4 createEnumQuery/alterEnumAddValueQuery —
CREATE ENUM Name VALUES { A, B }, ALTER ENUM Name ADD VALUE C, literals
Name::Value): definitions live on the storage; values are small immutable
(enum, value) pairs ordered by their declaration position.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from functools import total_ordering

from ..exceptions import QueryException


@total_ordering
@dataclass(frozen=True)
class EnumValue:
    enum_name: str
    value_name: str
    position: int = 0

    def __eq__(self, other):
        return (isinstance(other, EnumValue)
                and other.enum_name == self.enum_name
                and other.value_name == self.value_name)

    def __lt__(self, other):
        if not isinstance(other, EnumValue) or \
                other.enum_name != self.enum_name:
            return NotImplemented
        return self.position < other.position

    def __hash__(self):
        return hash((self.enum_name, self.value_name))

    def __str__(self):
        return f"{self.enum_name}::{self.value_name}"


class EnumRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._enums: dict[str, list[str]] = {}

    def create(self, name: str, values: list[str]) -> None:
        with self._lock:
            if name in self._enums:
                raise QueryException(f"enum {name!r} already exists")
            if len(set(values)) != len(values):
                raise QueryException("enum values must be unique")
            self._enums[name] = list(values)

    def add_value(self, name: str, value: str) -> None:
        with self._lock:
            if name not in self._enums:
                raise QueryException(f"enum {name!r} does not exist")
            if value in self._enums[name]:
                raise QueryException(
                    f"enum {name!r} already has value {value!r}")
            self._enums[name].append(value)

    def value(self, name: str, value_name: str) -> EnumValue:
        with self._lock:
            values = self._enums.get(name)
            if values is None:
                raise QueryException(f"enum {name!r} does not exist")
            try:
                pos = values.index(value_name)
            except ValueError:
                raise QueryException(
                    f"enum {name!r} has no value {value_name!r}") from None
            return EnumValue(name, value_name, pos)

    def all(self) -> dict[str, list[str]]:
        with self._lock:
            return {k: list(v) for k, v in self._enums.items()}

    def to_list(self):
        return sorted(self.all().items())

    def load(self, items) -> None:
        with self._lock:
            self._enums = {k: list(v) for k, v in items}


def enum_registry(storage) -> EnumRegistry:
    reg = getattr(storage, "_enum_registry", None)
    if reg is None:
        reg = storage._enum_registry = EnumRegistry()
    return reg
