"""MVCC in-memory graph storage engine (host side).

Re-design of the reference storage layer (/root/reference/src/storage/v2/):
optimistic MVCC with per-object undo-delta chains, snapshot isolation,
label / label+property indexes, existence/unique constraints, snapshot+WAL
durability — built TPU-first: the storage engine's job is fast point
reads/writes plus cheap export of immutable CSR snapshots to device memory
(see memgraph_tpu.ops.csr).
"""

from .common import Gid, View, IsolationLevel, StorageMode
from .storage import InMemoryStorage, StorageConfig

__all__ = ["Gid", "View", "IsolationLevel", "StorageMode", "InMemoryStorage",
           "StorageConfig"]
