"""Single-blob host->device transfer.

The tunneled TPU pays ~0.5-1s latency PER host->device transfer almost
regardless of size (measured r4: 100MB contiguous uint8 in 0.22s, a
40KB array in 1.1s). Any multi-array upload therefore ships ONE
contiguous blob and reconstructs the arrays device-side in ONE jitted
(persistently compile-cached) slice+bitcast call.

The blob dtype is int32, not uint8: narrow->wide conversions
(bitcast u8(...,4)->i32 or shift-combine) take ~7.5s to COMPILE per use
on this platform, while same-width bitcasts and right-shifts compile in
<0.5s. So every segment is stored as whole 4-byte words; 2-byte dtypes
are widened host-side; bit-packed segments are exposed as uint32 words
for the caller to shift-unpack.

Reference analog: none — this exists because of the tunnel's per-RPC
latency; the reference's mgp graph view is shared-memory.
"""

from __future__ import annotations

import numpy as np

_WORD = 4


def pack_blob(arrays: dict):
    """Concatenate host arrays into one contiguous int32-word blob.

    arrays values: numpy arrays of 4-byte dtypes (float32/int32/uint32),
    2-byte/1-byte ints or bool (widened host-side to int32), or the
    special form ("bits", uint8_array) for bit-packed payloads whose
    bytes are exposed device-side as uint32 words (trailing bytes of
    each row zero-padded to a word boundary).

    Returns (blob_i32, segments); segments[name] describes the layout
    for `unblob`.
    """
    segs = {}
    parts = []
    off = 0  # in words

    def add_words(name, words_i32, kind, shape, dtype):
        nonlocal off
        segs[name] = (off, words_i32.size, kind, shape, dtype)
        parts.append(words_i32)
        off += words_i32.size

    for name, arr in arrays.items():
        if isinstance(arr, tuple) and arr[0] == "bits":
            raw = np.ascontiguousarray(arr[1])
            if raw.dtype != np.uint8:
                raise TypeError(f"{name}: bits payload must be uint8")
            row_bytes = raw.shape[-1]
            pad = (-row_bytes) % _WORD
            if pad:
                raw = np.concatenate(
                    [raw, np.zeros(raw.shape[:-1] + (pad,), np.uint8)],
                    axis=-1)
            words = raw.reshape(-1).view(np.int32)
            add_words(name, words, "bits",
                      raw.shape[:-1] + (raw.shape[-1] // _WORD,), np.uint32)
            continue
        a = np.ascontiguousarray(arr)
        if a.dtype in (np.dtype(np.int16), np.dtype(np.uint16),
                       np.dtype(np.int8), np.dtype(np.uint8),
                       np.dtype(np.bool_)):
            widened = a.astype(np.int32)
            add_words(name, widened.reshape(-1), "cast", a.shape, a.dtype)
        elif a.dtype.itemsize == _WORD:
            add_words(name, a.reshape(-1).view(np.int32), "word",
                      a.shape, a.dtype)
        else:
            raise TypeError(f"{name}: unsupported dtype {a.dtype}")
    if not parts:
        raise ValueError("pack_blob: no arrays")
    return np.concatenate(parts), segs


def unblob(blob, segs, name):
    """Traced: reconstruct one array from the int32-word device blob.

    "bits" segments come back as uint32 words; use `unpack_bit_words`
    to expand to 0/1 bits.
    """
    import jax
    import jax.numpy as jnp
    off, n_words, kind, shape, dtype = segs[name]
    raw = jax.lax.dynamic_slice_in_dim(blob, off, n_words)
    if kind == "cast":
        return raw.reshape(shape).astype(jnp.dtype(dtype))
    if kind == "bits":
        return jax.lax.bitcast_convert_type(raw, jnp.uint32).reshape(shape)
    if dtype == np.int32:
        return raw.reshape(shape)
    return jax.lax.bitcast_convert_type(
        raw, jnp.dtype(dtype)).reshape(shape)


def unpack_bit_words(words, n_bits):
    """Traced: (..., W) uint32 words -> (..., n_bits) bool.

    Bit i lives at word i>>5; within the word, bytes are little-endian
    and bits MSB-first per byte (numpy.packbits order): shift
    8*((i&31)>>3) + 7 - (i&7).
    """
    import jax.numpy as jnp
    j = np.arange(32)
    shifts = jnp.asarray(8 * (j >> 3) + 7 - (j & 7), dtype=jnp.uint32)
    bits = (words[..., :, None] >> shifts) & jnp.uint32(1)
    bits = bits.reshape(*words.shape[:-1], words.shape[-1] * 32)
    return bits[..., :n_bits] != 0


#: jitted reconstruction programs keyed by the blob's segment layout —
#: re-jitting a fresh closure per call silently retraced + recompiled on
#: EVERY upload (mglint MG008 recompile-hazard; the docstring's
#: "compile-cached per shape signature" promise was only true for the
#: persistent on-disk cache, not the in-process one)
_PREPARE_CACHE: dict = {}


def put_packed(arrays: dict) -> dict:
    """Ship `arrays` (dict of host np arrays) in one transfer; returns a
    dict of device arrays (one jitted reconstruction call, compile-cached
    per shape signature)."""
    import jax
    from ..utils.jax_cache import ensure_compile_cache
    ensure_compile_cache()

    blob_np, segs = pack_blob(arrays)
    key = tuple(sorted(
        (name, off, n_words, kind, tuple(int(s) for s in shape),
         np.dtype(dtype).str)
        for name, (off, n_words, kind, shape, dtype) in segs.items()))
    prepare = _PREPARE_CACHE.get(key)
    if prepare is None:
        @jax.jit
        def prepare(blob, _segs=segs):
            return {name: unblob(blob, _segs, name) for name in _segs}

        _PREPARE_CACHE[key] = prepare

    return prepare(jax.device_put(blob_np))
