"""Graph snapshot → device CSR export.

The seam between the MVCC host store and the TPU kernels, playing the role
the reference's `mg_graph::Graph` snapshot plays for MAGE modules
(/root/reference/include/mg_utils.hpp:128-170 builds an adjacency-list copy
by iterating the mgp_graph view): here the snapshot is a set of padded,
immutable device arrays in CSR form.

Design points for XLA (SURVEY.md §7 "hard parts"):
  - **Static shapes**: `n_nodes`/`n_edges` are padded up to bucket sizes
    (powers of two by default) so repeated exports of a mutating graph hit
    the same compiled kernels. Padding edges point at a sink row whose
    weight is 0 and whose src degree is 0, so segment reductions ignore them.
  - **Dense ids**: storage gids are compacted to [0, n); the mapping back to
    gids rides along host-side for result streaming.
  - **Topology cache**: exports are cached per (storage, topology_version,
    weight_property) so repeated CALLs don't re-export an unchanged graph —
    the staleness contract matches the reference's "online" modules, which
    also compute over their own snapshot of the graph.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..storage.common import View


def _bucket(n: int, minimum: int = 8) -> int:
    """Round up to the next power of two (compilation-amortizing bucket)."""
    n = max(n, minimum)
    return 1 << (n - 1).bit_length()


def _coerce_weight(w) -> float:
    """Edge-weight property -> float; non-numeric/missing -> 1.0."""
    return (float(w) if isinstance(w, (int, float))
            and not isinstance(w, bool) else 1.0)


@dataclass(frozen=True)
class DeviceGraph:
    """Immutable CSR+CSC snapshot. Arrays may live on device (jax) or host (np).

    CSR layout (edges lexsorted by (src, dst)) — feeds walks / out-expansion:
      row_ptr:    (n_pad+1,) int32 — CSR offsets
      col_idx:    (e_pad,)   int32 — destination node per edge
      src_idx:    (e_pad,)   int32 — source node per edge (COO mirror)
      weights:    (e_pad,)   float32 — edge weight (1.0 default, 0.0 padding)

    CSC layout (same edges lexsorted by (dst, src)) — feeds the pull-style
    segment reductions (pagerank/katz/...): destination-sorted indices let
    XLA use its fast sorted-segment-sum lowering instead of scatter, which
    profiled ~3x faster per iteration on TPU v5e:
      csc_src / csc_dst: (e_pad,) int32
      csc_weights:       (e_pad,) float32

    out_degree: (n_pad,) float32 — true out-degrees (0 for padding rows)
    n_nodes / n_edges: true counts;  n_pad / e_pad: padded counts
    node_gids:  (n_nodes,) int64 host array — dense index -> storage gid
    host_coo:   optional (src, dst, w) HOST arrays of the true edges —
                kept so a successor snapshot can diff edges for the
                O(delta) MXU plan refresh (ops/spmv_mxu.DeltaPlan)
    """

    row_ptr: object
    col_idx: object
    src_idx: object
    weights: object
    csc_src: object
    csc_dst: object
    csc_weights: object
    out_degree: object
    n_nodes: int
    n_edges: int
    n_pad: int
    e_pad: int
    node_gids: np.ndarray
    gid_to_idx: dict = field(repr=False, hash=False, compare=False)
    host_coo: tuple = field(default=None, repr=False, hash=False,
                            compare=False)

    def to_device(self) -> "DeviceGraph":
        from .blob import put_packed
        if not isinstance(self.row_ptr, np.ndarray):
            # arrays already device-resident: shipping them through
            # pack_blob would round-trip device->host->device
            return self
        dev = put_packed({
            "row_ptr": self.row_ptr, "col_idx": self.col_idx,
            "src_idx": self.src_idx, "weights": self.weights,
            "csc_src": self.csc_src, "csc_dst": self.csc_dst,
            "csc_weights": self.csc_weights,
            "out_degree": self.out_degree})
        return DeviceGraph(
            row_ptr=dev["row_ptr"],
            col_idx=dev["col_idx"],
            src_idx=dev["src_idx"],
            weights=dev["weights"],
            csc_src=dev["csc_src"],
            csc_dst=dev["csc_dst"],
            csc_weights=dev["csc_weights"],
            out_degree=dev["out_degree"],
            n_nodes=self.n_nodes, n_edges=self.n_edges,
            n_pad=self.n_pad, e_pad=self.e_pad,
            node_gids=self.node_gids, gid_to_idx=self.gid_to_idx,
            host_coo=self.host_coo)


def from_coo(src: np.ndarray, dst: np.ndarray,
             weights: Optional[np.ndarray] = None,
             n_nodes: Optional[int] = None,
             node_gids: Optional[np.ndarray] = None,
             pad: bool = True) -> DeviceGraph:
    """Build a host-side DeviceGraph from COO edge arrays (dense node ids)."""
    src = np.asarray(src, dtype=np.int64)
    dst = np.asarray(dst, dtype=np.int64)
    n_edges = len(src)
    if n_nodes is None:
        n_nodes = int(max(src.max(initial=-1), dst.max(initial=-1)) + 1)
    if weights is None:
        weights = np.ones(n_edges, dtype=np.float32)
    else:
        weights = np.asarray(weights, dtype=np.float32)

    n_pad = _bucket(n_nodes + 1) if pad else n_nodes + 1
    e_pad = _bucket(n_edges) if pad else max(n_edges, 1)
    # padding edges: sink->sink self loops with zero weight; the sink is the
    # extra padding row n_nodes (guaranteed to exist since n_pad >= n_nodes+1)
    sink = n_nodes

    # fast path: native C++ counting-sort builder (O(E+N), ops/native.py)
    from .native import build_csr_csc_native
    native = build_csr_csc_native(src, dst, weights, n_nodes, n_pad, e_pad) \
        if n_edges > 0 else None
    if native is not None:
        src_full = native["csr_src"]
        dst_full = native["csr_dst"]
        w_full = native["csr_w"]
        csc_src = native["csc_src"]
        csc_dst = native["csc_dst"]
        csc_w = native["csc_w"]
        row_ptr = native["row_ptr"]
        out_degree = native["out_degree"]
    else:
        # numpy fallback — lexicographic (src, dst) order: rows contiguous
        # AND sorted by dst, so device-side edge-membership queries can
        # binary-search within a row
        order = np.lexsort((dst, src))
        s_sorted = src[order]
        d_sorted = dst[order]
        w_sorted = weights[order]

        src_full = np.full(e_pad, sink, dtype=np.int32)
        dst_full = np.full(e_pad, sink, dtype=np.int32)
        w_full = np.zeros(e_pad, dtype=np.float32)
        src_full[:n_edges] = s_sorted
        dst_full[:n_edges] = d_sorted
        w_full[:n_edges] = w_sorted

        # CSC mirror: (dst, src)-sorted. Reuse the (src, dst)-sorted arrays
        # with one single-key stable sort — stability preserves the src order
        # within equal dst, giving (dst, src) order at half the sort cost.
        corder = np.argsort(d_sorted, kind="stable")
        csc_src = np.full(e_pad, sink, dtype=np.int32)
        csc_dst = np.full(e_pad, sink, dtype=np.int32)
        csc_w = np.zeros(e_pad, dtype=np.float32)
        csc_src[:n_edges] = s_sorted[corder]
        csc_dst[:n_edges] = d_sorted[corder]
        csc_w[:n_edges] = w_sorted[corder]

        counts = np.bincount(s_sorted, minlength=n_pad).astype(np.int64)
        row_ptr = np.zeros(n_pad + 1, dtype=np.int32)
        np.cumsum(counts, out=row_ptr[1:])

        out_degree = np.zeros(n_pad, dtype=np.float32)
        out_degree[:n_nodes] = np.bincount(
            src, minlength=n_nodes).astype(np.float32)[:n_nodes]

    if node_gids is None:
        node_gids = np.arange(n_nodes, dtype=np.int64)
    gid_to_idx = {int(g): i for i, g in enumerate(node_gids)}

    return DeviceGraph(row_ptr=row_ptr, col_idx=dst_full, src_idx=src_full,
                       weights=w_full,
                       csc_src=csc_src, csc_dst=csc_dst, csc_weights=csc_w,
                       out_degree=out_degree,
                       n_nodes=n_nodes, n_edges=n_edges,
                       n_pad=n_pad, e_pad=e_pad,
                       node_gids=np.asarray(node_gids, dtype=np.int64),
                       gid_to_idx=gid_to_idx,
                       host_coo=(src.astype(np.int32), dst.astype(np.int32),
                                 weights))


def export_csr(accessor, weight_property: Optional[int] = None,
               label_filter: Optional[int] = None,
               edge_type_filter: Optional[set] = None,
               view: View = View.OLD,
               pad: bool = True,
               to_device: bool = True) -> DeviceGraph:
    """Export the accessor's visible graph as CSR arrays.

    Fast path: objects with no delta chain are read directly (no MVCC
    materialization); only objects with version chains pay the walk.
    """
    storage = accessor.storage
    txn = accessor.txn

    node_gids = []
    gid_to_idx: dict[int, int] = {}
    for vertex in list(storage._vertices.values()):
        if vertex.delta is None:
            if vertex.deleted:
                continue
            if label_filter is not None and label_filter not in vertex.labels:
                continue
        else:
            from ..storage.storage import VertexAccessor
            va = VertexAccessor(vertex, accessor)
            if not va.is_visible(view):
                continue
            if label_filter is not None and not va.has_label(label_filter, view):
                continue
        gid_to_idx[vertex.gid] = len(node_gids)
        node_gids.append(vertex.gid)

    srcs, dsts, ws = [], [], []
    has_w = weight_property is not None
    for edge in list(storage._edges.values()):
        if edge.delta is None:
            if edge.deleted:
                continue
            props = edge.properties if has_w else None
        else:
            from ..storage.storage import EdgeAccessor
            ea = EdgeAccessor(edge, accessor)
            if not ea.is_visible(view):
                continue
            props = ea.properties(view) if has_w else None
        if edge_type_filter is not None and edge.edge_type not in edge_type_filter:
            continue
        si = gid_to_idx.get(edge.from_vertex.gid)
        di = gid_to_idx.get(edge.to_vertex.gid)
        if si is None or di is None:
            continue
        srcs.append(si)
        dsts.append(di)
        if has_w:
            ws.append(_coerce_weight(
                props.get(weight_property) if props else None))

    g = from_coo(np.asarray(srcs, dtype=np.int64),
                 np.asarray(dsts, dtype=np.int64),
                 np.asarray(ws, dtype=np.float32) if has_w else None,
                 n_nodes=len(node_gids),
                 node_gids=np.asarray(node_gids, dtype=np.int64),
                 pad=pad)
    return g.to_device() if to_device else g


def export_csr_delta(prev: DeviceGraph, accessor, changed_gids,
                     weight_property=None, label_filter=None,
                     edge_type_filter=None, pad: bool = True,
                     to_device: bool = True):
    """O(changed) re-export: splice the changed vertices' edges into the
    previous snapshot's host arrays instead of walking ALL edges in
    Python (the full export is the dominant per-version cost at 10M
    edges). Valid only while the VERTEX SET of the view is unchanged —
    returns None when it cannot guarantee that (caller falls back to
    export_csr). Rebuild = drop every edge incident to a changed vertex
    from the previous COO, append the changed vertices' current edges
    read from storage (O(changed x degree)), then one native/numpy
    from_coo pass.
    """
    if prev.host_coo is None:
        return None
    storage = accessor.storage
    changed = list(changed_gids)
    bitmap = np.zeros(prev.n_nodes, dtype=bool)
    from ..storage.storage import VertexAccessor
    fresh_src: list = []
    fresh_dst: list = []
    fresh_w: list = []
    has_w = weight_property is not None
    for gid in changed:
        idx = prev.gid_to_idx.get(gid)
        vertex = storage._vertices.get(gid)
        if vertex is None:
            return None               # vertex gone: node set changed
        va = VertexAccessor(vertex, accessor)
        visible = va.is_visible(View.OLD)
        if label_filter is not None and visible:
            visible = va.has_label(label_filter, View.OLD)
        if idx is None or not visible:
            return None               # joined/left the view: full export
        bitmap[idx] = True
    from ..storage.storage import EdgeAccessor
    for gid in changed:
        idx = prev.gid_to_idx[gid]
        vertex = storage._vertices[gid]
        # raw MVCC state, NOT VertexAccessor.out_edges/in_edges: those
        # apply the SESSION's fine-grained permissions (_fg_edge_ok),
        # and a globally cached snapshot must match export_csr's
        # permission-free content regardless of which user built it
        st = accessor._vertex_state(vertex, View.OLD)
        for (etype, _other, edge) in st.out_edges:
            if edge_type_filter is not None and \
                    etype not in edge_type_filter:
                continue
            ea = EdgeAccessor(edge, accessor)
            if not ea.is_visible(View.OLD):
                continue
            di = prev.gid_to_idx.get(edge.to_vertex.gid)
            if di is None:
                return None           # new endpoint: node set changed
            # every out-edge of a changed vertex re-emits exactly once
            # here; edges INTO a changed vertex from an UNCHANGED source
            # re-emit in the in_edges pass below
            fresh_src.append(idx)
            fresh_dst.append(di)
            if has_w:
                fresh_w.append(_coerce_weight(
                    ea.properties(View.OLD).get(weight_property)))
        for (etype, _other, edge) in st.in_edges:
            if edge_type_filter is not None and \
                    etype not in edge_type_filter:
                continue
            ea = EdgeAccessor(edge, accessor)
            if not ea.is_visible(View.OLD):
                continue
            si = prev.gid_to_idx.get(edge.from_vertex.gid)
            if si is None:
                return None
            if bitmap[si]:
                continue              # its changed src re-emits it
            fresh_src.append(si)
            fresh_dst.append(idx)
            if has_w:
                fresh_w.append(_coerce_weight(
                    ea.properties(View.OLD).get(weight_property)))
    p_src, p_dst, p_w = prev.host_coo
    keep = ~(bitmap[p_src] | bitmap[p_dst])
    src = np.concatenate([p_src[keep].astype(np.int64),
                          np.asarray(fresh_src, dtype=np.int64)])
    dst = np.concatenate([p_dst[keep].astype(np.int64),
                          np.asarray(fresh_dst, dtype=np.int64)])
    weights = None
    if has_w:
        weights = np.concatenate(
            [p_w[keep], np.asarray(fresh_w, dtype=np.float32)])
    g = from_coo(src, dst, weights, n_nodes=prev.n_nodes,
                 node_gids=prev.node_gids, pad=pad)
    return g.to_device() if to_device else g


# --------------------------------------------------------------------------
# Partition-centric sharded layout (multi-chip analytics)
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class ShardedCSR:
    """Partition-centric (src-shard, dst-shard)-blocked edge layout.

    The mesh analog of DeviceGraph: vertices are split into `n_shards`
    contiguous blocks of `block` ids (padded to n_pad2 = n_shards*block,
    so uneven `n_nodes % n_shards` just pads the last block); every edge
    is owned by the shard of its `by` endpoint ("src" for the pull-style
    SpMV kernels, "dst" for label propagation). Within a shard, edges
    are (dst, src)-sorted, which makes the per-device edge list a
    concatenation of (owner, dst-shard) BLOCKS — the partition-centric
    layout of "Accelerating PageRank using Partition-Centric Processing"
    (PAPERS.md): a device's contribution to remote shard q is the
    contiguous run block_ptr[p, q]:block_ptr[p, q+1], and one
    psum/psum_scatter per iteration moves exactly those partials.

    Arrays are stacked (n_shards, edges_per_shard) and, once
    `.to_device(ctx)` runs, placed one row per device via the
    MeshContext's edge_blocks sharding — CSR shards resident per device,
    so graphs larger than one chip's HBM fit.

    Padding edges: src = shard base (locally index 0), dst = n_nodes
    (the sink row, always < n_pad2), weight 0 — inert under every
    segment reduction, and appended at the tail so dst stays sorted.
    """

    src: object          # (P, per) int32
    dst: object          # (P, per) int32
    weights: object      # (P, per) float32
    block_ptr: np.ndarray  # (P, P+1) int32 — (p, q)-block boundaries
    n_nodes: int
    n_edges: int
    n_shards: int
    block: int           # vertices per shard
    n_pad2: int          # n_shards * block
    per: int             # edges per shard row (incl. padding)
    by: str              # "src" | "dst" — owning endpoint

    def to_device(self, ctx) -> "ShardedCSR":
        """Place edge rows one-per-device under ctx's edge sharding."""
        if not isinstance(self.src, np.ndarray):
            return self
        return ShardedCSR(
            src=ctx.put_edge_blocks(self.src),
            dst=ctx.put_edge_blocks(self.dst),
            weights=ctx.put_edge_blocks(self.weights),
            block_ptr=self.block_ptr, n_nodes=self.n_nodes,
            n_edges=self.n_edges, n_shards=self.n_shards,
            block=self.block, n_pad2=self.n_pad2, per=self.per,
            by=self.by)

    def refresh(self, ctx) -> "ShardedCSR":
        """Re-place the edge rows on the mesh — the device_lost recovery
        hook (parallel/checkpoint.py): after a backend loss the resident
        rows are gone, so pull the host copy and re-run placement. On a
        host-side (not yet placed) layout this is a no-op."""
        if isinstance(self.src, np.ndarray):
            return self
        return ShardedCSR(
            src=ctx.put_edge_blocks(np.asarray(self.src)),
            dst=ctx.put_edge_blocks(np.asarray(self.dst)),
            weights=ctx.put_edge_blocks(np.asarray(self.weights)),
            block_ptr=self.block_ptr, n_nodes=self.n_nodes,
            n_edges=self.n_edges, n_shards=self.n_shards,
            block=self.block, n_pad2=self.n_pad2, per=self.per,
            by=self.by)


def _ceil_multiple(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def shard_edges(src, dst, weights, n_nodes: int, n_shards: int,
                by: str = "src", block_multiple: int = 8) -> ShardedCSR:
    """Block COO edges partition-centrically over `n_shards` shards.

    Host-side layout only — call `.to_device(ctx)` to make the rows
    device-resident. `block` is rounded to `block_multiple` so vertex
    blocks tile the VPU lanes on TPU.
    """
    if by not in ("src", "dst"):
        raise ValueError(f"by must be 'src' or 'dst', got {by!r}")
    src = np.asarray(src, dtype=np.int64)
    dst = np.asarray(dst, dtype=np.int64)
    n_edges = len(src)
    w = (np.ones(n_edges, dtype=np.float32) if weights is None
         else np.asarray(weights, dtype=np.float32))
    # +1: the sink row n_nodes must exist inside the padded vertex space
    block = _ceil_multiple(max((n_nodes + 1 + n_shards - 1) // n_shards, 1),
                           block_multiple)
    n_pad2 = n_shards * block

    key = src if by == "src" else dst
    owner = key // block
    order = np.lexsort((src, dst, owner))
    s_s, d_s, w_s, o_s = src[order], dst[order], w[order], owner[order]
    counts = np.bincount(o_s, minlength=n_shards)
    per = _ceil_multiple(max(int(counts.max(initial=0)), 1), block_multiple)

    sink = n_nodes
    src_b = np.empty((n_shards, per), dtype=np.int32)
    dst_b = np.full((n_shards, per), sink, dtype=np.int32)
    w_b = np.zeros((n_shards, per), dtype=np.float32)
    # padding src must gather in-bounds LOCALLY on its shard: shard base
    src_b[:] = (np.arange(n_shards, dtype=np.int32) * block)[:, None]
    offsets = np.concatenate([[0], np.cumsum(counts)])
    for p in range(n_shards):
        lo, hi = offsets[p], offsets[p + 1]
        src_b[p, :hi - lo] = s_s[lo:hi]
        dst_b[p, :hi - lo] = d_s[lo:hi]
        w_b[p, :hi - lo] = w_s[lo:hi]

    # partition-centric block boundaries: device p's edges into dst
    # shard q are dst_b[p, block_ptr[p, q]:block_ptr[p, q+1]] (the dst
    # sort within each shard makes these contiguous runs)
    block_ptr = np.empty((n_shards, n_shards + 1), dtype=np.int32)
    for p in range(n_shards):
        block_ptr[p] = np.searchsorted(
            dst_b[p], np.arange(n_shards + 1, dtype=np.int64) * block)

    return ShardedCSR(src=src_b, dst=dst_b, weights=w_b,
                      block_ptr=block_ptr, n_nodes=n_nodes,
                      n_edges=n_edges, n_shards=n_shards, block=block,
                      n_pad2=n_pad2, per=per, by=by)


_sharded_csr_guard = threading.Lock()


def shard_csr(graph: DeviceGraph, ctx, by: str = "src",
              doubled: bool = False) -> ShardedCSR:
    """Partition-centric ShardedCSR for `graph` on `ctx`, cached on the
    (immutable) DeviceGraph snapshot per (mesh, by, doubled) — repeated
    mesh CALLs on an unchanged graph pay the blocking and transfer once.

    `doubled=True` concatenates both edge directions before blocking
    (the undirected view label propagation iterates over)."""
    key = (ctx.cache_key, by, doubled)
    cache = getattr(graph, "_sharded_csr", None)
    if cache is not None and key in cache:
        return cache[key]
    with _sharded_csr_guard:
        cache = getattr(graph, "_sharded_csr", None)
        if cache is None:
            cache = {}
            object.__setattr__(graph, "_sharded_csr", cache)
        if key not in cache:
            if graph.host_coo is not None:
                src, dst, w = graph.host_coo
            else:
                src = np.asarray(graph.src_idx)[:graph.n_edges]
                dst = np.asarray(graph.col_idx)[:graph.n_edges]
                w = np.asarray(graph.weights)[:graph.n_edges]
            if doubled:
                src, dst = (np.concatenate([src, dst]),
                            np.concatenate([dst, src]))
                w = np.concatenate([w, w])
            scsr = shard_edges(src, dst, w, graph.n_nodes,
                               ctx.n_shards, by=by)
            cache[key] = scsr.to_device(ctx)
    return cache[key]


class GraphCache:
    """Per-storage cache of device CSR snapshots keyed by topology version.

    The framework-level staleness contract: a cached snapshot is valid while
    `storage.topology_version` is unchanged; any commit that touches
    topology (or properties, conservatively) bumps the version.

    Keyed on the storage object itself via a WeakKeyDictionary so snapshots
    die with their storage (no id()-recycling hazard, no leak).
    """

    def __init__(self) -> None:
        import weakref
        self._lock = threading.Lock()
        self._cache: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()

    def get(self, accessor, weight_property=None, label_filter=None,
            edge_type_filter=None) -> DeviceGraph:
        storage = accessor.storage
        etf = (tuple(sorted(edge_type_filter))
               if edge_type_filter is not None else None)
        # key on the TRANSACTION's topology snapshot, not the live
        # version: a concurrent commit after this txn began must not be
        # visible in (or poison) the snapshot cached for this view —
        # the bump is atomic with the visibility flip relative to this
        # capture (storage._commit), so the snapshot id and the MVCC
        # view agree (r5 review findings 2+3)
        version = getattr(accessor, "topology_snapshot", None)
        if version is None:
            version = storage.topology_version
        key = (version, weight_property, label_filter, etf)
        base_key = ("base", weight_property, label_filter, etf)
        newest = None
        with self._lock:
            per_storage = self._cache.get(storage)
            hit = per_storage.get(key) if per_storage else None
            base = per_storage.get(base_key) if per_storage else None
            for k, v in (per_storage or {}).items():
                if k[0] == "base" or k[1:] != key[1:]:
                    continue
                # base anchor: newest snapshot with a FULL mxu plan
                # (_mxu_base_self post-dates its get(), so scan live)
                if getattr(v, "_mxu_base_self", False) \
                        and (base is None or base[0] < k[0]):
                    base = (k[0], v)
                # delta-export base: newest snapshot STRICTLY OLDER than
                # this view (a newer one may contain commits this txn
                # cannot see)
                if k[0] < version and (newest is None
                                       or k[0] > newest[0]):
                    newest = (k[0], v)
        if hit is not None:
            return hit
        g = None
        # O(changed) incremental export (the python walk over ALL edges
        # is the dominant per-version cost at 10M+ edges); bulk commits
        # touching a large fraction of the graph fall back to the full
        # export, whose delta-free fast path is cheaper per edge
        if newest is not None:
            from ..storage.storage import ChangeLogUnknowable
            changed = storage.changes_between(newest[0], version)
            if isinstance(changed, ChangeLogUnknowable):
                # typed wrap verdict: the log cannot reconstruct the
                # gap — full export, LOUDLY counted (a silently-partial
                # delta here would cache a wrong snapshot)
                import logging
                from ..observability.metrics import global_metrics
                global_metrics.increment("delta.fallback_rebuild_total")
                logging.getLogger(__name__).info(
                    "change log unknowable (%s) for versions (%d, %d]; "
                    "full CSR export", changed.reason, newest[0],
                    version)
                changed = None
            if changed is not None and \
                    len(changed) <= max(1024, newest[1].n_nodes // 5):
                try:
                    g = export_csr_delta(
                        newest[1], accessor, changed,
                        weight_property=weight_property,
                        label_filter=label_filter,
                        edge_type_filter=edge_type_filter)
                except Exception:  # noqa: BLE001 — any doubt: full export
                    import logging
                    logging.getLogger(__name__).debug(
                        "delta CSR export failed; falling back to full "
                        "export", exc_info=True)
                    g = None
        if g is None:
            g = export_csr(accessor, weight_property=weight_property,
                           label_filter=label_filter,
                           edge_type_filter=edge_type_filter)
        # Delta lineage: if an earlier snapshot of this view carries a
        # fully-built MXU plan, record it plus the changed-vertex set so
        # the analytics layer can refresh O(delta) instead of replanning
        # (ops/pagerank._try_delta_plan).
        if base is not None:
            from ..storage.storage import ChangeLogUnknowable
            base_version, base_g = base
            changed = storage.changes_between(base_version, version)
            # an unknowable gap (typed wrap verdict) anchors nothing:
            # the MXU layer would replan from an incomplete diff
            if isinstance(changed, frozenset) \
                    and getattr(base_g, "_mxu_state", None) is not None:
                object.__setattr__(g, "_delta_ctx", (base_g, changed))
        with self._lock:
            # keep base anchors, this version's variants (e.g. other
            # weight properties), and NEWER versions (an older-view txn
            # storing must not evict a newer snapshot — r5 review);
            # drop strictly older version snapshots
            per = self._cache.get(storage) or {}
            prev = {k: v for k, v in per.items()
                    if k[0] == "base" or k[0] >= version}
            # the previous snapshot becomes the base anchor once a FULL
            # plan was built on it (pagerank marks _mxu_base_self)
            for k, v in per.items():
                if k[0] not in ("base", version) \
                        and k[1:] == key[1:] \
                        and getattr(v, "_mxu_base_self", False):
                    cur_base = prev.get(base_key)
                    if cur_base is None or cur_base[0] < k[0]:
                        prev[base_key] = (k[0], v)
            prev[key] = g
            self._cache[storage] = prev
        return g

    def clear(self) -> None:
        with self._lock:
            self._cache = __import__("weakref").WeakKeyDictionary()


GLOBAL_GRAPH_CACHE = GraphCache()
