"""Pallas TPU kernels applying a Benes network in 3 HBM passes.

The XLA roll formulation (ops/spmv_mxu._benes_apply_rolls) re-reads and
re-writes the full array once per stage: 2*log2(N)-1 HBM round trips
(~47 at N=2^24), which round-4 profiling showed is ~90% of the PageRank
per-iteration cost. This module exploits the Benes stage order
(d = N/2 ... 2, 1, 2 ... N/2): every stage with distance d < 2^K acts
entirely inside aligned 2^K-element blocks (XOR by d < 2^K cannot leave
the block), and those stages are CONTIGUOUS in the middle of the
schedule. So:

  pass A (outer-down): stages d = 2^(n-1) .. 2^K applied on a
          (2^(n-K), M, 128) view — axis-0 rolls, one read+write of x.
  pass B (middle):     all 2K-1 stages with d < 2^K fused in ONE kernel;
          each grid step holds a 2^K-element block in VMEM and applies
          every middle stage before writing back once.
  pass C (outer-up):   stages d = 2^K .. 2^(n-1), same view as pass A.

Masks are shipped as per-element int32 bit-planes: bit b of
word[plane, i] is stage (plane*31+b)'s swap decision for element i, so
extraction is an elementwise shift+AND — no gathers, no repeats, no
narrow dtypes (which this platform compiles pathologically, see
ops/blob.py). 31 bits per int32 plane keeps the sign bit out of play.

Reference analog: none — the reference scatters via CUDA/C++; this is
the TPU-native formulation of applying a fixed permutation at HBM speed.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import numpy as np

from .benes import benes_stage_distances

LANES = 128
BITS_PER_PLANE = 31
DEFAULT_K = 17          # middle-block log2 size: 2^17 elems = 1024 rows
# (2^18 blocks hit the 16MB scoped-vmem stack limit when the kernel is
# co-scheduled with the pagerank einsums inside one while_loop body)


def _log2(x: int) -> int:
    return int(x).bit_length() - 1


@dataclass(frozen=True)
class BenesPallasSpec:
    """Static routing metadata (hashable; closed over by the jitted fn).

    mid_stages / outer_down / outer_up: tuples of (plane, bit, distance)
    in application order. Dead (all-zero-mask) stages are omitted.
    """
    net_log2: int
    K: int
    mid_planes: int
    mid_stages: tuple
    outer_down: tuple
    outer_up: tuple


def build_pallas_masks(masks_packed: np.ndarray, net_log2: int,
                       K: int | None = None):
    """Reorganize bit-packed stage masks (n_stages, N/8 uint8, packbits
    order) into per-element int32 bit-planes + static spec.

    Returns (spec, mid_words, outer_words):
      mid_words   (mid_planes, N/128, 128) int32
      outer_words (N/128, 128) int32, or None when net fits one block
    """
    N = 1 << net_log2
    if K is None:
        K = min(net_log2, DEFAULT_K)
    K = min(K, net_log2)
    dists = benes_stage_distances(net_log2)
    n_stages = len(dists)
    assert masks_packed.shape[0] == n_stages

    n_outer = net_log2 - K            # per side
    rows = N // LANES

    mid_stages, outer_down, outer_up = [], [], []
    mid_pos = 0
    n_mid_planes = max(1, -(-(2 * K - 1) // BITS_PER_PLANE))
    mid_words = np.zeros((n_mid_planes, rows, LANES), dtype=np.int64)
    outer_words = np.zeros((rows, LANES), dtype=np.int64)
    outer_bit = 0
    for s, d in enumerate(dists):
        row = masks_packed[s]
        if not row.any():
            continue                   # dead stage: no swaps routed
        bits = np.unpackbits(row)[:N].astype(np.int64).reshape(rows, LANES)
        if d < (1 << K):
            plane, bit = divmod(mid_pos, BITS_PER_PLANE)
            mid_words[plane] |= bits << bit
            mid_stages.append((plane, bit, d))
            mid_pos += 1
        else:
            assert outer_bit < 31, "outer stages exceed one int32 plane"
            outer_words |= bits << outer_bit
            if s < n_stages // 2:
                outer_down.append((0, outer_bit, d))
            else:
                outer_up.append((0, outer_bit, d))
            outer_bit += 1
    spec = BenesPallasSpec(
        net_log2=net_log2, K=K, mid_planes=n_mid_planes,
        mid_stages=tuple(mid_stages), outer_down=tuple(outer_down),
        outer_up=tuple(outer_up))
    ow = outer_words.astype(np.int32) if n_outer > 0 else None
    return spec, mid_words.astype(np.int32), ow


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------

def _stage_in_block(x, w_planes, plane, bit, d, row_iota, lane_iota):
    """One masked-exchange stage on an in-VMEM block x (R, 128).

    w_planes: list of (R, 128) int32 bit-plane blocks.
    Partner of i is i^d: roll -d where bit_d(i)==0, +d where ==1.
    """
    import jax.numpy as jnp
    m = ((w_planes[plane] >> bit) & 1) == 1
    if d >= LANES:
        e = d // LANES
        sel = ((row_iota >> _log2(e)) & 1) == 1
        sw = jnp.where(sel, jnp.roll(x, e, axis=0), jnp.roll(x, -e, axis=0))
    else:
        sel = ((lane_iota >> _log2(d)) & 1) == 1
        sw = jnp.where(sel, jnp.roll(x, d, axis=1), jnp.roll(x, -d, axis=1))
    return jnp.where(m, sw, x)


def _mid_kernel(spec):
    import jax
    import jax.numpy as jnp

    def kernel(w_ref, x_ref, o_ref):
        x = x_ref[:]
        R = x.shape[0]
        row_iota = jax.lax.broadcasted_iota(jnp.int32, (R, LANES), 0)
        lane_iota = jax.lax.broadcasted_iota(jnp.int32, (R, LANES), 1)
        planes = [w_ref[p] for p in range(spec.mid_planes)]
        for plane, bit, d in spec.mid_stages:
            x = _stage_in_block(x, planes, plane, bit, d,
                                row_iota, lane_iota)
        o_ref[:] = x
    return kernel


def _outer_kernel(stages):
    """stages: tuple of (plane, bit, d); applied on a (G2, CH, 128) block
    where axis 0 spans the full outer dimension (distance d maps to an
    axis-0 roll by d / 2^K)."""
    import jax
    import jax.numpy as jnp

    def kernel(K, w_ref, x_ref, o_ref):
        x = x_ref[:]
        G2 = x.shape[0]
        a_iota = jax.lax.broadcasted_iota(
            jnp.int32, (G2, x.shape[1], LANES), 0)
        w = w_ref[:]
        for plane, bit, d in stages:
            t = d >> K
            m = ((w >> bit) & 1) == 1
            sel = ((a_iota >> _log2(t)) & 1) == 1
            sw = jnp.where(sel, jnp.roll(x, t, axis=0),
                           jnp.roll(x, -t, axis=0))
            x = jnp.where(m, sw, x)
        o_ref[:] = x
    return kernel


def benes_apply_pallas(x2, mid_words, outer_words, spec: BenesPallasSpec,
                       interpret: bool = False):
    """Apply the Benes network to x2 ((N/128, 128), any fp dtype) via the
    3-pass pallas formulation. Traced (usable under jit / while_loop)."""
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, K = spec.net_log2, spec.K
    N = 1 << n
    rows = N // LANES
    RB = 1 << (K - 7)                  # rows per middle block
    NB = rows // RB                    # middle grid size
    G2 = 1 << (n - K)                  # outer axis-0 extent
    M = rows // max(G2, 1)             # rows per outer column

    vmem = dict(memory_space=pltpu.VMEM)

    def outer_pass(x2, stages):
        if not stages:
            return x2
        # chunk the row dim so the x block stays ~2^19 elements
        # (~1 MiB bf16 / 2 MiB f32, double-buffered by mosaic)
        target = (1 << 19)
        CH = max(1, min(M, target // max(G2, 1) // LANES))
        while M % CH:
            CH -= 1
        x3 = x2.reshape(G2, M, LANES)
        w3 = outer_words.reshape(G2, M, LANES)
        out = pl.pallas_call(
            partial(_outer_kernel(stages), K),
            out_shape=jax.ShapeDtypeStruct(x3.shape, x3.dtype),
            grid=(M // CH,),
            in_specs=[
                pl.BlockSpec((G2, CH, LANES), lambda i: (0, i, 0), **vmem),
                pl.BlockSpec((G2, CH, LANES), lambda i: (0, i, 0), **vmem),
            ],
            out_specs=pl.BlockSpec((G2, CH, LANES), lambda i: (0, i, 0),
                                   **vmem),
            interpret=interpret,
        )(w3, x3)
        return out.reshape(rows, LANES)

    def mid_pass(x2):
        if not spec.mid_stages:
            return x2
        return pl.pallas_call(
            _mid_kernel(spec),
            out_shape=jax.ShapeDtypeStruct((rows, LANES), x2.dtype),
            grid=(NB,),
            in_specs=[
                pl.BlockSpec((spec.mid_planes, RB, LANES),
                             lambda i: (0, i, 0), **vmem),
                pl.BlockSpec((RB, LANES), lambda i: (i, 0), **vmem),
            ],
            out_specs=pl.BlockSpec((RB, LANES), lambda i: (i, 0), **vmem),
            interpret=interpret,
        )(mid_words, x2)

    x2 = outer_pass(x2, spec.outer_down)
    x2 = mid_pass(x2)
    x2 = outer_pass(x2, spec.outer_up)
    return x2
