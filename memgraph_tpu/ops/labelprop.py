"""Community detection by synchronous label propagation on the semiring
core.

Counterpart of the reference's community-detection modules
(/root/reference/query_modules/community_detection_module/ — online
label-propagation / LabelRankT — and mage/cpp/community_detection_module/
Louvain): each round every node adopts the label carrying the largest total
incident edge weight among its neighbors (both directions), with
deterministic min-label tie-breaking and a self-weight term for stability.

TPU formulation (no hash tables, static shapes): the election is a custom
semiring-core step — per round,
  1. gather neighbor labels onto edges:     lab_e = label[src_e]
  2. lexicographic sort of (dst_e, lab_e) pairs via `lax.sort` (num_keys=2)
  3. run-length-reduce equal (dst, lab) runs with a sum edge_reduce
  4. max-weight then min-label edge_reduce passes elect each dst's label
Everything is sorts + core ⊕-reductions — the shapes XLA tiles well; the
fused epilogue is the own-label-wins rule + the changed-any convergence
partial.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import semiring as S
from .csr import DeviceGraph


def _labelprop_step(labels, A, env, P, n_out):
    """One election round; returns the proposed labels (the `acc`)."""
    src2, dst2, w2 = A["src"], A["dst"], A["w"]
    e2 = src2.shape[0]
    big_w = jnp.float32(0.0)
    lab_e = labels[src2]
    # lexicographic sort by (dst, neighbor-label)
    d_s, l_s, w_s = jax.lax.sort((dst2, lab_e, w2), num_keys=2)
    first = jnp.concatenate([
        jnp.ones((1,), dtype=jnp.bool_),
        (d_s[1:] != d_s[:-1]) | (l_s[1:] != l_s[:-1])])
    run_id = jnp.cumsum(first.astype(jnp.int32)) - 1  # dense run ids < e2
    run_w = S.edge_reduce("sum", w_s, run_id, e2)
    # representative dst/label of each run (value at its first element)
    idx = jnp.arange(e2, dtype=jnp.int32)
    first_idx = S.edge_reduce("min", jnp.where(first, idx, e2), run_id, e2)
    first_idx = jnp.minimum(first_idx, e2 - 1)
    run_dst = d_s[first_idx]
    run_lab = l_s[first_idx]
    valid_run = idx <= run_id[-1]
    run_w = jnp.where(valid_run, run_w, big_w)
    # add self-weight as an implicit run for the node's own label: handled
    # by comparing the best neighbor run against self_weight below.
    best_w = S.edge_reduce("max", run_w, run_dst, n_out)
    # min label among runs achieving best weight for their dst
    is_best = run_w >= best_w[run_dst] - 1e-12
    cand_lab = jnp.where(valid_run & is_best, run_lab, jnp.int32(n_out))
    best_lab = S.edge_reduce("min", cand_lab, run_dst, n_out)
    has_nb = best_lab < n_out
    self_weight = P["self_weight"]
    # keep own label when it's at least as heavy (self_weight) or no nbrs
    own_wins = (~has_nb) | (self_weight >= best_w) | \
               (jnp.isclose(self_weight, best_w) & (labels <= best_lab))
    return jnp.where(own_wins, labels, best_lab)


def _labelprop_epilogue(labels, proposed, env, P):
    return proposed, jnp.any(proposed != labels)


def label_propagation(graph: DeviceGraph, max_iterations: int = 30,
                      self_weight: float = 0.0, directed: bool = False,
                      mesh=None, labels0=None):
    """Returns (community_label[:n_nodes], iterations).

    Labels are dense node indices (a community's label is one member's id).
    `mesh` (MeshContext | Mesh | int | None) routes through the
    multi-chip layer; see ops.pagerank.pagerank.

    `labels0` warm-starts the election from a previous labeling —
    callers must hold the ops/delta.py monotone contract (adds-only
    deltas; a removal must cold-start LOUDLY).
    """
    backend, ctx = S.route_backend(graph, mesh, semiring="max_min")
    if backend == "mesh":
        from ..parallel.analytics import label_propagation_mesh
        with S.backend_extent("mesh"):
            return label_propagation_mesh(
                graph, ctx, max_iterations=max_iterations,
                self_weight=self_weight, directed=directed,
                labels0=labels0)
    if directed:
        src2, dst2, w2 = graph.src_idx, graph.col_idx, graph.weights
    else:
        src2 = jnp.concatenate([graph.src_idx, graph.col_idx])
        dst2 = jnp.concatenate([graph.col_idx, graph.src_idx])
        w2 = jnp.concatenate([graph.weights, graph.weights])
    start = np.arange(graph.n_pad, dtype=np.int32)
    if labels0 is not None:
        arr = np.asarray(labels0, dtype=np.int32)[:graph.n_nodes]
        start[:len(arr)] = arr
    labels, _, iters = S.fixpoint(
        "max_min",
        arrays={"src": src2, "dst": dst2, "w": w2},
        params={"self_weight": np.float32(self_weight)},
        x0=jnp.asarray(start), n_out=graph.n_pad,
        step=_labelprop_step, epilogue=_labelprop_epilogue,
        max_iterations=max_iterations, metric="changed")
    # one fused host transfer for the whole result tuple (MG009)
    labels_h, iters_h = jax.device_get((labels[:graph.n_nodes], iters))  # mglint: disable=MG009 — results must ship host; this IS the single fused transfer for the whole tuple
    return labels_h, int(iters_h)
