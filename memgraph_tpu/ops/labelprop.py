"""Community detection by synchronous label propagation on TPU.

Counterpart of the reference's community-detection modules
(/root/reference/query_modules/community_detection_module/ — online
label-propagation / LabelRankT — and mage/cpp/community_detection_module/
Louvain): each round every node adopts the label carrying the largest total
incident edge weight among its neighbors (both directions), with
deterministic min-label tie-breaking and a self-weight term for stability.

TPU formulation (no hash tables, static shapes): per round,
  1. gather neighbor labels onto edges:     lab_e = label[src_e]
  2. lexicographic sort of (dst_e, lab_e) pairs via `lax.sort` (num_keys=2)
  3. run-length-reduce equal (dst, lab) runs with a segment-sum over run ids
  4. two segment-max/min passes pick each dst's max-weight (min-label) run
Everything is sorts + segment reductions — the shapes XLA tiles well.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .csr import DeviceGraph


@partial(jax.jit, static_argnames=("n_pad", "e2", "max_iterations"))
def _labelprop_kernel(src2, dst2, w2, n_pad: int, e2: int,
                      max_iterations: int, self_weight):
    """src2/dst2/w2: both edge directions concatenated, length e2."""
    labels0 = jnp.arange(n_pad, dtype=jnp.int32)
    big_w = jnp.float32(0.0)

    def one_round(labels):
        lab_e = labels[src2]
        # lexicographic sort by (dst, neighbor-label)
        d_s, l_s, w_s = jax.lax.sort((dst2, lab_e, w2), num_keys=2)
        first = jnp.concatenate([
            jnp.ones((1,), dtype=jnp.bool_),
            (d_s[1:] != d_s[:-1]) | (l_s[1:] != l_s[:-1])])
        run_id = jnp.cumsum(first.astype(jnp.int32)) - 1  # dense run ids < e2
        run_w = jax.ops.segment_sum(w_s, run_id, num_segments=e2)
        # representative dst/label of each run (value at its first element)
        idx = jnp.arange(e2, dtype=jnp.int32)
        first_idx = jax.ops.segment_min(jnp.where(first, idx, e2), run_id,
                                        num_segments=e2)
        first_idx = jnp.minimum(first_idx, e2 - 1)
        run_dst = d_s[first_idx]
        run_lab = l_s[first_idx]
        valid_run = idx <= run_id[-1]
        run_w = jnp.where(valid_run, run_w, big_w)
        # add self-weight as an implicit run for the node's own label: handled
        # by comparing the best neighbor run against self_weight below.
        best_w = jax.ops.segment_max(run_w, run_dst, num_segments=n_pad)
        # min label among runs achieving best weight for their dst
        is_best = run_w >= best_w[run_dst] - 1e-12
        cand_lab = jnp.where(valid_run & is_best, run_lab, jnp.int32(n_pad))
        best_lab = jax.ops.segment_min(cand_lab, run_dst, num_segments=n_pad)
        has_nb = best_lab < n_pad
        # keep own label when it's at least as heavy (self_weight) or no nbrs
        own_wins = (~has_nb) | (self_weight >= best_w) | \
                   (jnp.isclose(self_weight, best_w) & (labels <= best_lab))
        return jnp.where(own_wins, labels, best_lab)

    def body(carry):
        labels, _, it = carry
        new = one_round(labels)
        return new, jnp.any(new != labels), it + 1

    def cond(carry):
        _, changed, it = carry
        return changed & (it < max_iterations)

    labels, _, iters = jax.lax.while_loop(
        cond, body, (labels0, jnp.bool_(True), jnp.int32(0)))
    return labels, iters


def label_propagation(graph: DeviceGraph, max_iterations: int = 30,
                      self_weight: float = 0.0, directed: bool = False,
                      mesh=None):
    """Returns (community_label[:n_nodes], iterations).

    Labels are dense node indices (a community's label is one member's id).
    `mesh` (MeshContext | Mesh | int | None) routes through the
    multi-chip layer; see ops.pagerank.pagerank.
    """
    from ..parallel.mesh import resolve_mesh
    ctx = resolve_mesh(mesh)
    if ctx is not None:
        from ..parallel.analytics import label_propagation_mesh
        return label_propagation_mesh(
            graph, ctx, max_iterations=max_iterations,
            self_weight=self_weight, directed=directed)
    if directed:
        src2, dst2, w2 = graph.src_idx, graph.col_idx, graph.weights
        e2 = graph.e_pad
    else:
        src2 = jnp.concatenate([graph.src_idx, graph.col_idx])
        dst2 = jnp.concatenate([graph.col_idx, graph.src_idx])
        w2 = jnp.concatenate([graph.weights, graph.weights])
        e2 = 2 * graph.e_pad
    labels, iters = _labelprop_kernel(src2, dst2, w2, graph.n_pad, e2,
                                      max_iterations,
                                      jnp.float32(self_weight))
    return labels[:graph.n_nodes], int(iters)
