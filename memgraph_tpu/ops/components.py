"""Connected components on the semiring core.

Counterpart of the reference's WCC module
(/root/reference/mage/cpp/connectivity_module/ and query_modules/wcc.py):
WCC is a min-first semiring fixpoint over both edge directions (treating
the graph as undirected) with pointer-jumping (path halving) fused into
the epilogue, which converges in O(log n) rounds instead of O(diameter).
SCC is multi-pivot forward-backward coloring whose propagation rounds are
MASKED min-first matvecs (the masked-SpMV of GraphBLAST: edges with a
settled endpoint contribute the ⊕ identity).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from . import semiring as S
from .csr import DeviceGraph


def _wcc_epilogue(comp, acc, env, P):
    """Fused WCC epilogue: keep the smaller label, then pointer-jump
    (path halving: comp[v] = comp[comp[v]]) and the changed partial."""
    new_comp = jnp.minimum(comp, acc)
    new_comp = new_comp[new_comp]
    return new_comp, jnp.any(new_comp != comp)


def weakly_connected_components(graph: DeviceGraph,
                                max_iterations: int = 200, mesh=None,
                                comp0=None):
    """Returns (component_id[:n_nodes], iterations). Component ids are the
    minimum dense node index in each component.

    `mesh` (MeshContext | Mesh | int | None) routes through the
    multi-chip layer; see ops.pagerank.pagerank.

    `comp0` warm-starts the min-label propagation from a previous
    assignment — callers must hold the ops/delta.py monotone contract
    (only valid when the delta since that assignment ADDED edges;
    min-labels can merge components but never split them)."""
    backend, ctx = S.route_backend(graph, mesh, semiring="min_first")
    if backend == "mesh":
        from ..parallel.analytics import components_mesh
        with S.backend_extent("mesh"):
            return components_mesh(graph, ctx,
                                   max_iterations=max_iterations,
                                   comp0=comp0)
    start = np.arange(graph.n_pad, dtype=np.int32)
    if comp0 is not None:
        arr = np.asarray(comp0, dtype=np.int32)[:graph.n_nodes]
        start[:len(arr)] = arr
    comp, _, iters = S.fixpoint(
        "min_first",
        arrays={"src": graph.src_idx, "dst": graph.col_idx},
        x0=jnp.asarray(start), n_out=graph.n_pad,
        epilogue=_wcc_epilogue, max_iterations=max_iterations,
        metric="changed", direction="both")
    # one fused host transfer for the whole result tuple (MG009)
    comp_h, iters_h = jax.device_get((comp[:graph.n_nodes], iters))  # mglint: disable=MG009 — results must ship host; this IS the single fused transfer for the whole tuple
    return comp_h, int(iters_h)


@partial(jax.jit, static_argnames=("n_pad", "max_iterations"))
def _scc_round(src, dst, comp, n_pad: int, max_iterations: int):
    """One multi-pivot forward-backward coloring round over the unsettled
    subgraph (comp < 0 means unsettled).

    Correctness: with labels = own index on unsettled nodes, after min-label
    propagation fwd(v) = min index that reaches v, bwd(v) = min index v
    reaches (within the unsettled subgraph). fwd(v) == bwd(v) == m implies
    m reaches v and v reaches m ⇒ v is in m's SCC; every such set settled
    this round is exactly one whole SCC. At least the SCC of the minimum
    unsettled index settles each round, so the host outer loop terminates.
    """
    ids = jnp.arange(n_pad, dtype=jnp.int32)
    unsettled = comp < 0
    big = jnp.int32(n_pad)
    lab0 = jnp.where(unsettled, ids, big)
    # propagation only along edges with both endpoints unsettled: the
    # masked min-first matvec (masked edges contribute the sentinel)
    edge_ok = unsettled[src] & unsettled[dst]

    def propagate(a, b):
        def body(carry):
            lab, _, it = carry
            cand = S.spmv("min_first", lab, a, b, n_out=n_pad,
                          mask=edge_ok, mask_fill=big)
            new = jnp.minimum(lab, cand)
            return new, jnp.any(new != lab), it + 1

        def cond(carry):
            _, changed, it = carry
            return changed & (it < max_iterations)

        lab, _, _ = jax.lax.while_loop(
            cond, body, (lab0, jnp.bool_(True), jnp.int32(0)))
        return lab

    fwd = propagate(src, dst)
    bwd = propagate(dst, src)
    settle = unsettled & (fwd == bwd) & (fwd < big)
    return jnp.where(settle, fwd, comp)


@partial(jax.jit, static_argnames=("n_pad", "max_iterations"))
def _scc_trim(src, dst, comp, n_pad: int, max_iterations: int):
    """Trim to fixpoint: unsettled nodes with no unsettled in-neighbors or
    no unsettled out-neighbors are singleton SCCs."""
    def body(carry):
        comp, _, it = carry
        unsettled = comp < 0
        edge_ok = (unsettled[src] & unsettled[dst]).astype(jnp.int32)
        in_deg = S.edge_reduce("sum", edge_ok, dst, n_pad)
        out_deg = S.edge_reduce("sum", edge_ok, src, n_pad)
        trim = unsettled & ((in_deg == 0) | (out_deg == 0))
        new_comp = jnp.where(trim, jnp.arange(n_pad, dtype=jnp.int32), comp)
        return new_comp, jnp.any(trim), it + 1

    def cond(carry):
        _, changed, it = carry
        return changed & (it < max_iterations)

    comp, _, _ = jax.lax.while_loop(
        cond, body, (comp, jnp.bool_(True), jnp.int32(0)))
    return comp


def strongly_connected_components(graph: DeviceGraph,
                                  max_iterations: int = 1 << 30):
    """SCC labels (equal label ⇔ same SCC; label = min dense index in SCC).

    Multi-pivot FW-BW coloring with trimming; the outer loop runs on the
    host, each round jitted on device. Guaranteed ≥1 SCC settles per round.
    max_iterations bounds the *inner* propagation loops; the default is
    effectively unbounded because correctness requires running each
    propagation to its fixpoint (a C-node cycle needs C rounds).
    """
    n_pad = graph.n_pad
    comp = jnp.where(jnp.arange(n_pad, dtype=jnp.int32) < graph.n_nodes,
                     jnp.int32(-1), jnp.arange(n_pad, dtype=jnp.int32))
    while True:
        comp = _scc_trim(graph.src_idx, graph.col_idx, comp, n_pad,
                         max_iterations)
        if not bool(jnp.any(comp < 0)):
            break
        before = comp
        comp = _scc_round(graph.src_idx, graph.col_idx, comp, n_pad,
                          max_iterations)
        if not bool(jnp.any(comp < 0)):
            break
        if bool(jnp.all(comp == before)):  # safety: no progress → stop
            comp = jnp.where(comp < 0, jnp.arange(n_pad, dtype=jnp.int32),
                             comp)
            break
    return np.asarray(comp[:graph.n_nodes])
