"""Connected components on TPU.

Counterpart of the reference's WCC module
(/root/reference/mage/cpp/connectivity_module/ and query_modules/wcc.py):
iterative min-label propagation over both edge directions (treating the
graph as undirected) combined with pointer-jumping (path halving), which
converges in O(log n) rounds instead of O(diameter).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .csr import DeviceGraph


@partial(jax.jit, static_argnames=("n_pad", "max_iterations"))
def _wcc_kernel(src, dst, n_pad: int, max_iterations: int):
    comp0 = jnp.arange(n_pad, dtype=jnp.int32)

    def body(carry):
        comp, _, it = carry
        # propagate the minimum component over both edge directions
        cand_fwd = jax.ops.segment_min(comp[src], dst, num_segments=n_pad)
        cand_bwd = jax.ops.segment_min(comp[dst], src, num_segments=n_pad)
        new_comp = jnp.minimum(comp, jnp.minimum(cand_fwd, cand_bwd))
        # pointer jumping: comp[v] = comp[comp[v]] (path halving)
        new_comp = new_comp[new_comp]
        changed = jnp.any(new_comp != comp)
        return new_comp, changed, it + 1

    def cond(carry):
        _, changed, it = carry
        return changed & (it < max_iterations)

    comp, _, iters = jax.lax.while_loop(
        cond, body, (comp0, jnp.bool_(True), jnp.int32(0)))
    return comp, iters


def weakly_connected_components(graph: DeviceGraph,
                                max_iterations: int = 200, mesh=None):
    """Returns (component_id[:n_nodes], iterations). Component ids are the
    minimum dense node index in each component.

    `mesh` (MeshContext | Mesh | int | None) routes through the
    multi-chip layer; see ops.pagerank.pagerank."""
    from ..parallel.mesh import resolve_mesh
    ctx = resolve_mesh(mesh)
    if ctx is not None:
        from ..parallel.analytics import components_mesh
        return components_mesh(graph, ctx, max_iterations=max_iterations)
    comp, iters = _wcc_kernel(graph.src_idx, graph.col_idx, graph.n_pad,
                              max_iterations)
    return comp[:graph.n_nodes], int(iters)


@partial(jax.jit, static_argnames=("n_pad", "max_iterations"))
def _scc_round(src, dst, comp, n_pad: int, max_iterations: int):
    """One multi-pivot forward-backward coloring round over the unsettled
    subgraph (comp < 0 means unsettled).

    Correctness: with labels = own index on unsettled nodes, after min-label
    propagation fwd(v) = min index that reaches v, bwd(v) = min index v
    reaches (within the unsettled subgraph). fwd(v) == bwd(v) == m implies
    m reaches v and v reaches m ⇒ v is in m's SCC; every such set settled
    this round is exactly one whole SCC. At least the SCC of the minimum
    unsettled index settles each round, so the host outer loop terminates.
    """
    ids = jnp.arange(n_pad, dtype=jnp.int32)
    unsettled = comp < 0
    big = jnp.int32(n_pad)
    lab0 = jnp.where(unsettled, ids, big)
    # propagation only along edges with both endpoints unsettled
    edge_ok = unsettled[src] & unsettled[dst]

    def propagate(a, b):
        def body(carry):
            lab, _, it = carry
            vals = jnp.where(edge_ok, lab[a], big)
            cand = jax.ops.segment_min(vals, b, num_segments=n_pad)
            new = jnp.minimum(lab, cand)
            return new, jnp.any(new != lab), it + 1

        def cond(carry):
            _, changed, it = carry
            return changed & (it < max_iterations)

        lab, _, _ = jax.lax.while_loop(
            cond, body, (lab0, jnp.bool_(True), jnp.int32(0)))
        return lab

    fwd = propagate(src, dst)
    bwd = propagate(dst, src)
    settle = unsettled & (fwd == bwd) & (fwd < big)
    return jnp.where(settle, fwd, comp)


@partial(jax.jit, static_argnames=("n_pad", "max_iterations"))
def _scc_trim(src, dst, comp, n_pad: int, max_iterations: int):
    """Trim to fixpoint: unsettled nodes with no unsettled in-neighbors or
    no unsettled out-neighbors are singleton SCCs."""
    def body(carry):
        comp, _, it = carry
        unsettled = comp < 0
        edge_ok = (unsettled[src] & unsettled[dst]).astype(jnp.int32)
        in_deg = jax.ops.segment_sum(edge_ok, dst, num_segments=n_pad)
        out_deg = jax.ops.segment_sum(edge_ok, src, num_segments=n_pad)
        trim = unsettled & ((in_deg == 0) | (out_deg == 0))
        new_comp = jnp.where(trim, jnp.arange(n_pad, dtype=jnp.int32), comp)
        return new_comp, jnp.any(trim), it + 1

    def cond(carry):
        _, changed, it = carry
        return changed & (it < max_iterations)

    comp, _, _ = jax.lax.while_loop(
        cond, body, (comp, jnp.bool_(True), jnp.int32(0)))
    return comp


def strongly_connected_components(graph: DeviceGraph,
                                  max_iterations: int = 1 << 30):
    """SCC labels (equal label ⇔ same SCC; label = min dense index in SCC).

    Multi-pivot FW-BW coloring with trimming; the outer loop runs on the
    host, each round jitted on device. Guaranteed ≥1 SCC settles per round.
    max_iterations bounds the *inner* propagation loops; the default is
    effectively unbounded because correctness requires running each
    propagation to its fixpoint (a C-node cycle needs C rounds).
    """
    import numpy as np
    n_pad = graph.n_pad
    comp = jnp.where(jnp.arange(n_pad, dtype=jnp.int32) < graph.n_nodes,
                     jnp.int32(-1), jnp.arange(n_pad, dtype=jnp.int32))
    while True:
        comp = _scc_trim(graph.src_idx, graph.col_idx, comp, n_pad,
                         max_iterations)
        if not bool(jnp.any(comp < 0)):
            break
        before = comp
        comp = _scc_round(graph.src_idx, graph.col_idx, comp, n_pad,
                          max_iterations)
        if not bool(jnp.any(comp < 0)):
            break
        if bool(jnp.all(comp == before)):  # safety: no progress → stop
            comp = jnp.where(comp < 0, jnp.arange(n_pad, dtype=jnp.int32),
                             comp)
            break
    return np.asarray(comp[:graph.n_nodes])
