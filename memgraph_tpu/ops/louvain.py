"""Louvain community detection (modularity maximization).

Counterpart of /root/reference/mage/cpp/community_detection_module/ (Louvain
via grappolo) and cugraph_module/algorithms/louvain.cu. Host implementation
over the exported COO arrays: local-move phase with modularity gain, then
graph aggregation, repeated until modularity converges. The label-propagation
module (labelprop.py) covers the massively-parallel regime; Louvain is the
quality reference.
"""

from __future__ import annotations

from collections import defaultdict

import numpy as np

from .csr import DeviceGraph


def louvain(graph: DeviceGraph, max_levels: int = 10,
            min_gain: float = 1e-7, seed: int = 0):
    """Returns (community[:n_nodes] np.int64, modularity float).

    Treats the graph as undirected (weights symmetrized), standard Louvain.
    """
    n = graph.n_nodes
    if n == 0:
        return np.zeros(0, dtype=np.int64), 0.0
    src = np.asarray(graph.src_idx)[:graph.n_edges].astype(np.int64)
    dst = np.asarray(graph.col_idx)[:graph.n_edges].astype(np.int64)
    w = np.asarray(graph.weights)[:graph.n_edges].astype(np.float64)

    # symmetrize
    s = np.concatenate([src, dst])
    d = np.concatenate([dst, src])
    ww = np.concatenate([w, w])

    mapping = np.arange(n, dtype=np.int64)  # node -> final community
    cur_n = n

    for _level in range(max_levels):
        comm, gain = _one_level(cur_n, s, d, ww, min_gain, seed)
        mapping = comm[mapping]
        if gain < min_gain:
            break
        # aggregate: communities become nodes
        uniq, new_ids = np.unique(comm, return_inverse=True)
        mapping = new_ids[mapping]
        s2 = new_ids[s]
        d2 = new_ids[d]
        # merge parallel edges
        keys = s2 * len(uniq) + d2
        order = np.argsort(keys, kind="stable")
        keys_s = keys[order]
        w_s = ww[order]
        boundaries = np.concatenate([[True], keys_s[1:] != keys_s[:-1]])
        group_ids = np.cumsum(boundaries) - 1
        agg_w = np.zeros(group_ids[-1] + 1 if len(group_ids) else 0)
        np.add.at(agg_w, group_ids, w_s)
        first_idx = np.nonzero(boundaries)[0]
        s = keys_s[first_idx] // len(uniq)
        d = keys_s[first_idx] % len(uniq)
        ww = agg_w
        cur_n = len(uniq)
        if cur_n <= 1:
            break

    modularity = _modularity(n, np.concatenate([src, dst]),
                             np.concatenate([dst, src]),
                             np.concatenate([w, w]), mapping)
    # compact ids
    _, compact = np.unique(mapping, return_inverse=True)
    return compact.astype(np.int64), float(modularity)


def _one_level(n, s, d, w, min_gain, seed):
    """Local-move phase; returns (community assignment, total gain)."""
    m2 = w.sum()  # = 2m for the symmetrized graph
    if m2 <= 0:
        return np.arange(n, dtype=np.int64), 0.0
    # adjacency as python dicts for the move loop
    neighbors: list[dict] = [defaultdict(float) for _ in range(n)]
    k = np.zeros(n)  # weighted degree
    for si, di, wi in zip(s, d, w):
        if si != di:
            neighbors[si][di] += wi
        k[si] += wi
    comm = np.arange(n, dtype=np.int64)
    comm_tot = k.copy()  # total degree per community
    rng = np.random.default_rng(seed)
    order = rng.permutation(n)
    total_gain = 0.0
    improved = True
    rounds = 0
    while improved and rounds < 20:
        improved = False
        rounds += 1
        for v in order:
            cv = comm[v]
            kv = k[v]
            # weights to neighboring communities
            links: dict[int, float] = defaultdict(float)
            for u, wu in neighbors[v].items():
                links[comm[u]] += wu
            comm_tot[cv] -= kv
            best_c, best_gain = cv, 0.0
            base = links.get(cv, 0.0) - comm_tot[cv] * kv / m2
            for c, wc in links.items():
                if c == cv:
                    continue
                gain = (wc - comm_tot[c] * kv / m2) - base
                if gain > best_gain:
                    best_gain, best_c = gain, c
            comm[v] = best_c
            comm_tot[best_c] += kv
            if best_c != cv and best_gain > min_gain:
                improved = True
                total_gain += best_gain
    return comm, total_gain


def _modularity(n, s, d, w, comm):
    m2 = w.sum()
    if m2 <= 0:
        return 0.0
    internal = w[comm[s] == comm[d]].sum()
    k = np.zeros(n)
    np.add.at(k, s, w)
    comm_deg = defaultdict(float)
    for v in range(n):
        comm_deg[comm[v]] += k[v]
    expected = sum(x * x for x in comm_deg.values()) / (m2 * m2)
    return internal / m2 - expected
