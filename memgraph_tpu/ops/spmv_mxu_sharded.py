"""Multi-chip MXU PageRank: the Benes/MXU kernel sharded over the edge
axis of a device mesh.

Decomposition (1D edge partition, scaling-book style):
  - every shard holds ~E/P edges (round-robin assignment, which splits
    each node's edge bundle evenly across shards and so divides the
    per-src-row gather heights — R_G and the Benes net shrink ~P-fold);
  - node LABELINGS (out/in) are global and shared, so every shard's
    extract phase produces a partial accumulator in the SAME in-label
    dense layout (n_drows_p x 128);
  - one `psum` over the 'edges' mesh axis combines the partial
    accumulators — the only per-iteration communication, O(N) floats
    riding ICI;
  - the node-relabel Benes, dangling correction, and damping update run
    replicated on every device (O(N) work, no comms).

Per-iteration cost model: t_iter(P) = t_edge(E/P) + t_allreduce(N) +
t_node(N); measured numbers in docs/scaling_model_r4.md.

Reference analog: the reference scales pagerank via cuGraph/NCCL
(mage/cpp/cugraph_module/algorithms/pagerank.cu); this is the
TPU-native equivalent — XLA collectives over a jax.sharding.Mesh, not
message passing.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import numpy as np

from .spmv_mxu import (
    LANES, SG_ROWS, R_C, K_C,
    _benes_apply_rolls, _ceil_to, _edge_perm_masks, _gather_layout,
    _global_labelings, _node_relabel_masks, _scatter_layout,
    _unpack_mask_words,
)


@dataclass
class ShardedMXUPlan:
    n_nodes: int
    n_shards: int
    G: int
    R_G: int                   # uniform across shards (max)
    net_log2: int              # shared net size (max over shards)
    C: int                     # uniform extract chunks (max, padded)
    W: int
    n_drows_p: int
    # --- per-shard, stacked on axis 0 ---
    rowid: np.ndarray          # (P, G, R_G) int16
    mult: np.ndarray           # (P, G, R_G, LANES) f32
    masks_packed: np.ndarray   # (P, stages, N/8) uint8
    run_k: np.ndarray          # (P, C, R_C) int16
    win_oh: np.ndarray         # (P, C, W) f32
    # --- global (replicated) ---
    out_relabel: np.ndarray
    in_relabel: np.ndarray
    valid_out: np.ndarray
    dangling_out: np.ndarray
    node_net_log2: int
    node_masks_packed: np.ndarray


def _assign_shards(src, dst, n_nodes, n_shards):
    """Edge -> shard assignment. MXU-plan padding is governed by each
    128-node row's MAX per-shard degree, so balance matters more than
    randomness: the native balanced bipartite edge coloring (Euler
    splits) gives every node floor(d/P)..ceil(d/P) edges per shard on
    BOTH endpoints; the numpy fallback balances the src side only
    (round-robin within each node's edge bundle)."""
    levels = int(np.log2(n_shards))
    if (1 << levels) == n_shards and levels > 0:
        from .native import balanced_edge_color_native
        try:
            shard = balanced_edge_color_native(src, dst, n_nodes, n_nodes,
                                               levels)
        except Exception:  # noqa: BLE001 — fall back on any native issue
            import logging
            logging.getLogger(__name__).debug(
                "native edge coloring failed; numpy round-robin "
                "fallback", exc_info=True)
            shard = None
        if shard is not None:
            return shard.astype(np.int64)
    # fallback: seq-within-src-bucket round robin
    order = np.argsort(src, kind="stable")
    seq = np.arange(len(src)) - np.concatenate(
        ([0], np.cumsum(np.bincount(src, minlength=n_nodes))))[src[order]]
    shard = np.empty(len(src), dtype=np.int64)
    shard[order] = seq % n_shards
    return shard


def build_sharded_plan(src: np.ndarray, dst: np.ndarray,
                       weights: Optional[np.ndarray], n_nodes: int,
                       n_shards: int) -> ShardedMXUPlan:
    """Per-shard gather/scatter layouts + Benes nets under SHARED global
    node labelings, padded uniform so they stack on a leading shard axis."""
    src = np.asarray(src, dtype=np.int64)
    dst = np.asarray(dst, dtype=np.int64)
    E = len(src)
    w = (np.ones(E, dtype=np.float64) if weights is None
         else np.asarray(weights, dtype=np.float64))

    (G, relab_out, relab_in, inv_wsum, valid_out, dangling_out,
     n_drows_p, _wsum) = _global_labelings(src, dst, w, n_nodes)

    shard_of = _assign_shards(src, dst, n_nodes, n_shards)
    subs = [(src[shard_of == p], dst[shard_of == p], w[shard_of == p])
            for p in range(n_shards)]

    # first pass: per-shard required R_G (gather rows), to fix a uniform
    # R_G before computing positions (positions depend on R_G)
    req_R_G = []
    for s_src, _, _ in subs:
        u = relab_out[s_src]
        deg_l = np.bincount(u, minlength=G * SG_ROWS * LANES)
        H = deg_l.reshape(-1, LANES).max(axis=1)
        req_R_G.append(max(1, int(H.reshape(G, SG_ROWS).sum(axis=1).max())))
    R_G = max(req_R_G)

    gathers = [_gather_layout(s_src, s_w, relab_out, inv_wsum, G,
                              force_R_G=R_G)
               for s_src, _, s_w in subs]
    scatters = [_scatter_layout(s_dst, relab_in, n_drows_p)
                for _, s_dst, _ in subs]

    C = max(sc[0] for sc in scatters)
    W = n_drows_p // K_C
    net = max(G * R_G * LANES,
              max(sc[4] for sc in scatters) * LANES, 2)
    net_log2 = int(np.ceil(np.log2(net)))

    rowid = np.stack([g[1] for g in gathers])
    mult = np.stack([g[2] for g in gathers])
    masks = np.stack([
        _edge_perm_masks(g[3], sc[3], net_log2)
        for g, sc in zip(gathers, scatters)])
    # pad extract chunks to uniform C: padding rows are run_k == -1
    # (never extracted) and all-zero win_oh rows (no window contribution)
    run_k = np.full((n_shards, C, R_C), -1, dtype=np.int16)
    win_oh = np.zeros((n_shards, C, W), dtype=np.float32)
    for p, sc in enumerate(scatters):
        run_k[p, :sc[0]] = sc[1]
        win_oh[p, :sc[0]] = sc[2]

    node_flat = G * SG_ROWS * LANES
    node_net_log2, node_masks_packed = _node_relabel_masks(
        relab_out, relab_in, node_flat, n_drows_p)

    return ShardedMXUPlan(
        n_nodes=n_nodes, n_shards=n_shards, G=G, R_G=R_G,
        net_log2=net_log2, C=C, W=W, n_drows_p=n_drows_p,
        rowid=rowid, mult=mult, masks_packed=masks,
        run_k=run_k, win_oh=win_oh,
        out_relabel=relab_out, in_relabel=relab_in,
        valid_out=valid_out, dangling_out=dangling_out,
        node_net_log2=node_net_log2, node_masks_packed=node_masks_packed)


def make_sharded_pagerank_kernel(plan: ShardedMXUPlan, mesh,
                                 axis_name: str = "edges",
                                 route_dtype=None):
    """Returns jitted fn(rank0_flat, damping, max_iter, tol) ->
    (rank_flat, err, iters), with the edge phase sharded over
    `axis_name` of `mesh` and one psum per iteration.

    rank vectors are replicated, flat in OUT labeling.

    `mesh` may be a jax Mesh (with `axis_name` naming the edge axis) or
    a parallel.mesh.MeshContext (its axis wins)."""
    from ..parallel.mesh import MeshContext
    if isinstance(mesh, MeshContext):
        axis_name = mesh.axis
        mesh = mesh.mesh
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    # version-gated central resolution (parallel/mesh.py): warns once on
    # the jax-0.4 check_rep=False fallback instead of silently degrading
    from ..parallel.mesh import shard_map_fn
    shard_map = shard_map_fn()
    from .blob import pack_blob, unblob
    from ..utils.jax_cache import ensure_compile_cache
    ensure_compile_cache()

    if route_dtype is None:
        route_dtype = jnp.bfloat16
    if plan.n_shards != int(mesh.shape[axis_name]):
        raise ValueError(
            f"plan built for {plan.n_shards} shards but mesh axis "
            f"'{axis_name}' has {mesh.shape[axis_name]} devices")

    G, R_G, C, W = plan.G, plan.R_G, plan.C, plan.W
    Pn = plan.n_shards
    N_net = 1 << plan.net_log2
    N_nn = 1 << plan.node_net_log2
    node_flat = G * SG_ROWS * LANES
    n_f = float(plan.n_nodes)

    # per-shard payload: identical segment layout for every shard, so one
    # pack per shard stacks into a (P, words) blob sharded on axis 0
    shard_blobs = []
    segs = None
    for p in range(Pn):
        b, segs = pack_blob({
            "masks": ("bits", plan.masks_packed[p]),
            "mult": plan.mult[p],
            "rowid_i32": plan.rowid[p].astype(np.int32),
            "run_k_i32": plan.run_k[p].astype(np.int32),
            "win_oh": plan.win_oh[p],
        })
        shard_blobs.append(b)
    blob_np = np.stack(shard_blobs)
    gblob_np, gsegs = pack_blob({
        "node_masks": ("bits", plan.node_masks_packed),
        "valid": plan.valid_out,
        "dangling": plan.dangling_out,
    })

    live_big = [bool(plan.masks_packed[:, s].any())
                for s in range(plan.masks_packed.shape[1])]
    live_node = [bool(row.any()) for row in plan.node_masks_packed]

    def edge_phase(rank_flat, dv):
        rank_planes = rank_flat.reshape(G, SG_ROWS, LANES)
        T = jnp.einsum("grw,gwl->grl", dv["oh"], rank_planes,
                       preferred_element_type=jnp.float32)
        contrib = (T * dv["mult"]).astype(route_dtype).reshape(-1, LANES)
        x2 = jnp.zeros((N_net // LANES, LANES), route_dtype
                       ).at[:contrib.shape[0]].set(contrib)
        x2 = _benes_apply_rolls(x2, dv["masks2"], plan.net_log2,
                                live_stages=live_big)
        xc = x2[:C * R_C].reshape(C, R_C, LANES)
        per_chunk = jnp.einsum("cik,cil->ckl", dv["ohe"], xc,
                               preferred_element_type=jnp.float32)
        accw = jnp.einsum("cw,ckl->wkl", dv["win_oh"], per_chunk,
                          preferred_element_type=jnp.float32)
        return accw.reshape(-1, LANES)            # (n_drows_p, 128)

    def node_phase(acc_in2, rank_flat, gdv, d):
        from .semiring import pagerank_update
        xa = jnp.zeros((N_nn // LANES, LANES), jnp.float32
                       ).at[:acc_in2.shape[0]].set(acc_in2)
        acc_out = _benes_apply_rolls(
            xa, gdv["node_masks2"], plan.node_net_log2,
            live_stages=live_node).reshape(-1)[:node_flat]
        dm = jnp.sum(rank_flat * gdv["dangling"])
        # shared damping-update formula (ops/semiring.py): the sharded
        # MXU kernel applies the SAME epilogue as every other backend
        return pagerank_update(acc_out, dm, gdv["valid"], n_f, d)

    def shard_fn(blob_row, gblob, rank0, damping, tol, max_iterations):
        blob = blob_row[0]
        iota_sg = jnp.arange(SG_ROWS, dtype=jnp.int32)
        iota_kc = jnp.arange(K_C, dtype=jnp.int32)
        rowid = unblob(blob, segs, "rowid_i32")
        run_k = unblob(blob, segs, "run_k_i32")
        mwords = unblob(blob, segs, "masks")
        dv = dict(
            oh=(rowid[:, :, None] == iota_sg[None, None, :]
                ).astype(jnp.float32),
            ohe=((run_k[:, :, None] == iota_kc[None, None, :])
                 & (run_k[:, :, None] >= 0)).astype(route_dtype),
            mult=unblob(blob, segs, "mult"),
            win_oh=unblob(blob, segs, "win_oh"),
            masks2=_unpack_mask_words(mwords, plan.net_log2),
        )
        gdv = dict(
            node_masks2=_unpack_mask_words(
                unblob(gblob, gsegs, "node_masks"), plan.node_net_log2),
            valid=unblob(gblob, gsegs, "valid"),
            dangling=unblob(gblob, gsegs, "dangling"),
        )

        def body(carry):
            rank, _, it = carry
            acc_in2 = edge_phase(rank, dv)
            acc_in2 = jax.lax.psum(acc_in2, axis_name)
            new_rank = node_phase(acc_in2, rank, gdv, damping)
            err = jnp.sum(jnp.abs(new_rank - rank))
            return new_rank, err, it + 1

        def cond(carry):
            _, err, it = carry
            return (err > tol) & (it < max_iterations)

        return jax.lax.while_loop(
            cond, body, (rank0, jnp.float32(jnp.inf), jnp.int32(0)))

    Pr = P()
    sharded = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(axis_name, None), Pr, Pr, Pr, Pr, Pr),
        out_specs=(Pr, Pr, Pr))
    jitted = jax.jit(sharded, static_argnums=(5,))

    blob_dev = jax.device_put(blob_np, NamedSharding(mesh, P(axis_name,
                                                             None)))
    gblob_dev = jax.device_put(gblob_np, NamedSharding(mesh, Pr))

    def run(rank0, damping, max_iterations, tol):
        return jitted(blob_dev, gblob_dev, rank0,
                      jnp.float32(damping), jnp.float32(tol),
                      int(max_iterations))

    return run


def pagerank_mxu_sharded(src, dst, weights, n_nodes, mesh,
                         axis_name: str = "edges", damping=0.85,
                         max_iterations=100, tol=1e-6,
                         plan: ShardedMXUPlan = None, route_dtype=None):
    """End-to-end sharded MXU pagerank over `mesh` (a jax Mesh or a
    MeshContext). Returns ranks in ORIGINAL node ids plus (err, iters)."""
    import jax.numpy as jnp
    from ..parallel.mesh import MeshContext
    if isinstance(mesh, MeshContext):
        axis_name = mesh.axis
        mesh = mesh.mesh
    n_shards = int(mesh.shape[axis_name])
    if plan is None:
        plan = build_sharded_plan(src, dst, weights, n_nodes, n_shards)
    # the compiled kernel caches on the plan: rebuilding it per CALL
    # retraced + recompiled the whole sharded program every invocation
    # (mglint MG008 recompile-hazard)
    cache = getattr(plan, "_kernel_cache", None)
    if cache is None:
        cache = {}
        object.__setattr__(plan, "_kernel_cache", cache)
    key = (axis_name, tuple(d.id for d in mesh.devices.flat),
           None if route_dtype is None else str(route_dtype))
    run = cache.get(key)
    if run is None:
        run = cache[key] = make_sharded_pagerank_kernel(
            plan, mesh, axis_name, route_dtype=route_dtype)
    node_flat = plan.G * SG_ROWS * LANES
    rank0 = np.zeros(node_flat, dtype=np.float32)
    rank0[plan.out_relabel] = 1.0 / plan.n_nodes
    rank, err, iters = run(jnp.asarray(rank0), damping, max_iterations,
                           tol)
    rank = np.asarray(rank)
    return rank[plan.out_relabel], float(err), int(iters)
