"""Gather-free sparse matvec (PageRank core) built from MXU matmuls,
Benes routing, and roll-based exchanges.

Motivation (measured, docs/kernel_design_r2.md): on this TPU platform XLA
elementwise/matmul run at full speed while every gather/scatter/sort
formulation — including Pallas — is 2-3 orders of magnitude slower. This
module therefore expresses `acc[dst] += rank[src] * mult(edge)` with NO
data-dependent addressing on the device:

  1. EXPAND   — one-hot matmul multicast: per supergroup of 128 rank rows,
                T = einsum(OH(src_row), rank_planes) places rank[src] in
                every edge slot (slot lane == src & 127); multiply by the
                per-slot `mult` (weight / out-weight-sum, 0 on padding).
  2. PERMUTE  — a Benes network (ops.benes) moves every edge slot from its
                gather-layout position to its scatter-layout position via
                2*log2(N)-1 masked-exchange stages. Each stage exchanges
                partners i <-> i^d, realized as two jnp.rolls + selects on
                an (N/128, 128) layout: a row roll for d >= 128, a lane
                roll for d < 128. (The earlier reshape+flip formulation
                lowered to ~30 ms/stage at small d on this platform; rolls
                run at HBM speed at every distance.)
  3. REDUCE + EXTRACT — scatter layout keeps each destination's edges
                contiguous within its lane (lane == dst & 127, runs
                aligned per dst-row); a full-run one-hot matmul per chunk
                sums every run directly on the MXU (no roll-tree passes):
                per_chunk[c,k,l] = sum_i OH(run slot)[c,i,k] * x[c,i,l],
                then a small window one-hot sums chunks into aligned
                windows.
  4. RELABEL  — a second (node-sized) Benes converts the accumulator from
                the in-degree-sorted labeling (which keeps scatter padding
                small under skew) to the out-degree-sorted labeling (which
                keeps gather padding small), ready for the next EXPAND.

All routing/masks/layouts are precomputed on the host at export time and
shipped once; per-iteration device work is elementwise + MXU + rolls only.

Reference analog: the sparse power iteration of
/root/reference/mage/cpp/pagerank_module/ and the cuGraph CUDA variant
(mage/cpp/cugraph_module/algorithms/pagerank.cu); the formulation here is
TPU-native rather than scatter/gather-based.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from functools import partial
from typing import Optional

import numpy as np

from .benes import benes_stage_distances, route_packed

LANES = 128
SG_ROWS = 128          # rank rows per supergroup (=> 16384 nodes)
R_C = 256              # scatter rows per extract chunk
K_C = 256              # dst-rows per aligned output window


def _ceil_to(x: int, m: int) -> int:
    return -(-x // m) * m


@dataclass
class MXUPlan:
    n_nodes: int
    # --- gather (out-degree labeling) ---
    G: int                     # supergroups
    R_G: int                   # gather rows per supergroup (padded uniform)
    rowid: np.ndarray          # (G, R_G) int16: src row within supergroup
    mult: np.ndarray           # (G, R_G, LANES) f32: w/wsum, 0 = pad slot
    out_relabel: np.ndarray    # (n_nodes,) original -> out-label id
    valid_out: np.ndarray      # (G*SG_ROWS*LANES,) f32 1.0 for real nodes
    dangling_out: np.ndarray   # same shape: 1.0 where out-wsum == 0
    # --- big Benes ---
    net_log2: int
    masks_packed: np.ndarray   # (stages, N/8) uint8
    # --- scatter/extract (in-degree labeling) ---
    C: int                     # extract chunks (total rows = C * R_C)
    run_k: np.ndarray          # (C, R_C) int16: window slot of the row's
    #                            dst block (dr % K_C), -1 on padding rows
    win_oh: np.ndarray         # (C, W) f32 one-hot chunk->window
    W: int
    in_relabel: np.ndarray     # (n_nodes,) original -> in-label id
    # --- node relabel Benes (in-label acc -> out-label acc) ---
    node_net_log2: int
    node_masks_packed: np.ndarray
    # per-node out-weight sums (ORIGINAL ids) — the delta-refresh path
    # rescales stale w/wsum multipliers with these (see DeltaPlan)
    wsum: np.ndarray = None


def _relabel_by(key: np.ndarray, stripe_groups: int = 0) -> np.ndarray:
    """relabel[node] = position when sorted by key desc (stable).

    With stripe_groups=G, rows of 128 consecutive sorted nodes (degree-
    homogeneous, so each row's max ~ its mean) are dealt round-robin
    across the G supergroups: row j lands at supergroup j%G, slot j//G.
    This balances per-supergroup row totals so the uniform R_G padding of
    the batched expand einsum stays ~1x instead of concentrating all the
    tall rows in supergroup 0."""
    order = np.argsort(-key, kind="stable")
    n = len(key)
    pos = np.arange(n)
    if stripe_groups:
        j, lane = pos >> 7, pos & 127
        r2 = (j % stripe_groups) * SG_ROWS + j // stripe_groups
        pos = r2 * LANES + lane
    relab = np.empty(n, dtype=np.int64)
    relab[order] = pos
    return relab


def _gather_layout(src, w, relab_out, inv_wsum, G, force_R_G=None):
    """Gather-side layout for an edge subset under a FIXED out labeling.

    Returns (R_G, rowid, mult, gp_by_edge): rows per supergroup, the
    src-row id of every gather row, the per-slot multiplier (w/wsum,
    0 on padding), and each edge's flat gather position (edge order).

    force_R_G: use this (>= required) row count so plans for different
    edge shards stack into uniform arrays.
    """
    E = len(src)
    node_flat = G * SG_ROWS * LANES
    u = relab_out[src]
    srow, slane = u >> 7, u & 127
    # per-edge count per labeled node (LOCAL to this subset)
    deg_l = np.bincount(u, minlength=node_flat)
    # rows per src-row block = max subset-degree among its 128 nodes
    H_out = deg_l.reshape(-1, LANES).max(axis=1)              # per src-row
    rows_per_sg = H_out.reshape(G, SG_ROWS).sum(axis=1)
    R_G = max(1, int(rows_per_sg.max()))
    if force_R_G is not None:
        if force_R_G < R_G:
            raise ValueError(f"force_R_G={force_R_G} < required {R_G}")
        R_G = force_R_G
    # base row (within supergroup) of each src-row block
    base_in_sg = np.zeros(G * SG_ROWS, dtype=np.int64)
    for g in range(G):
        base_in_sg[g * SG_ROWS:(g + 1) * SG_ROWS] = \
            np.cumsum(H_out[g * SG_ROWS:(g + 1) * SG_ROWS]) \
            - H_out[g * SG_ROWS:(g + 1) * SG_ROWS]
    # per-edge sequence within its (node) bucket, in (src) sorted order
    order_g = np.argsort(u, kind="stable")
    seq = np.arange(E) - np.concatenate(([0], np.cumsum(
        deg_l)))[u[order_g]]
    sg = srow[order_g] >> 7
    grow = base_in_sg[srow[order_g]] + seq                    # row in sg
    gather_pos = ((sg * R_G + grow) * LANES + slane[order_g])

    rowid = np.zeros((G, R_G), dtype=np.int16)
    for g in range(G):
        rs = H_out[g * SG_ROWS:(g + 1) * SG_ROWS]
        rowid[g, :rs.sum()] = np.repeat(np.arange(SG_ROWS, dtype=np.int16),
                                        rs)
    mult = np.zeros((G, R_G, LANES), dtype=np.float32)
    mult_flat = mult.reshape(-1)
    mult_flat[gather_pos] = (w * inv_wsum[src])[order_g]
    gp_by_edge = np.empty(E, dtype=np.int64)
    gp_by_edge[order_g] = gather_pos
    return R_G, rowid, mult, gp_by_edge


def _scatter_layout(dst, relab_in, n_drows_p):
    """Scatter/extract layout for an edge subset under a FIXED in
    labeling. n_drows_p: dst-row count padded to whole K_C windows.

    Returns (C, run_k, win_oh, sp_by_edge, R_total).
    """
    E = len(dst)
    W = n_drows_p // K_C
    v = relab_in[dst]
    drow, dlane = v >> 7, v & 127
    cnt = np.bincount(v, minlength=n_drows_p * LANES)
    H_in = np.maximum(cnt.reshape(-1, LANES).max(axis=1), 1)[:n_drows_p]

    # chunked row allocation: the full-run one-hot extract sums EVERY row
    # of a dst block, so every row of a block must live in chunks claimed
    # by the block's window — pad to a chunk boundary whenever a block
    # would otherwise share a chunk with a different window.
    base2 = np.zeros(n_drows_p, dtype=np.int64)
    chunk_win: dict = {}
    rows_acc = 0
    for dr in range(n_drows_p):
        wdw = dr // K_C
        c = rows_acc // R_C
        if chunk_win.get(c, wdw) != wdw:
            rows_acc = _ceil_to(rows_acc, R_C)
        base2[dr] = rows_acc
        end = rows_acc + int(H_in[dr])
        for cc in range(rows_acc // R_C, (end - 1) // R_C + 1):
            chunk_win[cc] = wdw
        rows_acc = end
    R_total = _ceil_to(rows_acc, R_C)
    C = R_total // R_C

    win_of_chunk = np.zeros(C, dtype=np.int64)
    for c in range(C):
        win_of_chunk[c] = chunk_win.get(
            c, win_of_chunk[c - 1] if c else 0)
    win_oh = np.zeros((C, W), dtype=np.float32)
    win_oh[np.arange(C), win_of_chunk] = 1.0

    # run_k[c, i] = window slot (dr % K_C) of the block owning row
    # c*R_C + i, or -1 for padding rows. Distinct blocks sharing a chunk
    # share its window, so slots cannot collide.
    block_of_row = np.full(R_total, -1, dtype=np.int64)
    for dr in range(n_drows_p):
        block_of_row[base2[dr]:base2[dr] + H_in[dr]] = dr
    run_k = np.full(R_total, -1, dtype=np.int16)
    owned = block_of_row >= 0
    run_k[owned] = (block_of_row[owned] % K_C).astype(np.int16)
    run_k = run_k.reshape(C, R_C)

    # per-edge scatter position
    order_s = np.argsort(v, kind="stable")
    seq2 = np.arange(E) - np.concatenate(([0], np.cumsum(
        cnt)))[v[order_s]]
    scatter_pos = ((base2[drow[order_s]] + seq2) * LANES + dlane[order_s])
    sp_by_edge = np.empty(E, dtype=np.int64)
    sp_by_edge[order_s] = scatter_pos
    return C, run_k, win_oh, sp_by_edge, R_total


def _edge_perm_masks(gp_by_edge, sp_by_edge, net_log2):
    """Route the big Benes: scatter position <- gather position for every
    edge, identity-completed on free slots (all of which carry zeros)."""
    N_net = 1 << net_log2
    perm = np.full(N_net, -1, dtype=np.int64)
    perm[sp_by_edge] = gp_by_edge
    free_out = np.flatnonzero(perm < 0)
    used_in = np.zeros(N_net, dtype=bool)
    used_in[gp_by_edge] = True
    perm[free_out] = np.flatnonzero(~used_in)
    return route_packed(perm)


def _node_relabel_masks(relab_out, relab_in, node_flat, n_drows_p):
    """Route the node Benes: in-label dense acc -> out labeling."""
    acc_flat_len = n_drows_p * LANES
    node_net_log2 = int(np.ceil(np.log2(max(node_flat, acc_flat_len, 2))))
    N_nn = 1 << node_net_log2
    nperm = np.full(N_nn, -1, dtype=np.int64)
    nperm[relab_out] = relab_in                # out position <- in position
    free_out = np.flatnonzero(nperm < 0)
    used_in = np.zeros(N_nn, dtype=bool)
    used_in[relab_in] = True
    nperm[free_out] = np.flatnonzero(~used_in)
    return node_net_log2, route_packed(nperm)


def _global_labelings(src, dst, w, n_nodes):
    """Degree stats + out/in relabelings shared by all shards."""
    out_deg = np.bincount(src, minlength=n_nodes)
    in_deg = np.bincount(dst, minlength=n_nodes)
    wsum = np.bincount(src, weights=w, minlength=n_nodes)
    n_rows = _ceil_to(n_nodes, LANES) // LANES
    G = _ceil_to(n_rows, SG_ROWS) // SG_ROWS
    relab_out = _relabel_by(out_deg, stripe_groups=G)
    relab_in = _relabel_by(in_deg)
    inv_wsum = np.where(wsum > 0, 1.0 / np.maximum(wsum, 1e-300), 0.0)
    node_flat = G * SG_ROWS * LANES
    valid_out = np.zeros(node_flat, dtype=np.float32)
    valid_out[relab_out] = 1.0
    dangling_out = np.zeros(node_flat, dtype=np.float32)
    dangling_out[relab_out[wsum <= 0]] = 1.0
    n_drows = _ceil_to(n_nodes, LANES) // LANES
    n_drows_p = _ceil_to(n_drows, K_C)                        # whole windows
    return (G, relab_out, relab_in, inv_wsum, valid_out, dangling_out,
            n_drows_p, wsum)


def build_plan(src: np.ndarray, dst: np.ndarray,
               weights: Optional[np.ndarray], n_nodes: int,
               normalize: bool = True) -> MXUPlan:
    """Precompute layouts + routing for the MXU semiring-SpMV kernel.

    normalize=True bakes w / out-weight-sum multipliers (the column-
    stochastic matrix PageRank iterates); normalize=False bakes plain w
    (the raw A^T other plus-times algorithms — katz — iterate)."""
    src = np.asarray(src, dtype=np.int64)
    dst = np.asarray(dst, dtype=np.int64)
    E = len(src)
    w = (np.ones(E, dtype=np.float64) if weights is None
         else np.asarray(weights, dtype=np.float64))

    (G, relab_out, relab_in, inv_wsum, valid_out, dangling_out,
     n_drows_p, wsum) = _global_labelings(src, dst, w, n_nodes)
    if not normalize:
        inv_wsum = np.ones_like(inv_wsum)

    R_G, rowid, mult, gp_by_edge = _gather_layout(
        src, w, relab_out, inv_wsum, G)
    C, run_k, win_oh, sp_by_edge, R_total = _scatter_layout(
        dst, relab_in, n_drows_p)

    net = max(G * R_G * LANES, R_total * LANES, 2)
    net_log2 = int(np.ceil(np.log2(net)))
    masks_packed = _edge_perm_masks(gp_by_edge, sp_by_edge, net_log2)

    node_flat = G * SG_ROWS * LANES
    node_net_log2, node_masks_packed = _node_relabel_masks(
        relab_out, relab_in, node_flat, n_drows_p)

    return MXUPlan(
        n_nodes=n_nodes, G=G, R_G=R_G, rowid=rowid, mult=mult,
        out_relabel=relab_out, valid_out=valid_out,
        dangling_out=dangling_out,
        net_log2=net_log2, masks_packed=masks_packed,
        C=C, run_k=run_k, win_oh=win_oh, W=n_drows_p // K_C,
        in_relabel=relab_in,
        node_net_log2=node_net_log2, node_masks_packed=node_masks_packed,
        wsum=wsum)


# ---------------------------------------------------------------------------
# delta plans: O(changed-edges) refresh instead of a full replan
# ---------------------------------------------------------------------------

@dataclass
class DeltaPlan:
    """Side-plan covering edges added/removed since the base plan.

    The base plan keeps serving its (now stale) edges; this plan routes
    only the delta, and two correction vectors make the combination
    exact:
      - scale_out: rank is pre-scaled by wsum_old/wsum_new per source
        before the BASE expand, so stale w/wsum_old multipliers become
        w/wsum_new;
      - removed edges ride the delta net with NEGATIVE multipliers
        -w/wsum_new, cancelling the base contribution exactly;
      - dangling_out replaces the base vector (nodes may gain/lose all
        out-edges).
    Valid only while the node set is unchanged. Analog of the
    reference's online pagerank keeping incremental state
    (/root/reference/query_modules/pagerank_module/
    pagerank_online_module.cpp:17-20) — here the increment is a
    TPU-routable side-net rather than a CPU ordering.
    """
    n_delta: int
    R_G: int
    rowid: np.ndarray          # (G, R_G) int16
    mult: np.ndarray           # (G, R_G, LANES) f32 (signed)
    net_log2: int
    masks_packed: np.ndarray
    C: int
    run_k: np.ndarray
    win_oh: np.ndarray
    scale_out: np.ndarray      # (node_flat,) f32
    dangling_out: np.ndarray   # (node_flat,) f32 — replaces base's
    wsum: np.ndarray           # updated per-node out-weight sums


def build_delta_plan(base: MXUPlan,
                     add_src, add_dst, add_w=None,
                     rem_src=None, rem_dst=None, rem_w=None,
                     bucket: bool = True) -> DeltaPlan:
    """Build the O(delta) side-plan. All ids are ORIGINAL node ids and
    must be < base.n_nodes (node additions require a full replan).

    bucket=True pads R_G / C to powers of two so growing deltas reuse
    the same compiled kernel shapes (recompiles only on bucket jumps)."""
    if base.wsum is None:
        raise ValueError("base plan predates delta support (no wsum)")
    n = base.n_nodes
    add_src = np.asarray(add_src, dtype=np.int64)
    add_dst = np.asarray(add_dst, dtype=np.int64)
    a_w = (np.ones(len(add_src)) if add_w is None
           else np.asarray(add_w, dtype=np.float64))
    rem_src = np.asarray(
        rem_src if rem_src is not None else [], dtype=np.int64)
    rem_dst = np.asarray(
        rem_dst if rem_dst is not None else [], dtype=np.int64)
    r_w = (np.ones(len(rem_src)) if rem_w is None
           else np.asarray(rem_w, dtype=np.float64))
    for arr in (add_src, add_dst, rem_src, rem_dst):
        if len(arr) and (arr.min() < 0 or arr.max() >= n):
            raise ValueError("delta references nodes outside the base plan")

    wsum_new = base.wsum.copy()
    if len(add_src):
        wsum_new += np.bincount(add_src, weights=a_w, minlength=n)
    if len(rem_src):
        wsum_new -= np.bincount(rem_src, weights=r_w, minlength=n)
    wsum_new[np.abs(wsum_new) < 1e-9] = 0.0     # cancel fp dust at zero
    inv_new = np.where(wsum_new > 0, 1.0 / np.maximum(wsum_new, 1e-300),
                       0.0)

    d_src = np.concatenate([add_src, rem_src])
    d_dst = np.concatenate([add_dst, rem_dst])
    d_w = np.concatenate([a_w, -r_w])           # removals route negative

    G = base.G
    n_drows_p = base.W * K_C
    R_G, rowid, mult, gp = _gather_layout(d_src, d_w, base.out_relabel,
                                          inv_new, G)
    if bucket and R_G & (R_G - 1):
        R_G = 1 << R_G.bit_length()
        R_G, rowid, mult, gp = _gather_layout(
            d_src, d_w, base.out_relabel, inv_new, G, force_R_G=R_G)
    C, run_k, win_oh, sp, R_total = _scatter_layout(
        d_dst, base.in_relabel, n_drows_p)
    if bucket and C & (C - 1):
        # pad with dead chunks: run_k=-1 rows extract nothing, zero
        # win_oh rows route no window
        C_pad = 1 << C.bit_length()
        run_k = np.concatenate(
            [run_k, np.full((C_pad - C, R_C), -1, dtype=run_k.dtype)])
        win_oh = np.concatenate(
            [win_oh, np.zeros((C_pad - C, win_oh.shape[1]),
                              dtype=win_oh.dtype)])
        C, R_total = C_pad, C_pad * R_C
    net = max(G * R_G * LANES, R_total * LANES, 2)
    net_log2 = int(np.ceil(np.log2(net)))
    masks_packed = _edge_perm_masks(gp, sp, net_log2)

    node_flat = G * SG_ROWS * LANES
    # exact-1 scale for untouched nodes: only rescale where wsum changed
    changed = wsum_new != base.wsum
    scale_nodes = np.ones(n, dtype=np.float64)
    scale_nodes[changed] = base.wsum[changed] * inv_new[changed]
    scale_out = np.zeros(node_flat, dtype=np.float32)
    scale_out[base.out_relabel] = scale_nodes
    dangling_out = np.zeros(node_flat, dtype=np.float32)
    dangling_out[base.out_relabel[wsum_new <= 0]] = 1.0

    return DeltaPlan(
        n_delta=len(d_src), R_G=R_G, rowid=rowid, mult=mult,
        net_log2=net_log2, masks_packed=masks_packed,
        C=C, run_k=run_k, win_oh=win_oh,
        scale_out=scale_out, dangling_out=dangling_out, wsum=wsum_new)


# ---------------------------------------------------------------------------
# device kernel
# ---------------------------------------------------------------------------

def _unpack_mask_words(words, net_log2):
    """(stages, W) uint32 words -> (stages, N/128, 128) bool (flat if
    N < 128). Word layout per blob.unpack_bit_words."""
    from .blob import unpack_bit_words
    N = 1 << net_log2
    bits = unpack_bit_words(words, N)
    if N >= LANES:
        return bits.reshape(words.shape[0], N // LANES, LANES)
    return bits


def _benes_apply_rolls(x2, masks2, net_log2, live_stages=None):
    """Roll-based Benes. x2 is (N/128, 128) (or flat (N,) when N < 128).

    Stage distance d exchanges partners i <-> i^d (masks are symmetric:
    mask[i] == mask[i^d], see ops/benes.py). For i with bit d clear the
    partner is i+d == roll(x, -d)[i]; bit set, i-d == roll(x, +d)[i] —
    so the exchanged view is a two-roll select on a static bit pattern,
    a row roll when d >= 128 and a lane roll when d < 128. Rolls run at
    HBM bandwidth on this platform at every distance, unlike the
    reshape+flip lowering (docs/kernel_design_r2.md).

    live_stages: optional bool sequence; stages whose masks are all-zero
    (no swaps routed through that level) are skipped at trace time."""
    import jax.numpy as jnp
    flat = x2.ndim == 1
    for s, d in enumerate(benes_stage_distances(net_log2)):
        if live_stages is not None and not live_stages[s]:
            continue
        if flat:
            bit = ((jnp.arange(x2.shape[0]) // d) & 1) == 1
            sw = jnp.where(bit, jnp.roll(x2, d), jnp.roll(x2, -d))
        elif d >= LANES:
            e = d // LANES
            bit = ((jnp.arange(x2.shape[0]) // e) & 1) == 1
            sw = jnp.where(bit[:, None], jnp.roll(x2, e, axis=0),
                           jnp.roll(x2, -e, axis=0))
        else:
            bit = ((jnp.arange(LANES) // d) & 1) == 1
            sw = jnp.where(bit[None, :], jnp.roll(x2, d, axis=1),
                           jnp.roll(x2, -d, axis=1))
        x2 = jnp.where(masks2[s], sw, x2)
    return x2


def pagerank_mxu_epilogue(rank, acc, env, P):
    """The fused PageRank update + convergence partial, applied to the
    MXU matvec's out-labeled accumulator (shared formula:
    semiring.pagerank_update)."""
    import jax.numpy as jnp
    from .semiring import pagerank_update
    dm = jnp.sum(rank * env["dangling"])
    new_rank = pagerank_update(acc, dm, env["valid"], env["n_f"],
                               P["damping"])
    err = jnp.sum(jnp.abs(new_rank - rank))
    return new_rank, err


def make_semiring_kernel(plan: MXUPlan, epilogue, route_dtype=None,
                         delta: "DeltaPlan" = None,
                         x0_default: str = "uniform"):
    """Returns jitted fn(x0_flat, params, max_iter, tol) ->
    (x_flat, err, iters); state vectors are flat in OUT labeling,
    length G*SG_ROWS*LANES.  The semiring-parameterized generalization
    of the pagerank-only r5 kernel: the matvec (expand -> Benes route ->
    MXU reduce/extract -> node relabel) is fixed ⊕ = sum machinery —
    the one-hot extract matmul IS the sum — while the fused
    ``epilogue(x, acc, env, params) -> (new_x, err)`` supplies the
    algorithm (env carries valid / dangling / n_f; params is a dict of
    traced scalars).  ⊗ is baked into the plan's multipliers
    (build_plan(normalize=...)).

    route_dtype: dtype for the per-edge contributions through the big
    Benes (the dominant HBM traffic). bfloat16 halves it; sums still
    accumulate in f32 on the MXU, so each contribution carries one
    0.4%-relative rounding — validated to preserve exact top-100 order
    on the 10M-edge bench graph. float32 is the exact path.

    delta: optional DeltaPlan — per iteration the base expand reads
    rank pre-scaled by delta.scale_out, the delta edges route through
    their own (small) net, and both accumulators sum before the node
    relabel. Exact for edge additions AND removals.

    x0_default: the on-device start when x0 is None — "uniform"
    (valid/n, pagerank) or "zeros" (katz)."""
    import jax
    import jax.numpy as jnp
    from ..utils.jax_cache import ensure_compile_cache
    ensure_compile_cache()

    if route_dtype is None:
        route_dtype = (jnp.bfloat16 if os.environ.get(
            "MEMGRAPH_TPU_ROUTE_DTYPE", "f32") == "bf16" else jnp.float32)

    G, R_G, C, W = plan.G, plan.R_G, plan.C, plan.W
    N_net = 1 << plan.net_log2
    N_nn = 1 << plan.node_net_log2
    node_flat = G * SG_ROWS * LANES
    n_f = float(plan.n_nodes)

    # Benes backend: the pallas 3-pass formulation cuts per-stage HBM
    # round trips ~16x (measured 13.4 -> 3.7 ms/apply at 2^24, r5); the
    # XLA roll path remains for CPU (tests / virtual meshes) and tiny nets
    benes_mode = os.environ.get("MEMGRAPH_TPU_BENES", "auto")
    use_pallas = (benes_mode == "pallas"
                  or (benes_mode == "auto"
                      and jax.default_backend() not in ("cpu",)
                      and plan.net_log2 >= 12
                      and plan.node_net_log2 >= 12))

    from .blob import pack_blob, unblob
    blob_arrays = {
        "mult": plan.mult.astype(np.float32),
        "rowid_i32": plan.rowid.astype(np.int32),
        "run_k_i32": plan.run_k.astype(np.int32),
        "win_oh": plan.win_oh.astype(np.float32),
        "valid": plan.valid_out.astype(np.float32),
        "dangling": plan.dangling_out.astype(np.float32),
    }
    if use_pallas:
        from .benes_pallas import build_pallas_masks
        big_spec, big_mid, big_out = build_pallas_masks(
            plan.masks_packed, plan.net_log2)
        node_spec, node_mid, node_out = build_pallas_masks(
            plan.node_masks_packed, plan.node_net_log2)
        blob_arrays["pb_big_mid"] = big_mid
        if big_out is not None:
            blob_arrays["pb_big_out"] = big_out
        blob_arrays["pb_node_mid"] = node_mid
        if node_out is not None:
            blob_arrays["pb_node_out"] = node_out
    else:
        blob_arrays["masks"] = ("bits", plan.masks_packed)
        blob_arrays["node_masks"] = ("bits", plan.node_masks_packed)
    if delta is not None:
        N_dnet = 1 << delta.net_log2
        blob_arrays["d_mult"] = delta.mult.astype(np.float32)
        blob_arrays["d_rowid_i32"] = delta.rowid.astype(np.int32)
        blob_arrays["d_run_k_i32"] = delta.run_k.astype(np.int32)
        blob_arrays["d_win_oh"] = delta.win_oh.astype(np.float32)
        blob_arrays["d_scale"] = delta.scale_out.astype(np.float32)
        # the delta's dangling vector REPLACES the base one
        blob_arrays["dangling"] = delta.dangling_out.astype(np.float32)
        use_pallas_delta = use_pallas and delta.net_log2 >= 12
        if use_pallas_delta:
            d_spec, d_mid, d_out = build_pallas_masks(
                delta.masks_packed, delta.net_log2)
            blob_arrays["pb_d_mid"] = d_mid
            if d_out is not None:
                blob_arrays["pb_d_out"] = d_out
        else:
            blob_arrays["d_masks"] = ("bits", delta.masks_packed)
        live_delta = [bool(r.any()) for r in delta.masks_packed]
    blob_np, segs = pack_blob(blob_arrays)

    def _unblob(blob, name):
        return unblob(blob, segs, name)

    @jax.jit
    def prepare(blob):
        """One compiled pass: slice, bitcast, unpack masks, build one-hots."""
        iota_sg = jnp.arange(SG_ROWS, dtype=jnp.int32)
        iota_kc = jnp.arange(K_C, dtype=jnp.int32)
        # keep int32 on device: narrow conversions compile slowly here
        rowid = _unblob(blob, "rowid_i32")
        run_k = _unblob(blob, "run_k_i32")
        oh = (rowid[:, :, None] == iota_sg[None, None, :]
              ).astype(jnp.float32)                        # (G, R_G, 128)
        ohe = ((run_k[:, :, None] == iota_kc[None, None, :])
               & (run_k[:, :, None] >= 0)).astype(route_dtype)
        dv = dict(
            oh=oh,
            mult=_unblob(blob, "mult"),
            valid=_unblob(blob, "valid"),
            dangling=_unblob(blob, "dangling"),
            ohe=ohe,
            win_oh=_unblob(blob, "win_oh"),
        )
        if use_pallas:
            for name in ("pb_big_mid", "pb_big_out", "pb_node_mid",
                         "pb_node_out"):
                if name in segs:
                    dv[name] = _unblob(blob, name)
        else:
            dv["masks2"] = _unpack_mask_words(_unblob(blob, "masks"),
                                              plan.net_log2)
            dv["node_masks2"] = _unpack_mask_words(
                _unblob(blob, "node_masks"), plan.node_net_log2)
        if delta is not None:
            d_rowid = _unblob(blob, "d_rowid_i32")
            d_run_k = _unblob(blob, "d_run_k_i32")
            dv["d_oh"] = (d_rowid[:, :, None] == iota_sg[None, None, :]
                          ).astype(jnp.float32)
            dv["d_ohe"] = ((d_run_k[:, :, None] == iota_kc[None, None, :])
                           & (d_run_k[:, :, None] >= 0)).astype(route_dtype)
            dv["d_mult"] = _unblob(blob, "d_mult")
            dv["d_win_oh"] = _unblob(blob, "d_win_oh")
            dv["d_scale"] = _unblob(blob, "d_scale")
            if use_pallas_delta:
                dv["pb_d_mid"] = _unblob(blob, "pb_d_mid")
                if "pb_d_out" in segs:
                    dv["pb_d_out"] = _unblob(blob, "pb_d_out")
            else:
                dv["d_masks2"] = _unpack_mask_words(
                    _unblob(blob, "d_masks"), delta.net_log2)
        return dv

    blob_dev = jax.device_put(blob_np)
    # all-zero-mask stages route nothing: skip them at trace time
    live_big = [bool(row.any()) for row in plan.masks_packed]
    live_node = [bool(row.any()) for row in plan.node_masks_packed]

    def _route_big(x2, dv):
        if use_pallas:
            from .benes_pallas import benes_apply_pallas
            return benes_apply_pallas(x2, dv["pb_big_mid"],
                                      dv.get("pb_big_out"), big_spec)
        return _benes_apply_rolls(x2, dv["masks2"], plan.net_log2,
                                  live_stages=live_big)

    def _route_node(xa, dv):
        if use_pallas:
            from .benes_pallas import benes_apply_pallas
            return benes_apply_pallas(xa, dv["pb_node_mid"],
                                      dv.get("pb_node_out"), node_spec)
        return _benes_apply_rolls(xa, dv["node_masks2"],
                                  plan.node_net_log2,
                                  live_stages=live_node)

    def _route_delta(x2, dv):
        if use_pallas_delta:
            from .benes_pallas import benes_apply_pallas
            return benes_apply_pallas(x2, dv["pb_d_mid"],
                                      dv.get("pb_d_out"), d_spec)
        return _benes_apply_rolls(x2, dv["d_masks2"], delta.net_log2,
                                  live_stages=live_delta)

    def _delta_acc(rank_planes, dv):
        """Expand + route + extract the delta edges; (W, K_C, 128) f32."""
        T = jnp.einsum("grw,gwl->grl", dv["d_oh"], rank_planes,
                       preferred_element_type=jnp.float32)
        contrib = (T * dv["d_mult"]).astype(route_dtype).reshape(-1, LANES)
        N_rows = max((1 << delta.net_log2) // LANES, 1)
        x2 = jnp.zeros((N_rows, LANES), route_dtype
                       ).at[:contrib.shape[0]].set(contrib)
        x2 = _route_delta(x2, dv)
        xc = x2[:delta.C * R_C].reshape(delta.C, R_C, LANES)
        per_chunk = jnp.einsum("cik,cil->ckl", dv["d_ohe"], xc,
                               preferred_element_type=jnp.float32)
        return jnp.einsum("cw,ckl->wkl", dv["d_win_oh"], per_chunk,
                          preferred_element_type=jnp.float32)

    def matvec(rank_flat, dv):
        """⊕ = sum semiring matvec in OUT labeling (expand -> route ->
        MXU reduce/extract -> node relabel); ⊗ is baked into mult."""
        # base expand reads rank pre-scaled so stale w/wsum_old
        # multipliers become w/wsum_new (exact; see DeltaPlan)
        base_in = (rank_flat * dv["d_scale"] if delta is not None
                   else rank_flat)
        rank_planes = base_in.reshape(G, SG_ROWS, LANES)
        T = jnp.einsum("grw,gwl->grl", dv["oh"], rank_planes,
                       preferred_element_type=jnp.float32)
        contrib = (T * dv["mult"]).astype(route_dtype
                                          ).reshape(-1, LANES)
        x2 = jnp.zeros((N_net // LANES, LANES), route_dtype
                       ).at[:contrib.shape[0]].set(contrib)
        x2 = _route_big(x2, dv)
        xc = x2[:C * R_C].reshape(C, R_C, LANES)
        # full-run one-hot reduce+extract on the MXU (no roll-tree);
        # f32 accumulation regardless of the routed dtype
        per_chunk = jnp.einsum("cik,cil->ckl", dv["ohe"], xc,
                               preferred_element_type=jnp.float32)
        accw = jnp.einsum("cw,ckl->wkl", dv["win_oh"], per_chunk,
                          preferred_element_type=jnp.float32)
        if delta is not None:
            accw = accw + _delta_acc(
                rank_flat.reshape(G, SG_ROWS, LANES), dv)
        acc_in2 = accw.reshape(-1, LANES)                  # (W*K_C, 128)
        xa = jnp.zeros((N_nn // LANES, LANES), jnp.float32
                       ).at[:acc_in2.shape[0]].set(acc_in2)
        return _route_node(xa, dv).reshape(-1)[:node_flat]

    def _loop(x0, params, max_iterations, tol, dv):
        env = {"valid": dv["valid"], "dangling": dv["dangling"],
               "n_f": n_f}

        def body(carry):
            x, _, it = carry
            acc_out = matvec(x, dv)
            # FUSED-PAGERANK: the update + convergence partial run on
            # the accumulator inside the loop body — no extra HBM trip
            new_x, err = epilogue(x, acc_out, env, params)
            return new_x, err, it + 1

        def cond(carry):
            _, err, it = carry
            return (err > tol) & (it < max_iterations)

        return jax.lax.while_loop(
            cond, body, (x0, jnp.float32(jnp.inf), jnp.int32(0)))

    # prepare + loop fused into ONE jit call: the cold path is then a
    # single blob transfer + one compile-cached dispatch + one readback
    # (each extra RPC costs ~0.5-1s through the tunnel)
    @partial(jax.jit, static_argnames=("max_iterations",))
    def run_impl(blob, x0, params, max_iterations: int, tol):
        return _loop(x0, params, max_iterations, tol, prepare(blob))

    @partial(jax.jit, static_argnames=("max_iterations",))
    def run_impl_default(blob, params, max_iterations: int, tol):
        dv = prepare(blob)
        if x0_default == "zeros":
            x0 = jnp.zeros_like(dv["valid"])
        else:
            x0 = dv["valid"] * jnp.float32(1.0 / n_f)
        return _loop(x0, params, max_iterations, tol, dv)

    def run(x0, params, max_iterations, tol):
        """x0 = None starts from the on-device default state (uniform
        distribution or zeros; saves the x0 host->device transfer)."""
        if x0 is None:
            return run_impl_default(blob_dev, params, max_iterations, tol)
        return run_impl(blob_dev, x0, params, max_iterations, tol)

    # mgxla contract-checker hooks: the inner jitted programs + the
    # device blob, so tools/mgxla can abstractly .lower() the compiled
    # artifact (f64 / host-callback / collective contracts) without
    # executing a matvec
    run.jitted = run_impl
    run.jitted_default = run_impl_default
    run.blob = blob_dev
    return run


def make_pagerank_kernel(plan: MXUPlan, route_dtype=None,
                         delta: "DeltaPlan" = None):
    """Back-compat pagerank entry: the semiring kernel with the fused
    pagerank epilogue.  Returns jitted fn(rank0_flat, damping,
    max_iter, tol) -> (rank_flat, err, iters)."""
    run = make_semiring_kernel(plan, epilogue=pagerank_mxu_epilogue,
                               route_dtype=route_dtype, delta=delta,
                               x0_default="uniform")

    def run_pr(rank0, damping, max_iterations, tol):
        return run(rank0, {"damping": damping}, max_iterations, tol)

    return run_pr


def pagerank_mxu(src, dst, weights, n_nodes, damping=0.85,
                 max_iterations=100, tol=1e-6, plan: MXUPlan = None):
    """End-to-end: build plan (or reuse), run kernel, return ranks in
    ORIGINAL node ids plus (err, iters)."""
    import jax.numpy as jnp
    if plan is None:
        plan = build_plan(src, dst, weights, n_nodes)
    run = make_pagerank_kernel(plan)
    rank, err, iters = run(None, jnp.float32(damping),
                           max_iterations, jnp.float32(tol))
    rank = np.asarray(rank)
    return rank[plan.out_relabel], float(err), int(iters)


# ---------------------------------------------------------------------------
# plan persistence (bench reuse: routing a 10M-edge graph costs ~35s host-side)
# ---------------------------------------------------------------------------

_PLAN_VERSION = 4


def save_plan(plan: MXUPlan, path: str) -> None:
    np.savez_compressed(
        path, version=_PLAN_VERSION, n_nodes=plan.n_nodes, G=plan.G,
        R_G=plan.R_G, rowid=plan.rowid, mult=plan.mult,
        out_relabel=plan.out_relabel, valid_out=plan.valid_out,
        dangling_out=plan.dangling_out, net_log2=plan.net_log2,
        masks_packed=plan.masks_packed, C=plan.C, run_k=plan.run_k,
        win_oh=plan.win_oh, W=plan.W, in_relabel=plan.in_relabel,
        node_net_log2=plan.node_net_log2,
        node_masks_packed=plan.node_masks_packed,
        wsum=plan.wsum if plan.wsum is not None else np.zeros(0))


def load_plan(path: str) -> Optional[MXUPlan]:
    try:
        z = np.load(path)
        if int(z["version"]) != _PLAN_VERSION:
            return None
        return MXUPlan(
            n_nodes=int(z["n_nodes"]), G=int(z["G"]), R_G=int(z["R_G"]),
            rowid=z["rowid"], mult=z["mult"], out_relabel=z["out_relabel"],
            valid_out=z["valid_out"], dangling_out=z["dangling_out"],
            net_log2=int(z["net_log2"]), masks_packed=z["masks_packed"],
            C=int(z["C"]), run_k=z["run_k"],
            win_oh=z["win_oh"], W=int(z["W"]), in_relabel=z["in_relabel"],
            node_net_log2=int(z["node_net_log2"]),
            node_masks_packed=z["node_masks_packed"],
            wsum=z["wsum"] if z["wsum"].size else None)
    except Exception:  # noqa: BLE001 — any cache damage means "rebuild"
        import logging
        logging.getLogger(__name__).debug(
            "MXU plan cache at %s unreadable; rebuilding", path,
            exc_info=True)
        return None
