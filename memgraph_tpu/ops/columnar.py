"""Columnar property snapshots for intra-query parallel execution.

TPU-native counterpart of the reference's intra-query parallelism
(/root/reference/src/query/plan/operator.hpp:1925-2273 ScanAllParallel*/
AggregateParallel and plan/rewrite/parallel_rewrite.hpp): instead of a
work-stealing thread pool iterating record batches, the scan's property
accesses are exported ONCE into dense typed columns (the same
export-and-cache contract as the CSR snapshot in ops/csr.py), and
filter+aggregate lower onto whole-column vectorized kernels.

Execution runs on host numpy rather than the chip: predicate/aggregate
semantics need exact int64 (vertex ids and integer properties exceed
f32's 2^24 mantissa, and this jax build keeps x64 disabled), and a
column pass is a single streaming sweep — the layout here is
device-ready (dense values + present bitmask) for f32-safe offload, but
the win over the row-at-a-time Volcano path (~100x at 10M rows) comes
from the columnar representation itself.

Columns:
  kind "int"   int64 values  (all_int aggregates stay integers)
  kind "float" float64 values
  kind "bool"  int8 0/1
  kind "str"   int32 dictionary codes + vocab (equality only)
  kind "other" present mask only (count(prop) works; predicates do not)
Absent properties and deleted rows are absent from `present`.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

import numpy as np

from ..storage.common import IsolationLevel


@dataclass
class Column:
    kind: str                      # int | float | bool | str | other
    values: np.ndarray | None      # typed values (None for "other")
    present: np.ndarray            # (n,) bool
    vocab: dict | None = None      # str value -> code, for kind "str"
    big: bool = False              # int column holds |v| > 2^53: a float
    #                                rhs comparison would lose exactness
    mixed: bool = False            # float column coerced from int+float
    #                                values: original per-row types lost


@dataclass
class ColumnarSnapshot:
    n: int
    gids: np.ndarray               # (n,) int64 storage gids
    columns: dict = field(default_factory=dict)   # prop name -> Column


def _classify(values: list, present: np.ndarray) -> Column:
    """Pick the narrowest uniform kind covering all present values."""
    kinds = set()
    for v, p in zip(values, present):
        if not p:
            continue
        if isinstance(v, bool):
            kinds.add("bool")
        elif isinstance(v, int):
            kinds.add("int")
        elif isinstance(v, float):
            kinds.add("float")
        elif isinstance(v, str):
            kinds.add("str")
        else:
            kinds.add("other")
        if len(kinds) > 1 and kinds != {"int", "float"}:
            return Column("other", None, present)
    if not kinds:
        return Column("other", None, present)
    if kinds == {"int"}:
        if any(p and not -2**63 <= v < 2**63
               for v, p in zip(values, present)):
            return Column("other", None, present)   # beyond int64
        out = np.zeros(len(values), dtype=np.int64)
        for i, (v, p) in enumerate(zip(values, present)):
            if p:
                out[i] = v
        big = any(p and not -2**53 <= v <= 2**53
                  for v, p in zip(values, present))
        return Column("int", out, present, big=big)
    if kinds <= {"int", "float"}:
        # mixed numerics coerce to f64; an int beyond 2^53 would lose
        # exactness (= / < would diverge from the row path) -> opt out
        if any(p and isinstance(v, int) and not -2**53 <= v <= 2**53
               for v, p in zip(values, present)):
            return Column("other", None, present)
        out = np.zeros(len(values), dtype=np.float64)
        for i, (v, p) in enumerate(zip(values, present)):
            if p:
                out[i] = v
        return Column("float", out, present, mixed=("int" in kinds))
    if kinds == {"bool"}:
        out = np.zeros(len(values), dtype=np.int8)
        for i, (v, p) in enumerate(zip(values, present)):
            if p:
                out[i] = 1 if v else 0
        return Column("bool", out, present)
    if kinds == {"str"}:
        vocab: dict = {}
        out = np.zeros(len(values), dtype=np.int32)
        for i, (v, p) in enumerate(zip(values, present)):
            if p:
                out[i] = vocab.setdefault(v, len(vocab))
        return Column("str", out, present, vocab)
    return Column("other", None, present)


def export_columns(accessor, label: str | None,
                   props: tuple[str, ...], view,
                   abort_check=None) -> ColumnarSnapshot:
    """One sweep over the accessor's visible vertices of `label` (or all),
    materializing the requested properties as typed columns.
    abort_check (if given) is called periodically so TERMINATE/timeout
    interrupts the sweep like the row path's per-row check."""
    storage = accessor.storage
    prop_ids = []
    for p in props:
        prop_ids.append(storage.property_mapper.maybe_name_to_id(p))

    gids: list[int] = []
    raw: list[list] = [[] for _ in props]
    if label is not None:
        lid = storage.label_mapper.maybe_name_to_id(label)
        it = (accessor.vertices_by_label(lid, view) if lid is not None
              else iter(()))
    else:
        it = accessor.vertices(view)
    for i, va in enumerate(it):
        if abort_check is not None and (i & 0x1FFF) == 0:
            abort_check()
        gids.append(va.gid)
        pd = va.properties(view)
        for j, pid in enumerate(prop_ids):
            raw[j].append(None if pid is None else pd.get(pid))

    n = len(gids)
    snap = ColumnarSnapshot(n=n, gids=np.asarray(gids, dtype=np.int64))
    for j, p in enumerate(props):
        vals = raw[j]
        present = np.fromiter((v is not None for v in vals), dtype=bool,
                              count=n)
        snap.columns[p] = _classify(vals, present)
    return snap


@dataclass
class EdgeSnapshot:
    """Columnar edge table: one row per visible edge, with endpoint gids,
    type ids and requested edge-property columns (the edge analog of
    ColumnarSnapshot; feeds the columnar Expand collapse)."""
    n: int
    gids: np.ndarray               # (n,) int64 edge gids
    src: np.ndarray                # (n,) int64 from-vertex gids
    dst: np.ndarray                # (n,) int64 to-vertex gids
    type_ids: np.ndarray           # (n,) int32 edge type ids
    columns: dict = field(default_factory=dict)   # prop name -> Column


def export_edges(accessor, props: tuple[str, ...], view,
                 abort_check=None) -> EdgeSnapshot:
    """One MVCC-correct sweep over the accessor's visible edges."""
    storage = accessor.storage
    prop_ids = [storage.property_mapper.maybe_name_to_id(p) for p in props]
    gids: list[int] = []
    src: list[int] = []
    dst: list[int] = []
    types: list[int] = []
    raw: list[list] = [[] for _ in props]
    for i, ea in enumerate(accessor.edges(view)):
        if abort_check is not None and (i & 0x1FFF) == 0:
            abort_check()
        gids.append(ea.gid)
        src.append(ea.from_vertex().gid)
        dst.append(ea.to_vertex().gid)
        types.append(ea.edge_type)
        pd = ea.properties(view)
        for j, pid in enumerate(prop_ids):
            raw[j].append(None if pid is None else pd.get(pid))
    n = len(gids)
    snap = EdgeSnapshot(
        n=n, gids=np.asarray(gids, dtype=np.int64),
        src=np.asarray(src, dtype=np.int64),
        dst=np.asarray(dst, dtype=np.int64),
        type_ids=np.asarray(types, dtype=np.int32))
    for j, p in enumerate(props):
        vals = raw[j]
        present = np.fromiter((v is not None for v in vals), dtype=bool,
                              count=n)
        snap.columns[p] = _classify(vals, present)
    return snap


class ColumnarCache:
    """Per-storage cache keyed by (topology_version, label, props).

    A cached snapshot is only valid for transactions whose visible state
    IS the latest committed state: reads from a transaction with its own
    uncommitted writes, or a snapshot-isolation transaction started
    before the latest commit, bypass the cache (fresh, uncached build) —
    same staleness contract as ops/csr.py GraphCache, tightened for MVCC.
    """

    def __init__(self) -> None:
        import weakref
        self._lock = threading.Lock()
        self._cache: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()

    def _cacheable(self, accessor) -> bool:
        if getattr(accessor, "fine_grained", None) is not None:
            # label-restricted view: never share via the plain cache
            return False
        txn = accessor.txn
        if txn is None:
            return True
        if getattr(txn, "deltas", None):
            return False
        # READ_COMMITTED / READ_UNCOMMITTED resolve visibility against the
        # *live* latest commit ts, so a commit landing mid-sweep yields a
        # mixed snapshot that must never be shared under a version key.
        if txn.isolation is not IsolationLevel.SNAPSHOT_ISOLATION:
            return False
        return txn.effective_start_ts() >= accessor.storage.latest_commit_ts()

    def _get_cached(self, accessor, key, props, export_fn, abort_check):
        """Shared cache skeleton for vertex and edge snapshots: per
        (version, key) entries with column-level sharing — a later query
        needing extra properties sweeps only the missing columns (row
        order is stable within a version, so columns from separate
        sweeps align; verified by row count). The version is captured by
        the CALLER before its freshness check, embedded in `key`."""
        storage = accessor.storage
        with self._lock:
            per = self._cache.get(storage)
            entry = per.get(key) if per else None
        missing = tuple(p for p in props
                        if entry is None or p not in entry.columns)
        if missing or entry is None:
            snap = export_fn(missing)
            if storage.topology_version != key[0]:
                # topology moved mid-sweep: the sweep may be mixed — never
                # store it; serve this caller a fresh full (uncached) build
                if missing != props:
                    snap = export_fn(props)
                return snap
            with self._lock:
                per = self._cache.get(storage) or {}
                per = {k: v for k, v in per.items() if k[0] == key[0]}
                entry = per.get(key)
                if entry is None:
                    entry = snap
                elif entry.n == snap.n:
                    for p in missing:
                        entry.columns.setdefault(p, snap.columns[p])
                else:   # should not happen within one version
                    entry = snap
                per[key] = entry
                self._cache[storage] = per
        return entry

    def get(self, accessor, label: str | None, props: tuple[str, ...],
            view, abort_check=None) -> ColumnarSnapshot:
        # capture the version BEFORE the freshness check: a commit landing
        # between _cacheable() and the key read would otherwise let a
        # pre-commit sweep be stored under the post-commit version
        version = accessor.storage.topology_version
        if not self._cacheable(accessor):
            return export_columns(accessor, label, props, view,
                                  abort_check)
        return self._get_cached(
            accessor, (version, label), props,
            lambda ps: export_columns(accessor, label, ps, view,
                                      abort_check), abort_check)

    def get_edges(self, accessor, props: tuple[str, ...], view,
                  abort_check=None) -> EdgeSnapshot:
        """Edge-table analog of get(): cached under (version, _EDGES_KEY)
        with the same MVCC staleness contract."""
        version = accessor.storage.topology_version
        if not self._cacheable(accessor):
            return export_edges(accessor, props, view, abort_check)
        return self._get_cached(
            accessor, (version, _EDGES_KEY), props,
            lambda ps: export_edges(accessor, ps, view, abort_check),
            abort_check)


_EDGES_KEY = "\x00edges"   # sentinel: no label can collide (labels never contain NUL)

COLUMNAR_CACHE = ColumnarCache()
