"""Benes permutation-network routing.

TPU-native data movement: XLA on this platform runs elementwise/matmul at
full speed but any gather/scatter/sort formulation is ~1000x slower (see
docs/kernel_design_r2.md). A fixed permutation is therefore applied as a
Benes network: 2*log2(N)-1 stages of masked aligned swaps, each stage a
pure reshape + reverse + select — all VPU-friendly XLA ops.

The routing (which pairs swap at each stage) is computed once on the host
by the classic looping algorithm: at each level, elements paired at the
input stage (i, i+N/2) must route through different halves, as must
elements paired at the output stage; the union of the two pairings is a
disjoint set of even cycles, 2-colored by walking.

Reference analog: none — the reference (CUDA/C++) scatters directly; this
component exists because the TPU-idiomatic formulation of "scatter" is
"route, then reduce along lanes".

Stage application semantics (shared by numpy + jax implementations):
  stage s has block size B_s and distance d_s = B_s/2;
  y = x.reshape(N//B_s, 2, d_s); out = where(mask_s, y[:, ::-1, :], y)
with mask_s stored flat (N,) and mask_s[i] == mask_s[i ^ d_s].
"""

from __future__ import annotations

import numpy as np


def benes_stage_distances(n_log2: int) -> list[int]:
    """Distances of the 2n-1 stages, in application order."""
    down = [1 << k for k in range(n_log2 - 1, 0, -1)]
    return down + [1] + down[::-1]


def benes_route(perm: np.ndarray) -> list[np.ndarray]:
    """Compute swap masks realizing `perm` (N power of two).

    Semantics: applying the stages to input x yields y with
    y[i] = x[perm[i]] (i.e. perm is in "gather" form: output position i
    receives the element from input position perm[i]).

    Returns a list of (N,) bool masks, one per stage, in application
    order. Pure python/numpy; for large N use the native C++ router
    (ops.native.benes_route_native) which implements the same algorithm.
    """
    perm = np.asarray(perm, dtype=np.int64)
    N = len(perm)
    if N & (N - 1) or N < 2:
        raise ValueError("benes_route requires power-of-two N >= 2")
    n = N.bit_length() - 1
    n_stages = 2 * n - 1
    masks = [np.zeros(N, dtype=bool) for _ in range(n_stages)]

    # Work in "forward" form: element at input p must reach output q.
    # perm is gather form: out[i] = in[perm[i]]  =>  forward[perm[i]] = i.
    if len(np.unique(perm)) != N or perm.min() < 0 or perm.max() >= N:
        raise ValueError("perm is not a bijection on [0, N)")
    forward = np.empty(N, dtype=np.int64)
    forward[perm] = np.arange(N)

    # (level, block_start, forward-subperm) work items; level k has block
    # size N >> k. Stage index for the IN stage of level k is k; the OUT
    # stage is n_stages - 1 - k. Level n-1 (blocks of 2) is the middle
    # single stage.
    stack = [(0, 0, forward)]
    while stack:
        level, base, fwd = stack.pop()
        B = N >> level
        h = B >> 1
        in_stage = level
        out_stage = n_stages - 1 - level
        if B == 2:
            masks[in_stage][base:base + 2] = bool(fwd[0] == 1)
            continue

        # 2-color the pairing cycles. halves[i] = 0 (top) / 1 (bottom)
        # for the element at local input i.
        halves = np.full(B, -1, dtype=np.int8)
        inv = np.empty(B, dtype=np.int64)   # output slot -> input slot
        inv[fwd] = np.arange(B)
        for start in range(B):
            if halves[start] >= 0:
                continue
            i = start
            color = 0
            while halves[i] < 0:
                halves[i] = color
                # input partner must take the other half
                ip = i ^ h
                if halves[ip] < 0:
                    halves[ip] = color ^ 1
                # output partner of ip: element sharing ip's output pair
                op_out = fwd[ip] ^ h
                i = inv[op_out]
                color = halves[ip] ^ 1
        # IN stage masks: element at local input i goes to sub-slot i%h of
        # half halves[i]; swap iff (i < h) != (halves[i] == 0)
        loc = np.arange(B)
        swap_in = (halves == 1) == (loc < h)
        masks[in_stage][base:base + B] = swap_in
        # OUT stage masks: output o receives from half halves[inv-elem]:
        # swap iff (o < h) != (element's half == top)
        elem_at_out = inv  # output slot -> input slot of its element
        swap_out = (halves[elem_at_out] == 1) == (loc < h)
        masks[out_stage][base:base + B] = swap_out
        # Build sub-permutations (forward form, local to each half).
        sub_fwd = [np.empty(h, dtype=np.int64), np.empty(h, dtype=np.int64)]
        for i in range(B):
            hlf = halves[i]
            sub_fwd[hlf][i % h] = fwd[i] % h
        stack.append((level + 1, base, sub_fwd[0]))
        stack.append((level + 1, base + h, sub_fwd[1]))
    return masks


def benes_apply_np(x: np.ndarray, masks: list[np.ndarray]) -> np.ndarray:
    """Apply the stage masks to x (numpy reference of the jax kernel)."""
    N = len(x)
    n = N.bit_length() - 1
    dists = benes_stage_distances(n)
    out = np.asarray(x)
    for mask, d in zip(masks, dists):
        y = out.reshape(N // (2 * d), 2, d)
        sw = y[:, ::-1, :].reshape(N)
        out = np.where(mask, sw, out.reshape(N))
    return out


def route_packed(perm: np.ndarray) -> np.ndarray:
    """Bit-packed stage masks for perm: native C++ router when available
    (O(N log N), needed at 10M+ scale), python fallback otherwise."""
    from .native import benes_route_native
    try:
        packed = benes_route_native(perm)
    except Exception:  # noqa: BLE001 — any native failure falls back
        import logging
        logging.getLogger(__name__).debug(
            "native benes router failed; python fallback", exc_info=True)
        packed = None
    if packed is not None:
        return packed
    return pack_masks(benes_route(perm))


def pack_masks(masks: list[np.ndarray]) -> np.ndarray:
    """Bit-pack stage masks to a (n_stages, N//8) uint8 array."""
    return np.stack([np.packbits(m.astype(np.uint8)) for m in masks])


def unpack_masks(packed: np.ndarray, n: int) -> list[np.ndarray]:
    return [np.unpackbits(row)[:n].astype(bool) for row in packed]
