"""GraphBLAST-style semiring SpMV/SpMM core — the ONE linear-algebra seam
every SpMV-shaped algorithm in `ops/` rides.

Before r10 each algorithm (pagerank, katz, labelprop, wcc/scc, sssp/bfs,
betweenness, gnn) hand-rolled its own `jax.ops.segment_*`-inside-
`lax.while_loop` pipeline — 31 call sites across 8 files — and none of
them inherited the MXU fast path or the mesh story unless someone wired
it by hand. This module collapses all of them onto one algebra
(GraphBLAST, PAPERS.md): a graph algorithm is

    y = A ⊕.⊗ x          over a (⊕, ⊗) semiring,

iterated to a fixpoint with the rank-update and the convergence check
FUSED into the matvec body (FUSED-PAGERANK, PAPERS.md — the epilogue
runs on the accumulator while it is still in registers/VMEM, removing a
full HBM round trip per iteration).

Three backends sit behind one dispatch (`route_backend`):

  * ``segment``  — the reference path: per-edge gather + ⊗-combine +
    sorted segment-⊕ reduction, jitted with the epilogue fused into the
    `while_loop` body.  Runs everywhere (CPU tests, mesh-of-1).
  * ``mxu``      — the gather-free pallas/Benes MXU plan
    (`ops/spmv_mxu.py`), generalized from pagerank-only to
    semiring-parameterized kernels.  Only ⊕ = sum rides it (the
    reduce/extract phase is a one-hot matmul, i.e. a sum).
  * ``mesh``     — the partition-centric `ShardedCSR` kernels
    (`parallel/distributed.py`): exactly ONE collective per iteration,
    checkpoint-resumable through the r12 chunk machinery.

Mixed precision (`precision=`): ``f32`` is the exact path; ``bf16``
rounds each per-edge contribution to bfloat16 before the f32
accumulation (halves the routed HBM traffic on the MXU backend);
``int8`` quantizes the streamed vector symmetrically to int8 per
iteration and dequantizes after the gather (the reduced-precision
streaming SpMV of PAPERS.md).  The documented error bounds live in
:data:`PRECISION_BOUNDS` and are enforced by tests/test_semiring.py.

Direction optimization: :func:`select_pull` implements the
Beamer/GraphBLAST push/pull heuristic — pull (reduce over all edges)
when the frontier's out-edge mass exceeds ``n_edges / DIRECTION_ALPHA``,
push (frontier-masked contributions) when it is sparse.  Both sides are
exact; the selector only changes which formulation the device executes.

Adding a new algorithm is a ~50-line (semiring, setup, epilogue)
definition — see docs/architecture.md §Semiring kernel core.

The seam also serves NON-iterating consumers: the compiled Cypher read
lane (r20 mglane, ops/pipeline.py) lowers 1–2 hop expansions onto
fixed-depth masked :func:`spmv` chains over the ``plus_first`` /
``or_and`` rows of the table — same masks, same backends, same stage
attribution, no while_loop.
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass
from functools import partial

import numpy as np

# --------------------------------------------------------------------------
# semiring algebra
# --------------------------------------------------------------------------

#: ⊕ kinds understood by :func:`edge_reduce`
_ADD_KINDS = ("sum", "min", "max", "or")
#: ⊗ kinds understood by :func:`edge_combine`
_MUL_KINDS = ("times", "plus", "first", "min", "and")


@dataclass(frozen=True)
class Semiring:
    """A (⊕, ⊗) pair: ``y[j] = ⊕_{(i,j) ∈ E} (x[i] ⊗ w[i,j])``."""
    name: str
    add: str            # one of _ADD_KINDS
    mul: str            # one of _MUL_KINDS

    def __post_init__(self):
        if self.add not in _ADD_KINDS:
            raise ValueError(f"unknown ⊕ {self.add!r}")
        if self.mul not in _MUL_KINDS:
            raise ValueError(f"unknown ⊗ {self.mul!r}")


#: the semiring table (GraphBLAST's classics + the two degenerate ⊗=first
#: forms the label/component kernels use). mglint MG005 validates every
#: SPMV_ALGORITHMS "core" declaration against these keys.
SEMIRINGS = {
    "plus_times": Semiring("plus_times", "sum", "times"),   # pagerank/katz
    "min_plus": Semiring("min_plus", "min", "plus"),        # sssp/bfs
    "max_min": Semiring("max_min", "max", "min"),           # bottleneck path
    "or_and": Semiring("or_and", "or", "and"),              # reachability
    "plus_first": Semiring("plus_first", "sum", "first"),   # sigma/gnn agg
    "min_first": Semiring("min_first", "min", "first"),     # wcc/scc labels
}


def resolve_semiring(sr) -> Semiring:
    if isinstance(sr, Semiring):
        return sr
    got = SEMIRINGS.get(sr)
    if got is None:
        raise KeyError(f"unknown semiring {sr!r}; have {sorted(SEMIRINGS)}")
    return got


# --------------------------------------------------------------------------
# mixed precision
# --------------------------------------------------------------------------

#: Documented, test-enforced error bounds (tests/test_semiring.py asserts
#: converged pagerank on the seeded 300-node/3k-edge graph stays inside
#: these vs the f32 reference; docs/architecture.md §Semiring kernel core
#: carries the same table).  Derivation sketch:
#:   bf16 — each contribution carries one rounding of relative size
#:          2^-9..2^-8; with damping d the fixpoint error is bounded by
#:          d/(1-d) · 2^-8 · max(rank) per component.  Budgeted 4x.
#:   int8 — symmetric per-iteration quantization of the streamed vector:
#:          |x - dq(x)| ≤ max|x|/254 per element, amplified d/(1-d) at
#:          the fixpoint.  Budgeted 4x.
PRECISION_BOUNDS = {
    "bf16": {"pagerank_linf": 4 * (0.85 / 0.15) * 2.0 ** -8 * 0.05,
             "pagerank_l1": 2.5e-2, "topk_order": 5},
    "int8": {"pagerank_linf": 4 * (0.85 / 0.15) * (0.05 / 254.0),
             "pagerank_l1": 2.5e-2, "topk_order": 5},
}

_PRECISIONS = ("f32", "bf16", "int8")


def _check_precision(precision: str) -> str:
    if precision not in _PRECISIONS:
        raise ValueError(
            f"precision must be one of {_PRECISIONS}, got {precision!r}")
    return precision


def quantize_int8(x):
    """Symmetric per-vector int8 quantization: (q int8, scale f32) with
    x ≈ q * scale, |x - q·scale| ≤ max|x|/254 per element."""
    import jax.numpy as jnp
    scale = jnp.maximum(jnp.max(jnp.abs(x)) / 127.0, 1e-30)
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale


# --------------------------------------------------------------------------
# building blocks (traceable: usable inside any jitted kernel)
# --------------------------------------------------------------------------


def edge_combine(sr, xe, w=None):
    """Per-edge ⊗: combine the gathered vector entries with edge values.

    SpMM lanes: when ``xe`` carries feature columns (e, d) and ``w`` is
    the per-edge (e,) vector, the edge values broadcast across every
    lane — one weight per edge, applied to all d fixpoints at once (the
    batched multi-source PPR formulation)."""
    import jax.numpy as jnp
    sr = resolve_semiring(sr)
    if sr.mul == "first":
        return xe
    if w is None:
        raise ValueError(f"⊗ = {sr.mul!r} needs edge values")
    if getattr(xe, "ndim", 1) > 1 and getattr(w, "ndim", 1) == 1:
        w = w[(...,) + (None,) * (xe.ndim - 1)]
    if sr.mul == "times":
        return xe * w
    if sr.mul == "plus":
        return xe + w
    if sr.mul == "min":
        return jnp.minimum(xe, w)
    # "and": boolean conjunction
    return jnp.logical_and(xe, w)


def edge_reduce(kind, vals, ids, num_segments: int, sorted: bool = False):
    """⊕ segment reduction — THE routing point for every segment-shaped
    reduction in ops/ (mglint MG005 flags residual direct
    ``jax.ops.segment_*`` pipelines outside this module)."""
    import jax
    import jax.numpy as jnp
    if kind == "sum":
        return jax.ops.segment_sum(vals, ids, num_segments=num_segments,
                                   indices_are_sorted=sorted)
    if kind == "min":
        return jax.ops.segment_min(vals, ids, num_segments=num_segments,
                                   indices_are_sorted=sorted)
    if kind == "max":
        return jax.ops.segment_max(vals, ids, num_segments=num_segments,
                                   indices_are_sorted=sorted)
    if kind == "or":
        got = jax.ops.segment_max(vals.astype(jnp.int32), ids,
                                  num_segments=num_segments,
                                  indices_are_sorted=sorted)
        return got > 0
    raise ValueError(f"unknown ⊕ {kind!r}")


def reduce_identity(sr, dtype):
    """The ⊕ identity (what masked-out edges must contribute)."""
    import jax.numpy as jnp
    sr = resolve_semiring(sr)
    if sr.add == "sum":
        return jnp.zeros((), dtype=dtype)
    if sr.add == "or":
        return jnp.zeros((), dtype=jnp.bool_)
    info = (jnp.iinfo(dtype) if jnp.issubdtype(dtype, jnp.integer)
            else jnp.finfo(dtype))
    return jnp.array(info.max if sr.add == "min" else info.min,
                     dtype=dtype)


def combine_accumulators(sr, a, b):
    """⊕-combine two partial accumulators (e.g. fwd + bwd direction)."""
    import jax.numpy as jnp
    sr = resolve_semiring(sr)
    if sr.add == "sum":
        return a + b
    if sr.add == "min":
        return jnp.minimum(a, b)
    if sr.add == "max":
        return jnp.maximum(a, b)
    return jnp.logical_or(a, b)


def spmv(sr, x, src, dst, w=None, *, n_out: int, sorted: bool = False,
         mask=None, mask_fill=None, precision: str = "f32",
         frontier=None):
    """One semiring matvec ``y = A^T ⊕.⊗ x`` over COO edge arrays.

    Traceable — usable standalone or inside a jitted loop body.

      sr         semiring name or Semiring
      x          (n,) or (n, d) vector/matrix (SpMM: d feature lanes)
      src, dst   (e,) gather / reduce-key edge endpoints
      w          (e,) edge values (required unless ⊗ = first)
      sorted     dst is non-decreasing (CSC shards) → sorted lowering
      mask       (e,) bool — edges where False contribute the ⊕ identity
                 (or `mask_fill` when given: the masked-SpMV of
                 GraphBLAST, used by the SCC coloring rounds)
      precision  f32 | bf16 (contributions rounded, f32 accumulate) |
                 int8 (x quantized before the gather — the streamed
                 read is 1/4 the bytes — dequantized after)
      frontier   (n,) bool — push-mode source masking: only edges whose
                 src is in the frontier contribute (exact for monotone
                 iterations; see select_pull)
    """
    import jax.numpy as jnp
    sr = resolve_semiring(sr)
    _check_precision(precision)
    if precision == "int8":
        q, scale = quantize_int8(x)
        xe = q[src].astype(x.dtype) * scale
    else:
        xe = x[src]
    vals = edge_combine(sr, xe, w)
    if precision == "bf16":
        vals = vals.astype(jnp.bfloat16).astype(jnp.float32)
    sel = None
    if mask is not None:
        sel = mask
    if frontier is not None:
        fsel = frontier[src]
        sel = fsel if sel is None else (sel & fsel)
    if sel is not None:
        fill = (mask_fill if mask_fill is not None
                else reduce_identity(sr, vals.dtype))
        if vals.ndim > 1:
            sel = sel[(...,) + (None,) * (vals.ndim - 1)]
        vals = jnp.where(sel, vals, fill)
    return edge_reduce(sr.add, vals, dst, n_out, sorted=sorted)


# --------------------------------------------------------------------------
# direction-optimizing push/pull
# --------------------------------------------------------------------------

#: Beamer's alpha: pull once the frontier's out-edge mass exceeds
#: n_edges / alpha (the classic DO-BFS threshold; env-overridable)
DIRECTION_ALPHA = float(os.environ.get("MEMGRAPH_TPU_DO_ALPHA", 14.0))


def select_pull(frontier, out_degree, n_edges, alpha: float | None = None):
    """Traced push/pull decision from frontier density.

    Returns a traced bool: True → pull (reduce over every edge), False →
    push (frontier-masked contributions).  `frontier` is the (n,) bool
    active-vertex mask, `out_degree` the (n,) f32 out-degrees — the
    frontier's out-edge mass m_f is compared against m/alpha exactly as
    in direction-optimizing BFS (Beamer; GraphBLAST's switch)."""
    import jax.numpy as jnp
    a = DIRECTION_ALPHA if alpha is None else alpha
    m_f = jnp.sum(jnp.where(frontier, out_degree, 0.0))
    return m_f > (n_edges / a)


# --------------------------------------------------------------------------
# the fused fixpoint loop (segment backend)
# --------------------------------------------------------------------------
#
# One jitted program per (algorithm, shapes):   env = setup(A, P)
#   while cond:  acc = step(x);  x, metric = epilogue(x, acc, env, P)
# The epilogue — the algorithm's update rule AND its convergence partial
# — runs inside the while body, on the accumulator the matvec just
# produced (FUSED-PAGERANK): no extra HBM round trip, no second kernel.

_FIXPOINT_CACHE: dict = {}
_fixpoint_cache_lock = threading.Lock()


def _default_step(sr, A, env, x, P, *, n_out, sorted, sorted_backward,
                  direction, precision):
    w = env.get("w", A.get("w"))
    acc = spmv(sr, x, A["src"], A["dst"], w, n_out=n_out, sorted=sorted,
               precision=precision)
    if direction == "both":
        acc_b = spmv(sr, x, A["dst"], A["src"], w, n_out=n_out,
                     sorted=sorted_backward, precision=precision)
        acc = combine_accumulators(sr, acc, acc_b)
    return acc


def _build_fixpoint(sr, *, epilogue, setup, step, n_out, max_iterations,
                    metric, precision, sorted, sorted_backward, direction):
    import jax
    import jax.numpy as jnp

    def run(A, P, x0):
        env = dict(setup(A, P, n_out)) if setup is not None else {}
        x = env.pop("x0") if x0 is None else x0
        tol = P.get("tol")

        def body(carry):
            x, _, it = carry
            if step is not None:
                acc = step(x, A, env, P, n_out)
            else:
                acc = _default_step(
                    sr, A, env, x, P, n_out=n_out, sorted=sorted,
                    sorted_backward=sorted_backward, direction=direction,
                    precision=precision)
            new_x, m = epilogue(x, acc, env, P)
            return new_x, m, it + 1

        if metric == "changed":
            def cond(carry):
                _, m, it = carry
                return m & (it < max_iterations)
            m0 = jnp.bool_(True)
        else:
            def cond(carry):
                _, m, it = carry
                return (m > tol) & (it < max_iterations)
            m0 = jnp.float32(jnp.inf)

        return jax.lax.while_loop(cond, body, (x, m0, jnp.int32(0)))

    # the x0 seed is donated back to the iterate: callers pass freshly
    # built start vectors (or None, which donates nothing), so the
    # fixpoint carry never holds two live copies of the O(n) state
    return jax.jit(run, donate_argnums=(2,))


def fixpoint(sr, *, arrays, params=None, x0=None, n_out: int, epilogue,
             setup=None, step=None, max_iterations: int, metric="err",
             precision: str = "f32", sorted: bool = False,
             sorted_backward: bool = False, direction: str = "fwd"):
    """Run a fused semiring fixpoint on the segment backend.

    ``arrays``/``params`` are dicts of traced edge arrays / scalars;
    ``setup(A, P, n_out) -> env`` precomputes loop invariants (and may
    provide ``env["x0"]`` when `x0` is None); ``step(x, A, env, P,
    n_out) -> acc`` overrides the default matvec (multi-matvec bodies
    like HITS or labelprop's election); ``epilogue(x, acc, env, P) ->
    (new_x, metric)`` is the fused update + convergence partial.
    ``metric="err"`` iterates while ``metric > P["tol"]``;
    ``metric="changed"`` while the bool metric holds.

    Returns (x, metric, iterations).  Compiled programs are cached per
    (algorithm hooks, shapes) — repeated calls pay tracing once.
    """
    from ..utils.jax_cache import ensure_compile_cache
    from ..observability import stats as mgstats
    from ..observability import trace as mgtrace
    ensure_compile_cache()
    sr = resolve_semiring(sr)
    _check_precision(precision)
    params = params or {}
    key = (sr.name, epilogue, setup, step, int(n_out),
           int(max_iterations), metric, precision, bool(sorted),
           bool(sorted_backward), direction, tuple(sorted_keys(arrays)),
           tuple(sorted_keys(params)), x0 is None)
    fn = _FIXPOINT_CACHE.get(key)
    if fn is None:
        with _fixpoint_cache_lock:
            fn = _FIXPOINT_CACHE.get(key)
            if fn is None:
                fn = _build_fixpoint(
                    sr, epilogue=epilogue, setup=setup, step=step,
                    n_out=n_out, max_iterations=max_iterations,
                    metric=metric, precision=precision, sorted=sorted,
                    sorted_backward=sorted_backward, direction=direction)
                _FIXPOINT_CACHE[key] = fn
    t0 = time.perf_counter()
    with mgtrace.span("device.chunk") as sp:
        out = fn(arrays, params, x0)
        if sp:
            sp.set(semiring=sr.name, precision=precision,
                   backend="segment")
    dt = time.perf_counter() - t0
    mgstats.record_stage("device_iterate", dt)
    mgstats.record_stage("semiring_segment", dt)
    return out


def sorted_keys(d):
    return sorted(d) if d else ()


# --------------------------------------------------------------------------
# shared update rules (one definition; every backend folds onto it)
# --------------------------------------------------------------------------


def pagerank_update(acc, dangling_mass, valid, n_f, damping):
    """THE PageRank damping update — shared by the segment kernel, the
    MXU kernel (spmv_mxu), the sharded MXU kernel (spmv_mxu_sharded)
    and the partition-centric mesh kernel (parallel/distributed), so
    the formula exists exactly once in the tree."""
    return valid * ((1.0 - damping) / n_f
                    + damping * (acc + dangling_mass / n_f))


# --------------------------------------------------------------------------
# backend routing
# --------------------------------------------------------------------------

#: Above this edge count the gather-free MXU formulation (ops/spmv_mxu.py)
#: wins despite its host-side plan build; below it the segment kernel's
#: zero setup cost wins. Plan+kernel are cached on the DeviceGraph
#: snapshot, so repeated CALLs on an unchanged graph pay the build once.
MXU_MIN_EDGES = int(os.environ.get("MEMGRAPH_TPU_MXU_MIN_EDGES", 500_000))


def route_backend(graph, mesh=None, *, semiring="plus_times",
                  precision: str = "f32", min_edges: int | None = None):
    """Resolve which backend a core-routed algorithm runs on.

    Returns ("mesh", MeshContext) | ("mxu", None) | ("segment", None).
    The MXU plan's reduce/extract phase is a one-hot matmul — a SUM —
    so only ⊕ = sum semirings ride it; int8 streaming stays on the
    segment backend (the Benes route dtype is f32/bf16).
    """
    import jax
    from ..parallel.mesh import resolve_mesh
    _check_precision(precision)
    ctx = resolve_mesh(mesh)
    if ctx is not None:
        return "mesh", ctx
    sr = resolve_semiring(semiring)
    if min_edges is None:
        min_edges = MXU_MIN_EDGES
    if (sr.add == "sum" and precision != "int8"
            and graph.n_edges >= min_edges
            and (jax.default_backend() != "cpu"
                 or os.environ.get("MEMGRAPH_TPU_FORCE_MXU"))):
        return "mxu", None
    return "segment", None


@contextmanager
def backend_extent(backend: str, record_iterate: bool = False):
    """Attribute a backend dispatch to the active mgstat stage
    accumulator (PROFILE of a core-routed query shows time per backend:
    ``semiring_mesh`` / ``semiring_mxu`` / ``semiring_segment``).  The
    segment fixpoint records its own extent; mesh/MXU call sites wrap
    their dispatch with this."""
    from ..observability import stats as mgstats
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        mgstats.record_stage(f"semiring_{backend}", dt)
        if record_iterate:
            mgstats.record_stage("device_iterate", dt)


# --------------------------------------------------------------------------
# generalized MXU backend (semiring-parameterized plan cache)
# --------------------------------------------------------------------------

_mxu_plan_guard = threading.Lock()


def mxu_fixpoint(graph, *, epilogue, params, max_iterations, tol,
                 normalize: bool = True, precision: str = "f32",
                 cache_tag: str = "generic", x0_default: str = "zeros",
                 x0=None):
    """Run a ⊕ = sum fixpoint on the gather-free MXU backend.

    Builds (or reuses, cached on the immutable DeviceGraph snapshot) a
    `spmv_mxu` plan with ``normalize=True`` baking w/out-weight-sum
    multipliers (the stochastic matrix pagerank iterates) or plain w
    (katz's A^T), then runs `make_semiring_kernel` with the given fused
    epilogue.  Returns (x_original_ids, err, iters).

    ``x0`` — optional (n_nodes,) warm-start seed in ORIGINAL node ids
    (ops/delta.py commit-then-CALL); mapped into the plan's OUT
    labeling before dispatch. None keeps the on-device default start
    (``x0_default``), which saves the host->device transfer."""
    import jax.numpy as jnp
    from . import spmv_mxu
    _check_precision(precision)
    if precision == "int8":
        raise ValueError("the MXU backend routes f32/bf16 only; int8 "
                         "streaming rides the segment backend")
    key = (cache_tag, bool(normalize), precision, epilogue, x0_default)
    cache = getattr(graph, "_mxu_semiring", None)
    if cache is None or key not in cache:
        with _mxu_plan_guard:
            cache = getattr(graph, "_mxu_semiring", None)
            if cache is None:
                cache = {}
                object.__setattr__(graph, "_mxu_semiring", cache)
            if key not in cache:
                plan_key = ("plan", cache_tag, bool(normalize))
                plan = cache.get(plan_key)
                if plan is None:
                    src = np.asarray(graph.src_idx)[:graph.n_edges]
                    dst = np.asarray(graph.col_idx)[:graph.n_edges]
                    w = np.asarray(graph.weights)[:graph.n_edges]
                    plan = spmv_mxu.build_plan(src, dst, w,
                                               graph.n_nodes,
                                               normalize=normalize)
                    cache[plan_key] = plan
                route_dtype = (jnp.bfloat16 if precision == "bf16"
                               else jnp.float32)
                cache[key] = (plan, spmv_mxu.make_semiring_kernel(
                    plan, epilogue=epilogue, route_dtype=route_dtype,
                    x0_default=x0_default))
    plan, run = cache[key]
    x0_flat = None
    if x0 is not None:
        x0_flat = np.zeros(len(plan.valid_out), dtype=np.float32)
        x0_flat[plan.out_relabel] = \
            np.asarray(x0, dtype=np.float32)[:graph.n_nodes]
    with backend_extent("mxu", record_iterate=True):
        x, err, iters = run(x0_flat, params, int(max_iterations),
                            np.float32(tol))
    return np.asarray(x)[plan.out_relabel], float(err), int(iters)
