"""Betweenness centrality: batched Brandes on the device.

TPU-native replacement for the reference's exact/C++ implementation
(/root/reference/mage/cpp/betweenness_centrality_module/) and cuGraph's
betweenness_centrality.cu: per-source level-synchronous BFS with
shortest-path counting (sigma) expressed as segment reductions over the
edge list, then the backward dependency accumulation — both batched over
sources with vmap so the MXU/VPU sees (B, n_pad) blocks instead of
pointer chasing.

Unweighted Brandes (the reference module is unweighted too). Sources are
processed in chunks to bound device memory at (chunk, n_pad) floats.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from . import semiring as S
from .csr import DeviceGraph

INF = jnp.float32(3.0e38)


@partial(jax.jit, static_argnames=("n_pad", "max_levels"))
def _brandes_chunk(src, dst, edge_valid, sources, weights, n_pad: int,
                   max_levels: int):
    """Weighted sum of per-source dependency scores: (n_pad,).
    weights: (B,) — 0 entries let the final chunk pad to a uniform
    static shape without double-counting.

    The whole chunk runs level-synchronously as ONE while_loop over
    (B, n_pad) state — NOT vmap-of-while_loop, which mis-executes on
    this backend (the masked continuation stops after one iteration;
    verified r4). Rows whose BFS finished simply stop discovering.
    """
    B = sources.shape[0]
    rows = jnp.arange(B)
    seg_ids = rows[:, None] * n_pad + dst[None, :]   # batched segment ids
    seg_ids_back = rows[:, None] * n_pad + src[None, :]

    dist0 = jnp.full((B, n_pad), INF, jnp.float32).at[rows, sources].set(0.0)
    sigma0 = jnp.zeros((B, n_pad), jnp.float32).at[rows, sources].set(1.0)

    # forward: settle level L+1 from level L, all sources in lockstep
    def fwd_body(carry):
        dist, sigma, level, _ = carry
        on_frontier = (dist[:, src] == level) & edge_valid[None, :]
        contrib = jnp.where(on_frontier, sigma[:, src], 0.0)
        # batched plus-first reduction (core ⊕): sigma flows along the
        # frontier edges of every source row at once
        sig_new = S.edge_reduce(
            "sum", contrib.reshape(-1), seg_ids.reshape(-1),
            B * n_pad).reshape(B, n_pad)
        newly = (dist >= INF / 2) & (sig_new > 0)
        dist = jnp.where(newly, level + 1.0, dist)
        sigma = jnp.where(newly, sig_new, sigma)
        return dist, sigma, level + 1.0, jnp.any(newly)

    def fwd_cond(carry):
        _, _, level, progressed = carry
        return progressed & (level < max_levels)

    dist, sigma, top_level, _ = jax.lax.while_loop(
        fwd_cond, fwd_body,
        (dist0, sigma0, jnp.float32(0.0), jnp.bool_(True)))

    # backward: accumulate dependencies from the deepest level down
    def bwd_body(carry):
        delta, level = carry
        on_edge = (dist[:, src] == level) \
            & (dist[:, dst] == level + 1.0) & edge_valid[None, :]
        safe_sigma = jnp.maximum(sigma[:, dst], 1.0)
        contrib = jnp.where(
            on_edge,
            sigma[:, src] / safe_sigma * (1.0 + delta[:, dst]), 0.0)
        add = S.edge_reduce(
            "sum", contrib.reshape(-1), seg_ids_back.reshape(-1),
            B * n_pad).reshape(B, n_pad)
        delta = jnp.where(dist == level, add, delta)
        return delta, level - 1.0

    delta0 = jnp.zeros((B, n_pad), jnp.float32)
    delta, _ = jax.lax.while_loop(
        lambda c: c[1] >= 0.0, bwd_body, (delta0, top_level - 1.0))
    # sources accumulate no dependency for their own BFS
    delta = delta.at[rows, sources].set(0.0)
    return (weights[:, None] * delta).sum(axis=0)


def autotune_chunk(n_edges: int, n_pad: int,
                   budget_bytes: int | None = None) -> int:
    """Pick the source-chunk size B from a device-memory budget.

    Live state per source row: ~2 (B, E) f32 temporaries in the
    segment-sum (frontier contributions + their exchange buffer) plus
    3 (B, n_pad) f32 carries (dist/sigma/delta). At bench scale
    (1M nodes / 10M edges) an unbounded B=32 would demand >1.2 GB of
    (B, E) temporaries alone — the autotuner keeps the total under the
    budget (default 4 GiB, MEMGRAPH_TPU_BC_MEM_BUDGET_MB overrides)."""
    import os
    if budget_bytes is None:
        budget_bytes = int(os.environ.get(
            "MEMGRAPH_TPU_BC_MEM_BUDGET_MB", 4096)) << 20
    per_row = 2 * n_edges * 4 + 3 * n_pad * 4
    return int(max(1, min(64, budget_bytes // max(per_row, 1))))


def betweenness_centrality(graph: DeviceGraph, directed: bool = True,
                           normalized: bool = True, samples=None,
                           chunk=None, seed: int = 0,
                           max_levels: int | None = None):
    """Betweenness scores (n_nodes,). samples=None → exact (all sources);
    an int → sampled approximation scaled by n/samples. chunk=None →
    autotuned from the device-memory budget (autotune_chunk)."""
    n = graph.n_nodes
    if n == 0:
        return jnp.zeros((0,), jnp.float32)
    # simple-graph semantics (Brandes sigma counts SHORTEST PATHS, not
    # parallel-edge multiplicities): dedupe edges host-side; undirected
    # canonicalizes (min, max) then mirrors
    s_np = np.asarray(graph.src_idx)[:graph.n_edges]
    d_np = np.asarray(graph.col_idx)[:graph.n_edges]
    keep = s_np != d_np                 # self-loops never carry paths
    s_np, d_np = s_np[keep], d_np[keep]
    if directed:
        pairs = np.unique(np.stack([s_np, d_np], axis=1), axis=0)
        src = jnp.asarray(pairs[:, 0], jnp.int32)
        dst = jnp.asarray(pairs[:, 1], jnp.int32)
    else:
        canon = np.stack([np.minimum(s_np, d_np),
                          np.maximum(s_np, d_np)], axis=1)
        pairs = np.unique(canon, axis=0)
        src = jnp.asarray(np.concatenate([pairs[:, 0], pairs[:, 1]]),
                          jnp.int32)
        dst = jnp.asarray(np.concatenate([pairs[:, 1], pairs[:, 0]]),
                          jnp.int32)
    edge_valid = jnp.ones(src.shape, bool)

    if samples is None or samples >= n:
        sources = np.arange(n, dtype=np.int32)
        scale = 1.0
    else:
        rng = np.random.default_rng(seed)
        sources = rng.choice(n, size=int(samples),
                             replace=False).astype(np.int32)
        scale = n / float(len(sources))

    if chunk is None:
        chunk = autotune_chunk(int(src.shape[0]), graph.n_pad)
    levels = max_levels if max_levels is not None else n_levels_bound(n)
    bc = jnp.zeros((graph.n_pad,), jnp.float32)
    for i in range(0, len(sources), chunk):
        part = sources[i:i + chunk]
        pad = chunk - len(part)
        # the final chunk pads with repeats weighted 0: one jit shape,
        # no duplicate contributions
        padded = np.concatenate([part, np.full(pad, part[0], np.int32)]) \
            if pad else part
        w = np.concatenate([np.ones(len(part), np.float32),
                            np.zeros(pad, np.float32)])
        bc = bc + _brandes_chunk(src, dst, edge_valid,
                                 jnp.asarray(padded), jnp.asarray(w),
                                 graph.n_pad, levels)

    bc = bc[:n] * scale
    if not directed:
        bc = bc / 2.0
    if normalized and n > 2:
        denom = (n - 1) * (n - 2)
        if not directed:
            denom /= 2.0
        bc = bc / denom
    return bc


def n_levels_bound(n: int) -> int:
    """BFS level cap: the diameter can't exceed n-1; bounded for jit."""
    return max(2, min(n, 10_000))
