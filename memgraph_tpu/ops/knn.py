"""Vector similarity search on TPU: brute-force and IVF kNN.

TPU-native replacement for the reference's usearch-backed HNSW vector index
(/root/reference/src/storage/v2/indices/vector_index.cpp uses
usearch/index_dense.hpp): instead of a pointer-chasing graph index — hostile
to the MXU — similarity search is a dense matmul (scores = Q @ X^T in
bfloat16 with float32 accumulation) + `lax.top_k`. Brute force on TPU beats
HNSW-on-CPU well past 10M vectors; the IVF variant (coarse k-means
quantizer + probed cells) covers the larger regime.

Metrics match the reference's vector-index options: cosine, l2sq (squared
euclidean), dot (inner product).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("k", "metric", "use_bf16"))
def knn(corpus, queries, k: int, metric: str = "cosine",
        use_bf16: bool = True, valid_count=None, valid_mask=None):
    """Top-k nearest rows of `corpus` (n, d) for each of `queries` (q, d).

    Returns (scores (q, k), indices (q, k)); higher score = closer.
    `valid_count`: rows >= valid_count are padding and never returned.
    `valid_mask`: optional (n,) bool/float — rows where falsy are masked
    out (delta-maintained indexes keep free rows in place).
    """
    x = corpus
    qv = queries
    if metric == "cosine":
        x = x / jnp.maximum(jnp.linalg.norm(x, axis=1, keepdims=True), 1e-12)
        qv = qv / jnp.maximum(jnp.linalg.norm(qv, axis=1, keepdims=True), 1e-12)
    if use_bf16:
        scores = jax.lax.dot_general(
            qv.astype(jnp.bfloat16), x.astype(jnp.bfloat16),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:
        scores = qv @ x.T
    if metric == "l2sq":
        # -||q - x||^2 = 2 q·x - ||x||^2 - ||q||^2 ; drop the per-query term
        xsq = jnp.sum(corpus.astype(jnp.float32) ** 2, axis=1)
        scores = 2.0 * scores - xsq[None, :]
    if valid_count is not None:
        col = jnp.arange(corpus.shape[0])
        scores = jnp.where(col[None, :] < valid_count, scores, -jnp.inf)
    if valid_mask is not None:
        scores = jnp.where(valid_mask[None, :] > 0, scores, -jnp.inf)
    top_scores, top_idx = jax.lax.top_k(scores, k)
    return top_scores, top_idx


@partial(jax.jit, static_argnames=("n_clusters", "iters"))
def kmeans_fit(points, key, n_clusters: int, iters: int = 10):
    """Light k-means for the IVF coarse quantizer (and the kmeans module —
    analog of mage/python/kmeans.py). Returns (centroids, assignment)."""
    n = points.shape[0]
    init_idx = jax.random.choice(key, n, shape=(n_clusters,), replace=False)
    cent0 = points[init_idx]

    def step(cent, _):
        d = (jnp.sum(points ** 2, axis=1, keepdims=True)
             - 2.0 * points @ cent.T + jnp.sum(cent ** 2, axis=1)[None, :])
        assign = jnp.argmin(d, axis=1)
        one_hot = jax.nn.one_hot(assign, n_clusters, dtype=points.dtype)
        sums = one_hot.T @ points
        counts = jnp.sum(one_hot, axis=0)[:, None]
        new_cent = jnp.where(counts > 0, sums / jnp.maximum(counts, 1.0), cent)
        return new_cent, None

    cent, _ = jax.lax.scan(step, cent0, None, length=iters)
    d = (jnp.sum(points ** 2, axis=1, keepdims=True)
         - 2.0 * points @ cent.T + jnp.sum(cent ** 2, axis=1)[None, :])
    return cent, jnp.argmin(d, axis=1)


class IvfIndex:
    """IVF-flat index: coarse k-means cells, search probes the closest cells.

    Host-side bookkeeping + device kernels; rebuildable from the storage's
    vector columns. For most graph workloads brute-force `knn` is faster on
    TPU; IVF exists for the >10M-vector regime.
    """

    def __init__(self, points, n_clusters: int = 64, seed: int = 0):
        import numpy as np
        points = jnp.asarray(points, dtype=jnp.float32)
        self.points = points
        n_clusters = max(1, min(n_clusters, points.shape[0]))
        key = jax.random.PRNGKey(seed)
        self.centroids, assign = kmeans_fit(points, key, n_clusters)
        assign = np.asarray(assign)
        order = np.argsort(assign, kind="stable")
        self.order = jnp.asarray(order)
        self.sorted_points = points[self.order]
        counts = np.bincount(assign, minlength=n_clusters)
        self.cell_start = jnp.asarray(
            np.concatenate([[0], np.cumsum(counts)]).astype(np.int32))
        self.n_clusters = n_clusters

    def search(self, queries, k: int, n_probe: int = 8,
               metric: str = "cosine"):
        """Probe the n_probe nearest cells per query; exact within cells."""
        queries = jnp.asarray(queries, dtype=jnp.float32)
        # rank cells by centroid similarity, then score only their members
        _, cell_idx = knn(self.centroids, queries, k=min(n_probe,
                                                         self.n_clusters),
                          metric=metric, use_bf16=False)
        import numpy as np
        cell_idx = np.asarray(cell_idx)
        start = np.asarray(self.cell_start)
        out_scores, out_ids = [], []
        for qi in range(queries.shape[0]):
            member_rows = np.concatenate([
                np.arange(start[c], start[c + 1]) for c in cell_idx[qi]
            ]) if cell_idx.shape[1] else np.empty(0, np.int64)
            if len(member_rows) == 0:
                out_scores.append(np.full(k, -np.inf, np.float32))
                out_ids.append(np.full(k, -1, np.int64))
                continue
            cand = self.sorted_points[jnp.asarray(member_rows)]
            kk = min(k, len(member_rows))
            s, i = knn(cand, queries[qi:qi + 1], k=kk, metric=metric,
                       use_bf16=False)
            ids = np.asarray(self.order)[member_rows[np.asarray(i[0])]]
            s = np.asarray(s[0])
            if kk < k:
                s = np.pad(s, (0, k - kk), constant_values=-np.inf)
                ids = np.pad(ids, (0, k - kk), constant_values=-1)
            out_scores.append(s)
            out_ids.append(ids)
        return np.stack(out_scores), np.stack(out_ids)
