"""ctypes bridge to the native CSR builder (native/csr_builder.cpp).

Builds the shared library on first use if a compiler is available; falls
back to the numpy path in csr.py otherwise. The native counting-sort builder
is O(E + N) vs numpy's O(E log E) lexsort — the dominant host-side cost of
exporting large graphs to the device.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

log = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libcsr_builder.so")

_lock = threading.Lock()
_lib = None
_tried = False


_SOURCES = ("csr_builder.cpp", "benes_router.cpp", "edge_color.cpp")


def _ensure_built() -> bool:
    srcs = [os.path.join(_NATIVE_DIR, f) for f in _SOURCES]
    if not all(os.path.exists(p) for p in srcs):
        # sources pruned (e.g. binary-only deployment): trust a prebuilt .so
        return os.path.exists(_LIB_PATH)
    if os.path.exists(_LIB_PATH) and all(
            os.path.getmtime(_LIB_PATH) >= os.path.getmtime(p)
            for p in srcs):
        return True
    # compile to a temp name and rename: an interrupted build must never
    # leave a half-written .so that later loads treat as valid
    tmp = _LIB_PATH + f".tmp{os.getpid()}"
    try:
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-Wall",
             "-o", tmp] + srcs,
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB_PATH)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        log.info("native csr builder unavailable (%s); using numpy path", e)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def get_lib():
    """Load (building if needed) the native library, or None."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not _ensure_built():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as e:
            log.info("cannot load native csr builder: %s", e)
            return None
        try:
            lib.benes_route.restype = ctypes.c_int
            lib.benes_route.argtypes = [
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
                ctypes.POINTER(ctypes.c_uint8),
            ]
            lib._has_benes = True
        except AttributeError:  # stale prebuilt .so without the router
            lib._has_benes = False
        try:
            lib.balanced_edge_color.restype = ctypes.c_int
            lib.balanced_edge_color.argtypes = [
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int, ctypes.POINTER(ctypes.c_uint8),
            ]
            lib._has_edge_color = True
        except AttributeError:
            lib._has_edge_color = False
        lib.build_csr_csc.restype = ctypes.c_int
        lib.build_csr_csc.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float),
        ]
        _lib = lib
        return _lib


def build_csr_csc_native(src: np.ndarray, dst: np.ndarray,
                         weights, n_nodes: int, n_pad: int, e_pad: int):
    """Run the native builder. Returns dict of arrays or None if unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    n_edges = len(src)
    src = np.ascontiguousarray(src, dtype=np.int64)
    dst = np.ascontiguousarray(dst, dtype=np.int64)
    w_ptr = None
    if weights is not None:
        weights = np.ascontiguousarray(weights, dtype=np.float32)
        w_ptr = weights.ctypes.data_as(ctypes.POINTER(ctypes.c_float))

    csr_src = np.empty(e_pad, dtype=np.int32)
    csr_dst = np.empty(e_pad, dtype=np.int32)
    csr_w = np.empty(e_pad, dtype=np.float32)
    csc_src = np.empty(e_pad, dtype=np.int32)
    csc_dst = np.empty(e_pad, dtype=np.int32)
    csc_w = np.empty(e_pad, dtype=np.float32)
    row_ptr = np.empty(n_pad + 1, dtype=np.int32)
    out_degree = np.empty(n_pad, dtype=np.float32)

    def p32(a):
        return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))

    def pf(a):
        return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))

    rc = lib.build_csr_csc(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        dst.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        w_ptr, n_edges, n_nodes, n_pad, e_pad,
        p32(csr_src), p32(csr_dst), pf(csr_w),
        p32(csc_src), p32(csc_dst), pf(csc_w),
        p32(row_ptr), pf(out_degree))
    if rc == 2:
        # invalid input, not "builder unavailable": the numpy path would
        # silently build a corrupt graph from the same ids
        raise ValueError(
            f"edge endpoint id out of range [0, {n_nodes}) in COO input")
    if rc != 0:
        log.warning("native csr builder returned %d; falling back", rc)
        return None
    return {
        "csr_src": csr_src, "csr_dst": csr_dst, "csr_w": csr_w,
        "csc_src": csc_src, "csc_dst": csc_dst, "csc_w": csc_w,
        "row_ptr": row_ptr, "out_degree": out_degree,
    }


def balanced_edge_color_native(src: np.ndarray, dst: np.ndarray,
                               n_src: int, n_dst: int, levels: int):
    """Balanced bipartite edge coloring into 2^levels shards (Euler
    splits, native/edge_color.cpp): every vertex's edges divide
    floor(d/P)..ceil(d/P) per shard on BOTH sides. Returns uint8
    shard ids, or None if the native library is unavailable."""
    lib = get_lib()
    if lib is None or not getattr(lib, "_has_edge_color", False):
        return None
    src = np.ascontiguousarray(src, dtype=np.int64)
    dst = np.ascontiguousarray(dst, dtype=np.int64)
    out = np.zeros(len(src), dtype=np.uint8)
    rc = lib.balanced_edge_color(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        dst.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(src), n_src, n_dst, levels,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    if rc != 0:
        raise ValueError("invalid input for balanced_edge_color")
    return out


def benes_route_native(perm: np.ndarray):
    """Bit-packed Benes stage masks via the C++ router, or None.

    Returns (n_stages, (N+7)//8) uint8, rows packbits-compatible.
    """
    lib = get_lib()
    if lib is None or not getattr(lib, "_has_benes", False):
        return None
    perm = np.ascontiguousarray(perm, dtype=np.int64)
    N = len(perm)
    if N < 2 or N & (N - 1):
        raise ValueError("benes_route_native requires power-of-two N >= 2")
    n_stages = 2 * (N.bit_length() - 1) - 1
    out = np.zeros((n_stages, (N + 7) // 8), dtype=np.uint8)
    rc = lib.benes_route(
        perm.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        N, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    if rc != 0:
        raise ValueError("invalid permutation for benes_route")
    return out
