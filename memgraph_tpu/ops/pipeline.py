"""mglane device kernels: whole read pipelines compiled onto the
semiring core.

The columnar lane (query/plan/parallel.py) already collapses an
eligible ``filter -> [expand] -> aggregate`` tail into whole-column
host-numpy kernels. This module is the DEVICE half of the same lane:
each recognized pipeline *shape* is compiled ONCE (per plan-cache
fingerprint, see query/plan/lane.py) into a single jitted XLA program
in which the predicate masks, the expansion and the aggregate epilogue
are fused — masks are applied with ``where(mask, v, identity)`` inside
the reduction (GraphBLAST's masked-SpMV formulation), never as a
gather-then-filter materialization.

Three program families:

  * ``masked_aggregate`` — columnar predicate masks over stacked int32
    property columns + fused count/sum/min/max epilogues. Used by both
    the scan tail and the one-hop edge-table tail (an edge snapshot is
    just another column set).
  * ``hop_counts`` — 1–2 hop expansion counts from a masked source
    frontier: ``x1 = A^T ⊕.⊗ s`` over the **plus_first** semiring
    (path multiplicities), chained for the second hop, with the
    self-loop edge-uniqueness correction and an optional **or_and**
    style distinct-target epilogue (``count(DISTINCT m)`` is a
    reachability popcount). Rides :func:`ops.semiring.spmv`.
  * ``masked_topk`` — ORDER BY <int key> LIMIT k as one fused
    mask + stable argsort program (nulls ranked per openCypher:
    last ascending, first descending).

Exactness discipline (this jax build keeps x64 disabled): columns are
admitted only when every value fits int32; predicate compares run in
int32 (bit-exact vs the row path); count/sum epilogues accumulate in
int32 with an f32 absolute-mass shadow — the host refuses the result
(typed ``precision_overflow`` fallback) unless the shadow proves no
int32 partial could have wrapped (mass < 2^30; path-count chains
additionally prove every per-node multiplicity stayed under f32's 2^24
integer range). Anything the discipline cannot prove falls back to the
host columnar path, which is exact by construction.

Shapes are padded to power-of-two buckets before dispatch, so the
compile count is O(shapes x log(size)) — the same bounded-bucket
contract the PPR serving lanes carry, checked statically by
tools/mgxla (``segment:lane_*`` contracts: zero collectives, no f64,
no host callbacks).
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from ..utils.locks import tracked_lock
from ..utils.sanitize import shared_field, shared_read, shared_write

#: device dispatch pays off only past this row/edge count (below it the
#: host columnar sweep wins); USING PARALLEL EXECUTION forces through
LANE_MIN_ROWS = int(os.environ.get("MEMGRAPH_TPU_LANE_MIN_ROWS", 4096))

#: f32 integer-exactness ceiling for per-node path multiplicities
_F24 = float(1 << 24)
#: int32 no-partial-wrap ceiling for the f32 mass shadows
_I30 = float(1 << 30)

#: predicate opcodes (static program structure; rhs stays traced)
_OPS = ("=", "<>", "<", "<=", ">", ">=", "present")

#: int32 identities for masked min/max
_I32_MAX = np.int32(2**31 - 1)
_I32_MIN = np.int32(-(2**31) + 1)


class LaneRefused(Exception):
    """Typed device-lane refusal; ``reason`` feeds
    ``lane.fallback_total.<reason>`` and the per-fingerprint registry."""

    def __init__(self, reason: str, detail: str = "") -> None:
        super().__init__(detail or reason)
        self.reason = reason


def _bucket(n: int, floor: int = 1024) -> int:
    """Power-of-two padding bucket: bounded distinct compiled shapes."""
    b = floor
    while b < n:
        b <<= 1
    return b


def _pad(arr: np.ndarray, size: int, fill) -> np.ndarray:
    if len(arr) == size:
        return arr
    out = np.full(size, fill, dtype=arr.dtype)
    out[: len(arr)] = arr
    return out


# --------------------------------------------------------------------------
# program cache (fingerprint-keyed bookkeeping lives in LaneRegistry;
# programs themselves are keyed structurally so identical shapes from
# different fingerprints share one executable)
# --------------------------------------------------------------------------

_PROGRAM_CACHE: dict = {}
_program_lock = threading.Lock()


def _get_program(key, build, *build_args):
    """MG008-shaped memo: get-then-build-then-store under one lock, with
    compile accounting (lane.compiled_total / compile-latency histogram
    / the ``lane_compile`` PROFILE stage)."""
    fn = _PROGRAM_CACHE.get(key)
    if fn is not None:
        return fn
    from ..observability import stats as mgstats
    from ..observability.metrics import global_metrics
    from ..utils.jax_cache import ensure_compile_cache
    ensure_compile_cache()
    with _program_lock:
        fn = _PROGRAM_CACHE.get(key)
        if fn is None:
            t0 = time.perf_counter()
            fn = build(*build_args)
            _PROGRAM_CACHE[key] = fn
            dt = time.perf_counter() - t0
            global_metrics.increment("lane.compiled_total")
            global_metrics.observe("lane.compile_latency_sec", dt)
            global_metrics.set_gauge("lane.resident",
                                     float(len(_PROGRAM_CACHE)))
            mgstats.record_stage("lane_compile", dt)
    return fn


def resident_programs() -> int:
    return len(_PROGRAM_CACHE)


def drop_programs() -> None:
    """Schema-change invalidation: drop every compiled lane program
    (query/plan/lane.py calls this from the plan-cache invalidation
    hook — a lane compiled under dropped DDL must never serve)."""
    from ..observability.metrics import global_metrics
    with _program_lock:
        _PROGRAM_CACHE.clear()
    global_metrics.set_gauge("lane.resident", 0.0)


# --------------------------------------------------------------------------
# per-fingerprint lane registry (compiles / hits / typed fallbacks)
# --------------------------------------------------------------------------


class LaneRegistry:
    """Per-plan-cache-fingerprint lane accounting, surfaced as the
    ``lane`` section of ``GET /stats``. Plan-time refusals (shape never
    compiled) land under the ``"<plan>"`` pseudo-fingerprint."""

    def __init__(self) -> None:
        self._lock = tracked_lock("LaneRegistry._lock")
        self._by_fp: dict[str, dict] = {}
        shared_field(self, "_by_fp")

    def _entry(self, fp: str | None) -> dict:
        key = fp or "<plan>"
        # mglint: disable=MG006,MG007 — every caller holds self._lock
        # around this helper (leaf lock; intraprocedural analysis
        # cannot see the caller's lock region)
        e = self._by_fp.get(key)
        if e is None:
            e = self._by_fp[key] = {"compiled": 0, "hits": 0,  # mglint: disable=MG006,MG007 — under caller's self._lock
                                    "fallbacks": {}}
        return e

    def note_compiled(self, fp: str | None) -> None:
        with self._lock:
            shared_write(self, "_by_fp")
            self._entry(fp)["compiled"] += 1

    def note_hit(self, fp: str | None) -> None:
        from ..observability.metrics import global_metrics
        global_metrics.increment("lane.hit_total")
        with self._lock:
            shared_write(self, "_by_fp")
            self._entry(fp)["hits"] += 1

    def note_fallback(self, fp: str | None, reason: str) -> None:
        from ..observability.metrics import global_metrics
        global_metrics.increment(f"lane.fallback_total.{reason}")
        with self._lock:
            shared_write(self, "_by_fp")
            fb = self._entry(fp)["fallbacks"]
            fb[reason] = fb.get(reason, 0) + 1

    def compiles_for(self, fp: str | None) -> int:
        with self._lock:
            shared_read(self, "_by_fp")
            return self._entry(fp)["compiled"]

    def reset(self) -> None:
        with self._lock:
            shared_write(self, "_by_fp")
            self._by_fp.clear()

    def snapshot(self) -> dict:
        with self._lock:
            shared_read(self, "_by_fp")
            return {fp: {"compiled": e["compiled"], "hits": e["hits"],
                         "fallbacks": dict(e["fallbacks"])}
                    for fp, e in self._by_fp.items()}


LANE_REGISTRY = LaneRegistry()


def lane_stats() -> dict:
    """The ``lane`` section of ``GET /stats``."""
    return {"resident_programs": resident_programs(),
            "fingerprints": LANE_REGISTRY.snapshot()}


# --------------------------------------------------------------------------
# masked aggregate program (scan tail + one-hop edge tail)
# --------------------------------------------------------------------------


def _compare(v, r, op):
    import jax.numpy as jnp
    if op == "=":
        return v == r
    if op == "<>":
        return v != r
    if op == "<":
        return v < r
    if op == "<=":
        return v <= r
    if op == ">":
        return v > r
    if op == ">=":
        return v >= r
    return jnp.ones_like(v, dtype=bool)       # "present": presence only


def _build_agg_program(preds: tuple, aggs: tuple):
    """One fused program: predicate masks AND-folded into every
    aggregate's reduction via where(mask, v, identity) — never a
    gathered intermediate. Returns a flat tuple of int32/f32 scalars
    laid out per _AGG_WIDTH."""
    import jax
    import jax.numpy as jnp

    def run(vals, present, base, rhs):
        mask = base
        for i, (ci, op) in enumerate(preds):
            m = _compare(vals[ci], rhs[i], op)
            mask = mask & m & present[ci]
        outs = []
        mask_i = mask.astype(jnp.int32)
        for kind, ci in aggs:
            if ci is None:                    # count(*) / count(sym)
                outs.append(jnp.sum(mask_i))
                continue
            sel = mask & present[ci]
            v = vals[ci]
            if kind == "count":
                outs.append(jnp.sum(sel.astype(jnp.int32)))
            elif kind == "sum":
                sv = jnp.where(sel, v, 0)
                outs.append(jnp.sum(sv))
                outs.append(jnp.sum(jnp.where(
                    sel, jnp.abs(v.astype(jnp.float32)), 0.0)))
            elif kind == "min":
                outs.append(jnp.min(jnp.where(sel, v, _I32_MAX)))
                outs.append(jnp.sum(sel.astype(jnp.int32)))
            else:                             # max
                outs.append(jnp.max(jnp.where(sel, v, _I32_MIN)))
                outs.append(jnp.sum(sel.astype(jnp.int32)))
        return tuple(outs)

    return jax.jit(run)


def masked_aggregate(preds: tuple, aggs: tuple, vals: np.ndarray,
                     present: np.ndarray, base: np.ndarray,
                     rhs: list, fingerprint: str | None = None) -> list:
    """Dispatch one compiled scan/expand aggregate.

    ``vals``/``present`` are (C, n) int32 / bool stacks; ``preds`` is a
    static tuple of (col_idx, op); ``aggs`` a static tuple of
    (kind, col_idx|None); ``rhs`` the traced per-predicate int32
    right-hand sides. Returns python aggregate values in ``aggs``
    order; raises :class:`LaneRefused` when the exactness witness
    cannot prove the int32 accumulation safe.
    """
    from ..observability import stats as mgstats
    n = vals.shape[1] if vals.size else len(base)
    nb = _bucket(max(n, 1))
    key = ("agg", preds, aggs, vals.shape[0], nb)
    was = key in _PROGRAM_CACHE
    fn = _get_program(key, _build_agg_program, preds, aggs)
    if not was:
        LANE_REGISTRY.note_compiled(fingerprint)
    t0 = time.perf_counter()
    if n != nb:
        vals = np.concatenate(
            [vals, np.zeros((vals.shape[0], nb - n), np.int32)], axis=1)
        present = np.concatenate(
            [present, np.zeros((present.shape[0], nb - n), bool)], axis=1)
        base = _pad(base, nb, False)
    rhs_arr = np.asarray(rhs, dtype=np.int32) if rhs else \
        np.zeros(0, dtype=np.int32)
    mgstats.record_stage("lane_dispatch", time.perf_counter() - t0)
    t0 = time.perf_counter()
    raw = [np.asarray(x) for x in fn(vals, present, base, rhs_arr)]
    mgstats.record_stage("lane_iterate", time.perf_counter() - t0)

    out = []
    i = 0
    for kind, ci in aggs:
        if ci is None or kind == "count":
            out.append(int(raw[i]))
            i += 1
        elif kind == "sum":
            total, mass = int(raw[i]), float(raw[i + 1])
            i += 2
            if mass >= _I30:
                raise LaneRefused("precision_overflow",
                                  f"sum mass {mass:.3g} >= 2^30")
            out.append(total)
        else:                                  # min / max
            val, cnt = int(raw[i]), int(raw[i + 1])
            i += 2
            out.append(val if cnt else None)
    return out


# --------------------------------------------------------------------------
# hop-count program (1–2 hop expansion from a masked frontier)
# --------------------------------------------------------------------------


def _build_hops_program(hops: int, include_lower: bool, edge_unique: bool,
                        need_rows: bool, need_distinct: bool, n_out: int):
    """Masked plus_first SpMV chain over the semiring core. All masks
    arrive as traced (n,)/(e,) arrays so one program serves every
    predicate/parameter combination of the shape."""
    import jax
    import jax.numpy as jnp

    from . import semiring as S

    def run(src, dst, emask, smask, midmask, tmask):
        x0 = smask.astype(jnp.float32)
        x1 = S.spmv("plus_first", x0, src, dst, n_out=n_out, mask=emask)
        p = jnp.zeros(n_out, dtype=jnp.float32)
        max1 = jnp.max(x1)
        if hops == 2:
            x1m = x1 * midmask
            x2 = S.spmv("plus_first", x1m, src, dst, n_out=n_out,
                        mask=emask)
            p2 = x2 * tmask
            if edge_unique:
                # the ONLY length-2 path reusing its edge is a source
                # self-loop traversed twice: subtract one per such edge
                w = x0 * midmask
                sl = S.spmv("plus_first", w, src, dst, n_out=n_out,
                            mask=emask & (src == dst))
                p2 = p2 - sl * tmask
            p = p + p2
            max2 = jnp.max(x2)
        else:
            max2 = jnp.float32(0.0)
        if hops == 1 or include_lower:
            p = p + x1 * tmask
        outs = [max1, max2, jnp.sum(p)]
        if need_rows:
            outs.append(jnp.sum(p.astype(jnp.int32)))
        if need_distinct:
            outs.append(jnp.sum((p > 0.5).astype(jnp.int32)))
        return tuple(outs)

    return jax.jit(run)


def stage_edges(src: np.ndarray, dst: np.ndarray,
                emask: np.ndarray) -> tuple:
    """Pad the edge arrays to their bucket and ship them to the device
    ONCE. Callers cache the staged tuple per (topology version, edge
    types, direction) — the per-query hop dispatch then moves only the
    O(n) node masks, which is what makes the lane's per-query export
    cost zero on an unchanged graph (the PR 14 residency contract)."""
    import jax
    e = len(src)
    eb = _bucket(max(e, 1))
    return (jax.device_put(_pad(np.asarray(src, np.int32), eb, 0)),
            jax.device_put(_pad(np.asarray(dst, np.int32), eb, 0)),
            jax.device_put(_pad(np.asarray(emask, bool), eb, False)),
            eb)


def hop_counts(src, dst, emask, smask: np.ndarray,
               midmask: np.ndarray, tmask: np.ndarray, n_nodes: int, *,
               hops: int, include_lower: bool = False,
               edge_unique: bool = True, need_rows: bool = True,
               need_distinct: bool = False,
               fingerprint: str | None = None) -> dict:
    """Run a compiled 1–2 hop count. ``src``/``dst``/``emask`` may be a
    :func:`stage_edges` result (already padded + device-resident) or
    raw host arrays. Returns {"rows": int, "distinct": int} (keys per
    request); raises :class:`LaneRefused` when the f32 multiplicity
    witness trips."""
    from ..observability import stats as mgstats
    t0 = time.perf_counter()
    n = int(n_nodes)
    nb = _bucket(max(n, 1))
    if isinstance(src, np.ndarray):
        src, dst, emask, eb = stage_edges(src, dst, emask)
    else:
        eb = len(src)
    smask = _pad(np.asarray(smask, bool), nb, False)
    midmask = _pad(np.asarray(midmask, np.float32), nb, 0.0)
    tmask = _pad(np.asarray(tmask, np.float32), nb, 0.0)
    key = ("hops", hops, include_lower, edge_unique, need_rows,
           need_distinct, eb, nb)
    was = key in _PROGRAM_CACHE
    fn = _get_program(key, _build_hops_program, hops, include_lower,
                      edge_unique, need_rows, need_distinct, nb)
    if not was:
        LANE_REGISTRY.note_compiled(fingerprint)
    mgstats.record_stage("lane_dispatch", time.perf_counter() - t0)
    t0 = time.perf_counter()
    raw = [np.asarray(x) for x in
           fn(src, dst, emask, smask, midmask, tmask)]
    mgstats.record_stage("lane_iterate", time.perf_counter() - t0)
    max1, max2, total_f = float(raw[0]), float(raw[1]), float(raw[2])
    if max1 >= _F24 or max2 >= _F24:
        raise LaneRefused("precision_overflow",
                          "per-node path multiplicity >= 2^24")
    if total_f >= _I30:
        raise LaneRefused("precision_overflow",
                          f"path total {total_f:.3g} >= 2^30")
    out: dict = {}
    i = 3
    if need_rows:
        out["rows"] = int(raw[i])
        i += 1
    if need_distinct:
        out["distinct"] = int(raw[i])
    return out


# --------------------------------------------------------------------------
# top-k ORDER BY program
# --------------------------------------------------------------------------

#: null ordering sentinels — finite so they sort between real keys
#: (|v| < 2^24 admitted) and the +inf "predicate excluded" sentinel
_NULL_LAST = np.float32(3.0e38)
_NULL_FIRST = np.float32(-3.0e38)


def _build_topk_program(preds: tuple, ascending: bool):
    """Fused mask + stable ascending argsort. Nulls rank last under ASC
    and first under DESC (openCypher orderability); rows excluded by a
    predicate sort to the very end, past every included row."""
    import jax
    import jax.numpy as jnp

    def run(vals, present, keyv, keyp, rhs):
        mask = jnp.ones_like(keyp)
        for i, (ci, op) in enumerate(preds):
            m = _compare(vals[ci], rhs[i], op)
            mask = mask & m & present[ci]
        kf = keyv.astype(jnp.float32)
        if not ascending:
            kf = -kf
        null_rank = _NULL_LAST if ascending else _NULL_FIRST
        kf = jnp.where(keyp, kf, null_rank)
        kf = jnp.where(mask, kf, jnp.float32(np.inf))
        order = jnp.argsort(kf)                # stable: ties keep row order
        return order, jnp.sum(mask.astype(jnp.int32))

    return jax.jit(run)


def masked_topk(preds: tuple, ascending: bool, vals: np.ndarray,
                present: np.ndarray, keyv: np.ndarray, keyp: np.ndarray,
                rhs: list, fingerprint: str | None = None):
    """Returns (order, n_included): row indices in final ORDER BY order
    (callers take the first min(k, n_included))."""
    from ..observability import stats as mgstats
    n = len(keyv)
    nb = _bucket(max(n, 1))
    key = ("topk", preds, ascending, vals.shape[0], nb)
    was = key in _PROGRAM_CACHE
    fn = _get_program(key, _build_topk_program, preds, ascending)
    if not was:
        LANE_REGISTRY.note_compiled(fingerprint)
    t0 = time.perf_counter()
    if n != nb:
        vals = np.concatenate(
            [vals, np.zeros((vals.shape[0], nb - n), np.int32)], axis=1)
        present = np.concatenate(
            [present, np.zeros((present.shape[0], nb - n), bool)], axis=1)
        keyv = _pad(keyv, nb, np.int32(0))
        keyp = _pad(keyp, nb, False)
    rhs_arr = np.asarray(rhs, dtype=np.int32) if rhs else \
        np.zeros(0, dtype=np.int32)
    mgstats.record_stage("lane_dispatch", time.perf_counter() - t0)
    t0 = time.perf_counter()
    order, count = fn(vals, present, keyv, keyp, rhs_arr)
    order = np.asarray(order)
    count = int(count)
    mgstats.record_stage("lane_iterate", time.perf_counter() - t0)
    return order, count


# --------------------------------------------------------------------------
# host-side column admission (exactness gate) + device staging
# --------------------------------------------------------------------------


def i32_column(col) -> np.ndarray | None:
    """An ops/columnar.py Column as an int32 value array, or None when
    the lane's exactness discipline cannot admit it (float columns,
    ints beyond int32, "other" kinds). The verdict is cached on the
    column — snapshots live per topology version, so this runs once per
    (version, column)."""
    cached = getattr(col, "_lane_i32", False)
    if cached is not False:
        return cached
    out = None
    if col.kind in ("int", "bool", "str") and col.values is not None:
        if col.kind == "int":
            v = col.values
            sel = v[col.present] if col.present.any() else v[:0]
            if sel.size == 0 or (int(sel.min()) > -(2**31)
                                 and int(sel.max()) < 2**31):
                out = v.astype(np.int32)
        else:
            out = col.values.astype(np.int32)
    try:
        col._lane_i32 = out
    except AttributeError:
        pass
    return out
