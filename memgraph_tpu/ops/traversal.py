"""Whole-graph traversal kernels on the semiring core: BFS levels,
single-source shortest paths.

Device-side counterparts of the traversal algorithms the reference embeds
in its ExpandVariable operator (BFS/weighted shortest path,
/root/reference/src/query/plan/operator.hpp:1140) for the *analytics*
regime: when the query wants distances/paths from a source over the whole
graph, a min-plus semiring fixpoint (Bellman-Ford: gather + ⊕=min until
fixpoint) beats pull-based expansion by orders of magnitude on TPU.

BFS additionally rides the core's direction-optimizing push/pull
selection (semiring.select_pull, the Beamer/GraphBLAST heuristic): a
sparse frontier relaxes push-style (frontier-masked contributions), a
dense one pulls over every edge — both exact, chosen per level from the
frontier's out-edge mass.

The point-query regime (short anchored expansions) stays on the host
executor, which walks adjacency directly — same split the reference makes
between operator-embedded traversals and MAGE whole-graph algorithms.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from . import semiring as S
from .csr import DeviceGraph

INF = jnp.float32(3.4e38)


def _sssp_step_directed(dist, A, env, P, n_out):
    """min-plus relaxation: cand[v] = min over edges (u,v) of d[u]+w."""
    cand = S.spmv("min_plus", dist, A["src"], A["dst"], A["w"],
                  n_out=n_out)
    return jnp.minimum(dist, cand)


def _sssp_step_undirected(dist, A, env, P, n_out):
    """Directed pass then the reverse orientation over the UPDATED
    distances (Gauss-Seidel flavor: halves the round count)."""
    new = _sssp_step_directed(dist, A, env, P, n_out)
    cand_b = S.spmv("min_plus", new, A["dst"], A["src"], A["w"],
                    n_out=n_out)
    return jnp.minimum(new, cand_b)


def _sssp_epilogue(dist, new, env, P):
    return new, jnp.any(new < dist)


def sssp(graph: DeviceGraph, source: int, weighted: bool = True,
         directed: bool = True, max_iterations: int = 10_000):
    """Bellman-Ford SSSP as a min-plus fixpoint. Returns
    (dist[:n_nodes] float32, iterations); unreachable nodes get +inf.
    With weighted=False computes hop counts (= BFS levels)."""
    w = graph.weights if weighted else jnp.where(
        jnp.arange(graph.e_pad) < graph.n_edges, 1.0, INF).astype(jnp.float32)
    if weighted:
        # padding edges have weight 0 into the sink row — force them inert
        w = jnp.where(jnp.arange(graph.e_pad) < graph.n_edges, w, INF)
    dist0 = np.full((graph.n_pad,), float(INF), dtype=np.float32)
    dist0[source] = 0.0
    dist, _, iters = S.fixpoint(
        "min_plus",
        arrays={"src": graph.src_idx, "dst": graph.col_idx, "w": w},
        x0=jnp.asarray(dist0), n_out=graph.n_pad,
        step=(_sssp_step_directed if directed
              else _sssp_step_undirected),
        epilogue=_sssp_epilogue, max_iterations=max_iterations,
        metric="changed")
    out = dist[:graph.n_nodes]
    return jnp.where(out >= INF / 2, jnp.inf, out), int(iters)


def _bfs_step(x, A, env, P, n_out):
    """Direction-optimizing BFS relaxation: push (frontier-masked
    contributions) while the frontier's out-edge mass is below
    n_edges / alpha, pull (all edges) once it saturates.  Both sides
    are exact for the monotone level recurrence; the selector only
    changes the executed formulation."""
    dist, frontier = x
    pull = S.select_pull(frontier, A["deg"], P["n_edges"])
    new = jax.lax.cond(
        pull,
        lambda d: S.spmv("min_plus", d, A["src"], A["dst"], A["w"],
                         n_out=n_out),
        lambda d: S.spmv("min_plus", d, A["src"], A["dst"], A["w"],
                         n_out=n_out, frontier=frontier),
        dist)
    return jnp.minimum(dist, new)


def _bfs_epilogue(x, new, env, P):
    dist, _frontier = x
    new_frontier = new < dist
    return (new, new_frontier), jnp.any(new_frontier)


def do_bfs(graph: DeviceGraph, source: int, max_iterations: int = 10_000):
    """Direction-optimizing BFS (directed): returns (dist f32 hops with
    +inf for unreachable, iterations).  Level-exact vs the plain
    min-plus fixpoint — only the push/pull execution strategy differs."""
    w = jnp.where(jnp.arange(graph.e_pad) < graph.n_edges, 1.0,
                  INF).astype(jnp.float32)
    dist0 = np.full((graph.n_pad,), float(INF), dtype=np.float32)
    dist0[source] = 0.0
    frontier0 = np.zeros(graph.n_pad, dtype=bool)
    frontier0[source] = True
    (dist, _), _, iters = S.fixpoint(
        "min_plus",
        arrays={"src": graph.src_idx, "dst": graph.col_idx, "w": w,
                "deg": graph.out_degree},
        params={"n_edges": np.float32(graph.n_edges)},
        x0=(jnp.asarray(dist0), jnp.asarray(frontier0)),
        n_out=graph.n_pad, step=_bfs_step, epilogue=_bfs_epilogue,
        max_iterations=max_iterations, metric="changed")
    out = dist[:graph.n_nodes]
    return jnp.where(out >= INF / 2, jnp.inf, out), int(iters)


def bfs_levels(graph: DeviceGraph, source: int, directed: bool = True,
               max_iterations: int = 10_000):
    """BFS levels from source (-1 for unreachable).  The directed case
    rides the direction-optimizing push/pull core path; the undirected
    view falls back to the Gauss-Seidel min-plus fixpoint."""
    if directed:
        dist, iters = do_bfs(graph, source, max_iterations=max_iterations)
    else:
        dist, iters = sssp(graph, source, weighted=False,
                           directed=directed,
                           max_iterations=max_iterations)
    levels = jnp.where(jnp.isinf(dist), -1, dist.astype(jnp.int32))
    return levels, iters


@partial(jax.jit, static_argnames=("n_pad", "max_iterations"))
def _mssp_kernel(src, dst, w, sources, n_pad: int, max_iterations: int):
    """Multi-source SSSP: one distance row per source, vmapped min-plus
    relaxation."""
    def single(source):
        dist0 = jnp.full((n_pad,), INF, dtype=jnp.float32).at[source].set(0.0)

        def body(carry):
            dist, _, it = carry
            cand = S.spmv("min_plus", dist, src, dst, w, n_out=n_pad)
            new = jnp.minimum(dist, cand)
            return new, jnp.any(new < dist), it + 1

        def cond(carry):
            _, changed, it = carry
            return changed & (it < max_iterations)

        dist, _, _ = jax.lax.while_loop(
            cond, body, (dist0, jnp.bool_(True), jnp.int32(0)))
        return dist

    return jax.vmap(single)(sources)


def multi_source_sssp(graph: DeviceGraph, sources, weighted: bool = True,
                      directed: bool = True, max_iterations: int = 10_000):
    """Distances from each of B sources: (B, n_nodes). Feeds betweenness
    sampling and graph-context retrieval (GraphRAG expansions)."""
    w = graph.weights if weighted else jnp.ones_like(graph.weights)
    w = jnp.where(jnp.arange(graph.e_pad) < graph.n_edges, w, INF)
    src, dst = graph.src_idx, graph.col_idx
    if not directed:
        src = jnp.concatenate([graph.src_idx, graph.col_idx])
        dst = jnp.concatenate([graph.col_idx, graph.src_idx])
        w = jnp.concatenate([w, w])
    dist = _mssp_kernel(src, dst, w,
                        jnp.asarray(sources, dtype=jnp.int32),
                        graph.n_pad, max_iterations)
    out = dist[:, :graph.n_nodes]
    return jnp.where(out >= INF / 2, jnp.inf, out)


def khop_neighborhood(graph: DeviceGraph, sources, k: int,
                      directed: bool = False):
    """Boolean mask (n_nodes,) of nodes within k hops of any source —
    the device-side version of the GraphRAG '2-hop expand' step.

    Each Bellman-Ford round extends reach by ≥1 hop, so k rounds settle
    every node within k hops."""
    levels = multi_source_sssp(graph, sources, weighted=False,
                               directed=directed, max_iterations=k + 1)
    return jnp.any(levels <= float(k), axis=0)
