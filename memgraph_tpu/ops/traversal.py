"""Whole-graph traversal kernels: BFS levels, single-source shortest paths.

Device-side counterparts of the traversal algorithms the reference embeds in
its ExpandVariable operator (BFS/weighted shortest path,
/root/reference/src/query/plan/operator.hpp:1140) for the *analytics* regime:
when the query wants distances/paths from a source over the whole graph, a
frontier-relaxation program (Bellman-Ford style: gather + segment-min until
fixpoint) beats pull-based expansion by orders of magnitude on TPU.

The point-query regime (short anchored expansions) stays on the host
executor, which walks adjacency directly — same split the reference makes
between operator-embedded traversals and MAGE whole-graph algorithms.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .csr import DeviceGraph

INF = jnp.float32(3.4e38)


@partial(jax.jit, static_argnames=("n_pad", "max_iterations", "directed"))
def _sssp_kernel(src, dst, w, source, n_pad: int, max_iterations: int,
                 directed: bool):
    dist0 = jnp.full((n_pad,), INF, dtype=jnp.float32).at[source].set(0.0)

    def body(carry):
        dist, _, it = carry
        relax = dist[src] + w
        cand = jax.ops.segment_min(relax, dst, num_segments=n_pad)
        new = jnp.minimum(dist, cand)
        if not directed:
            relax_b = new[dst] + w
            cand_b = jax.ops.segment_min(relax_b, src, num_segments=n_pad)
            new = jnp.minimum(new, cand_b)
        return new, jnp.any(new < dist), it + 1

    def cond(carry):
        _, changed, it = carry
        return changed & (it < max_iterations)

    dist, _, iters = jax.lax.while_loop(
        cond, body, (dist0, jnp.bool_(True), jnp.int32(0)))
    return dist, iters


def sssp(graph: DeviceGraph, source: int, weighted: bool = True,
         directed: bool = True, max_iterations: int = 10_000):
    """Bellman-Ford SSSP. Returns (dist[:n_nodes] float32, iterations);
    unreachable nodes get +inf. With weighted=False computes hop counts
    (= BFS levels)."""
    w = graph.weights if weighted else jnp.where(
        jnp.arange(graph.e_pad) < graph.n_edges, 1.0, INF).astype(jnp.float32)
    if weighted:
        # padding edges have weight 0 into the sink row — force them inert
        w = jnp.where(jnp.arange(graph.e_pad) < graph.n_edges, w, INF)
    dist, iters = _sssp_kernel(graph.src_idx, graph.col_idx, w,
                               jnp.int32(source), graph.n_pad,
                               max_iterations, directed)
    out = dist[:graph.n_nodes]
    return jnp.where(out >= INF / 2, jnp.inf, out), int(iters)


def bfs_levels(graph: DeviceGraph, source: int, directed: bool = True,
               max_iterations: int = 10_000):
    """BFS levels from source (-1 for unreachable)."""
    dist, iters = sssp(graph, source, weighted=False, directed=directed,
                       max_iterations=max_iterations)
    levels = jnp.where(jnp.isinf(dist), -1, dist.astype(jnp.int32))
    return levels, iters


@partial(jax.jit, static_argnames=("n_pad", "max_iterations"))
def _mssp_kernel(src, dst, w, sources, n_pad: int, max_iterations: int):
    """Multi-source SSSP: one distance row per source, vmapped relaxation."""
    def single(source):
        dist0 = jnp.full((n_pad,), INF, dtype=jnp.float32).at[source].set(0.0)

        def body(carry):
            dist, _, it = carry
            cand = jax.ops.segment_min(dist[src] + w, dst, num_segments=n_pad)
            new = jnp.minimum(dist, cand)
            return new, jnp.any(new < dist), it + 1

        def cond(carry):
            _, changed, it = carry
            return changed & (it < max_iterations)

        dist, _, _ = jax.lax.while_loop(
            cond, body, (dist0, jnp.bool_(True), jnp.int32(0)))
        return dist

    return jax.vmap(single)(sources)


def multi_source_sssp(graph: DeviceGraph, sources, weighted: bool = True,
                      directed: bool = True, max_iterations: int = 10_000):
    """Distances from each of B sources: (B, n_nodes). Feeds betweenness
    sampling and graph-context retrieval (GraphRAG expansions)."""
    w = graph.weights if weighted else jnp.ones_like(graph.weights)
    w = jnp.where(jnp.arange(graph.e_pad) < graph.n_edges, w, INF)
    src, dst = graph.src_idx, graph.col_idx
    if not directed:
        src = jnp.concatenate([graph.src_idx, graph.col_idx])
        dst = jnp.concatenate([graph.col_idx, graph.src_idx])
        w = jnp.concatenate([w, w])
    dist = _mssp_kernel(src, dst, w,
                        jnp.asarray(sources, dtype=jnp.int32),
                        graph.n_pad, max_iterations)
    out = dist[:, :graph.n_nodes]
    return jnp.where(out >= INF / 2, jnp.inf, out)


def khop_neighborhood(graph: DeviceGraph, sources, k: int,
                      directed: bool = False):
    """Boolean mask (n_nodes,) of nodes within k hops of any source —
    the device-side version of the GraphRAG '2-hop expand' step.

    Each Bellman-Ford round extends reach by ≥1 hop, so k rounds settle
    every node within k hops."""
    levels = multi_source_sssp(graph, sources, weighted=False,
                               directed=directed, max_iterations=k + 1)
    return jnp.any(levels <= float(k), axis=0)
