"""mgtier data plane: out-of-core streamed edge-block execution.

Every device path in the repo assumes the whole edge set fits in HBM;
this module is the half that makes beyond-HBM graphs executable at all.
The edge set is blocked partition-centrically (PR 6's
:class:`~.csr.ShardedCSR` — the SAME ``(P, per)`` + ``block_ptr`` layout
the mesh kernels shard across devices) but the rows stay PINNED
HOST-SIDE: a fixpoint iteration becomes a sweep that streams one
compressed row at a time through a double-buffered device window
(``parallel/distributed.py`` owns the execution loop), while the O(n)
iterate vectors stay device-resident. The streaming-SpMV architecture of
the reduced-precision FPGA PPR accelerator (PAPERS.md, arXiv:2009.10443)
applied at the host→HBM boundary instead of BRAM.

Block wire format (per ShardedCSR row ``p``):

* indices — LOSSLESS compression whenever ``block`` ≤ 65536: ``src``
  is local to shard ``p`` (``src_off`` uint16 + the shard base), and the
  (dst, src) sort within the row makes ``dst`` a concatenation of
  dst-shard runs bounded by ``block_ptr[p]``, so ``dst_off`` uint16 +
  the run's shard base reconstructs it exactly. 8 bytes/edge of int32
  indices become 4.
* weights — per request precision: ``f32`` ships them verbatim (the
  sweep stays bit-exact), ``bf16`` rounds them, ``int8`` symmetric
  per-block quantization (``w ≈ q · scale``, the
  :data:`~.semiring.PRECISION_BOUNDS` error budget); accumulation is
  always f32 on device.

Bytes per edge: 12 (f32, u16 off) → 8; bf16 → 6; int8 → 5 — a
1.5×/2×/2.4× transfer-volume cut vs the raw int32+f32 triple.

The admission story (``server/kernel_server.py``): a request whose
RESIDENT footprint exceeds the HBM budget no longer sheds outright —
:func:`admission_verdict` grows the third option, **streamed**, chosen
automatically when the streamed working set (iterate vectors + two
block buffers) still fits. ``ops/delta.py`` splices committed deltas
into the host rows and :meth:`TierCSR.apply_delta` re-encodes ONLY the
touched rows, so a churned beyond-HBM graph never re-ships cold.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np

from .csr import ShardedCSR, shard_edges
from ..observability.metrics import global_metrics

#: device-side byte budget for ONE streamed block buffer (two are live
#: at once under double buffering). Env-tunable so tests can force many
#: tiny blocks through the streaming path on small graphs.
DEFAULT_BLOCK_BYTES = 32 << 20

#: O(n) f32 iteration-state vectors the streamed fixpoints keep
#: device-resident (iterate, accumulator, inv_wsum, masks + headroom) —
#: kept in sync with the kernel server's resident-side estimate.
VECTOR_SLOTS = 8

#: largest vertex block the uint16 offset codec can address
U16_MAX_BLOCK = 1 << 16

#: wire bytes per edge WEIGHT at each precision
_W_BYTES = {"f32": 4, "bf16": 2, "int8": 1}


def block_bytes_budget() -> int:
    """Per-buffer block budget: MEMGRAPH_TPU_TIER_BLOCK_BYTES override,
    else :data:`DEFAULT_BLOCK_BYTES`."""
    env = os.environ.get("MEMGRAPH_TPU_TIER_BLOCK_BYTES")
    if env:
        try:
            return max(1 << 10, int(env))
        except ValueError:
            pass
    return DEFAULT_BLOCK_BYTES


def edge_wire_bytes(precision: str, u16: bool = True) -> int:
    """Wire bytes one edge costs in a streamed block."""
    idx = 4 if u16 else 8
    return idx + _W_BYTES[precision]


# --------------------------------------------------------------------------
# block codec
# --------------------------------------------------------------------------


def _bf16(w: np.ndarray) -> np.ndarray:
    import ml_dtypes  # jax dependency; host-side bfloat16 storage
    return w.astype(ml_dtypes.bfloat16)


@dataclass(frozen=True)
class HostBlock:
    """One compressed, host-pinned edge block (one ShardedCSR row).

    ``payload`` ships to the device verbatim (one ``jax.device_put`` of
    the dict); the decode runs INSIDE the jitted sweep kernel, so the
    wire bytes are what actually crosses the host→HBM boundary.
    """

    payload: dict          # name -> np.ndarray
    nbytes: int            # compressed wire bytes
    raw_nbytes: int        # int32 + f32 equivalent bytes


def _dst_runs(bounds: np.ndarray, per: int) -> np.ndarray:
    """Per-edge dst-shard index from the row's block_ptr boundaries —
    the HOST half of the codec; the device decode applies the identical
    searchsorted, so offsets round-trip exactly."""
    return np.searchsorted(bounds[1:], np.arange(per), side="right")


def pack_block(scsr: ShardedCSR, p: int, precision: str) -> HostBlock:
    """Encode ShardedCSR row ``p`` into its streamed wire format."""
    src = np.asarray(scsr.src[p])
    dst = np.asarray(scsr.dst[p])
    w = np.asarray(scsr.weights[p])
    raw = src.nbytes + dst.nbytes + w.nbytes
    u16 = scsr.block <= U16_MAX_BLOCK
    # real edges sort before the padding tail (padding dst = the sink
    # row n_nodes ≥ every real dst); rc masks weightless reductions
    rc = int(np.searchsorted(dst, scsr.n_nodes, side="left"))
    payload: dict = {"rc": np.int32(rc)}
    if u16:
        bounds = scsr.block_ptr[p].astype(np.int32)
        q = _dst_runs(bounds, scsr.per)
        payload["src_off"] = (src - np.int32(p * scsr.block)
                              ).astype(np.uint16)
        payload["dst_off"] = (dst - (q * scsr.block)).astype(np.uint16)
        payload["bounds"] = bounds
        payload["base"] = np.int32(p * scsr.block)
    else:
        payload["src"] = src
        payload["dst"] = dst
    if precision == "f32":
        payload["w"] = w
    elif precision == "bf16":
        payload["w"] = _bf16(w)
    elif precision == "int8":
        amax = float(np.max(np.abs(w))) if w.size else 0.0
        scale = np.float32(max(amax / 127.0, 1e-30))
        payload["w"] = np.clip(np.round(w / scale), -127, 127
                               ).astype(np.int8)
        payload["scale"] = scale
    else:
        raise ValueError(f"tier precision must be f32/bf16/int8, "
                         f"got {precision!r}")
    nbytes = sum(int(np.asarray(v).nbytes) for v in payload.values())
    return HostBlock(payload=payload, nbytes=nbytes, raw_nbytes=raw)


# --------------------------------------------------------------------------
# the paging plan
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class TierCSR:
    """Host-pinned paging plan: a ShardedCSR whose rows never all go to
    the device at once, plus their pre-encoded wire blocks."""

    scsr: ShardedCSR       # HOST layout — the delta-splice substrate
    blocks: tuple          # HostBlock per shard row
    precision: str

    @property
    def n_blocks(self) -> int:
        return self.scsr.n_shards

    @property
    def block(self) -> int:
        return self.scsr.block

    @property
    def per(self) -> int:
        return self.scsr.per

    @property
    def n_nodes(self) -> int:
        return self.scsr.n_nodes

    @property
    def n_edges(self) -> int:
        return self.scsr.n_edges

    @property
    def n_pad2(self) -> int:
        return self.scsr.n_pad2

    @property
    def u16(self) -> bool:
        return self.scsr.block <= U16_MAX_BLOCK

    @property
    def wire_bytes_per_sweep(self) -> int:
        """Bytes one full-edge-set sweep actually ships."""
        return sum(b.nbytes for b in self.blocks)

    @property
    def raw_bytes_per_sweep(self) -> int:
        """int32+f32-equivalent bytes the sweep represents."""
        return sum(b.raw_nbytes for b in self.blocks)

    def apply_delta(self, delta) -> "TierCSR | None":
        """Advance the plan by one EdgeDelta WITHOUT a cold re-encode.

        The splice (:func:`~.delta.apply_edge_delta`) rewrites only the
        shard rows the delta touches; this re-packs exactly those rows
        and reuses every other wire block untouched — a churned
        beyond-HBM graph keeps its encoded pages. Returns None when the
        splice itself cannot preserve the layout (row overflow /
        removal mismatch): the caller rebuilds via :func:`plan_tier`.
        """
        from .delta import apply_edge_delta
        new_scsr = apply_edge_delta(self.scsr, delta)
        if new_scsr is None:
            return None
        if new_scsr is self.scsr:      # empty delta
            return self
        block = self.scsr.block
        key_add = delta.add_src if self.scsr.by == "src" else delta.add_dst
        key_rem = delta.rem_src if self.scsr.by == "src" else delta.rem_dst
        touched = np.union1d(np.unique(key_add // block),
                             np.unique(key_rem // block)).astype(np.int64)
        blocks = list(self.blocks)
        for p in touched:
            blocks[int(p)] = pack_block(new_scsr, int(p), self.precision)
        global_metrics.increment("tier.blocks_repacked_total",
                                 len(touched))
        global_metrics.increment("tier.blocks_reused_total",
                                 len(blocks) - len(touched))
        return TierCSR(scsr=new_scsr, blocks=tuple(blocks),
                       precision=self.precision)


def tier_from_scsr(scsr: ShardedCSR, precision: str = "f32") -> TierCSR:
    """Pack an existing HOST ShardedCSR into a paging plan (the
    ``ops/delta.py`` path: the resident generation's host variant IS
    the substrate — no re-sort, no re-blocking)."""
    if not isinstance(scsr.src, np.ndarray):
        raise ValueError("tier_from_scsr needs the HOST-side layout")
    blocks = tuple(pack_block(scsr, p, precision)
                   for p in range(scsr.n_shards))
    return TierCSR(scsr=scsr, blocks=blocks, precision=precision)


def plan_blocks(n_nodes: int, n_edges: int, precision: str = "f32",
                block_bytes: int | None = None) -> int:
    """Pick the block count P: enough that one row's wire payload fits
    the per-buffer budget, enough that vertex blocks stay uint16-
    addressable, and ≥ 2 so the double buffer actually alternates."""
    bb = block_bytes or block_bytes_budget()
    wire = max(n_edges, 1) * edge_wire_bytes(precision, u16=True)
    p_budget = -(-wire // bb)
    # margin for shard_edges' block_multiple rounding
    p_u16 = -(-(n_nodes + 1) // (U16_MAX_BLOCK - 8))
    return max(2, int(p_budget), int(p_u16))


def plan_tier(src, dst, weights, n_nodes: int, *,
              precision: str = "f32", n_blocks: int | None = None,
              block_bytes: int | None = None) -> TierCSR:
    """Block a COO edge set into a host-pinned streamed paging plan."""
    if n_blocks is None:
        n_blocks = plan_blocks(n_nodes, len(np.asarray(src)), precision,
                               block_bytes)
    scsr = shard_edges(src, dst, weights, n_nodes, int(n_blocks),
                       by="src")
    return tier_from_scsr(scsr, precision)


# --------------------------------------------------------------------------
# admission estimates (the kernel server's third verdict)
# --------------------------------------------------------------------------

#: requests whose graph-shaped op can degrade to the streamed path
ADMISSION_VERDICTS = ("resident", "streamed", "shed")


#: device bytes ONE streamed edge costs at the sweep's compiled peak:
#: the wire offsets PLUS the int32 index reconstruction and f32
#: contribution temps the block decode materializes. The 2x-wire hand
#: count this replaced undercounted exactly that decode expansion
#: (pagerank sweep: 8 wire bytes, 32 at peak). wcc decodes weightless
#: (need_w=False) and prices lower — a flat worst-case would shed wcc
#: traffic that fits. Machine-checked within [1x, 2x] against the tier
#: sweep kernels' footprint models by tools/mgmem.
DECODED_EDGE_BYTES = {"pagerank": 36, "katz": 36, "wcc": 16}


def streamed_request_bytes(n_nodes: int, n_edges: int,
                           precision: str = "f32",
                           block_bytes: int | None = None,
                           algorithm: str = "pagerank") -> int:
    """Working-set estimate for a STREAMED run: the O(n) device-resident
    iteration vectors (over the PLAN's padded node count, not the raw
    one) plus one resident block at its decoded sweep peak plus the next
    block's wire payload in flight — the whole point being that the O(E)
    term is bounded by the buffer budget, not the edge count.

    Priced per the plan :func:`plan_blocks` would actually build; shard
    skew can inflate a real plan's per-block capacity past the even
    split priced here (documented residual, ROADMAP item 2)."""
    bb = block_bytes or block_bytes_budget()
    p = plan_blocks(n_nodes, n_edges, precision, bb)
    block = _ceil8(-(-(n_nodes + 1) // p))
    n_pad2 = p * block
    e_blk = _ceil8(-(-max(n_edges, 1) // p))
    vectors = n_pad2 * 4 * VECTOR_SLOTS
    decoded = e_blk * DECODED_EDGE_BYTES.get(str(algorithm),
                                             DECODED_EDGE_BYTES["pagerank"])
    wire_in_flight = e_blk * edge_wire_bytes(precision, u16=True)
    return vectors + decoded + wire_in_flight


def _ceil8(n: int) -> int:
    """shard_edges' block_multiple=8 rounding, mirrored for pricing."""
    return -(-int(n) // 8) * 8


def admission_verdict(est_resident: int, budget: int, *, n_nodes: int,
                      n_edges: int, streamable: bool = True,
                      precision: str = "f32",
                      algorithm: str = "pagerank") -> tuple[str, int]:
    """resident / streamed / shed, from the estimated footprints.

    Returns ``(verdict, est_bytes)`` where ``est_bytes`` is the
    footprint of the CHOSEN execution mode (callers log/expose it).
    Oversized-but-streamable requests degrade gracefully; shed remains
    the honest answer when even the streamed working set (or the op)
    cannot fit the budget.
    """
    if est_resident <= budget:
        return "resident", int(est_resident)
    est_streamed = streamed_request_bytes(n_nodes, n_edges, precision,
                                          algorithm=algorithm)
    if streamable and est_streamed <= budget:
        return "streamed", int(est_streamed)
    return "shed", int(est_streamed)
