"""PageRank on the semiring kernel core (ops/semiring.py).

TPU-native counterpart of the reference's PageRank modules
(/root/reference/mage/cpp/pagerank_module/, CUDA analog
mage/cpp/cugraph_module/algorithms/pagerank.cu, online variant
query_modules/pagerank_module/pagerank_online_module.cpp): weighted power
iteration as a plus-times semiring fixpoint — the setup hoists the
per-edge `w / wsum[src]` multipliers, the fused epilogue applies the
damping update (semiring.pagerank_update, shared with every backend) and
the L1 convergence partial inside the matvec body. Dangling-node mass is
redistributed uniformly each round (standard PageRank semantics).

All shapes static; padding edges carry weight 0 into a sink row, so they
contribute nothing.  `precision=` selects the f32 (exact) / bf16 /
int8-streaming variants (semiring.PRECISION_BOUNDS documents the bounds).
"""

from __future__ import annotations

import threading
from functools import partial

import jax.numpy as jnp
import numpy as np

from . import semiring as S
from .csr import DeviceGraph

# back-compat alias; the routing threshold lives with the dispatch now
MXU_MIN_EDGES = S.MXU_MIN_EDGES

# serializes the expensive plan build PER GRAPH so concurrent first CALLs
# on one snapshot don't each run it (~35s host-side at 10M edges), while
# unrelated graphs build in parallel; the registry lock only guards the
# per-graph lock creation
_mxu_locks_guard = threading.Lock()


def _pagerank_setup(A, P, n_out):
    """Loop invariants: hoisted edge multipliers + dangling/valid masks.
    CSR order is src-sorted, so the out-weight sum takes the sorted
    lowering; the per-edge multiplier is gathered ONCE per run."""
    n_nodes = P["n_nodes"]
    n_f = n_nodes.astype(jnp.float32)
    valid = (jnp.arange(n_out, dtype=jnp.int32) < n_nodes)
    valid_f = valid.astype(jnp.float32)
    wsum = S.edge_reduce("sum", A["csr_w"], A["csr_src"], n_out,
                         sorted=True)
    inv_wsum = jnp.where(wsum > 0, 1.0 / jnp.maximum(wsum, 1e-30), 0.0)
    dangling = valid & (wsum <= 0)
    dangling_f = dangling.astype(jnp.float32)
    edge_mult = A["w"] * inv_wsum[A["src"]]  # hoisted: one gather per run
    return {"w": edge_mult, "valid_f": valid_f, "dangling_f": dangling_f,
            "n_f": n_f, "x0": valid_f / n_f}


def _pagerank_epilogue(rank, acc, env, P):
    """FUSED-PAGERANK epilogue: damping update + L1 convergence partial
    computed on the accumulator inside the while body."""
    dangling_mass = jnp.sum(rank * env["dangling_f"])
    new_rank = S.pagerank_update(acc, dangling_mass, env["valid_f"],
                                 env["n_f"], P["damping"])
    err = jnp.sum(jnp.abs(new_rank - rank))
    return new_rank, err


# a delta larger than this fraction of the base edge set triggers a full
# replan (padding inflation + per-iter delta cost outgrow the saving)
DELTA_RECOMPACT_FRACTION = 0.10


def _edge_diff(base_g: DeviceGraph, new_g: DeviceGraph, changed_gids):
    """Multiset edge diff restricted to vertices in changed_gids.
    Returns (added, removed) as (src, dst, w) tuples of host arrays, or
    None when the diff cannot be derived (node set changed, no host
    arrays kept, ...)."""
    if base_g.host_coo is None or new_g.host_coo is None:
        return None
    if base_g.n_nodes != new_g.n_nodes or \
            not np.array_equal(base_g.node_gids, new_g.node_gids):
        return None     # node set changed: dense ids shifted
    bitmap = np.zeros(new_g.n_nodes, dtype=bool)
    for gid in changed_gids:
        idx = new_g.gid_to_idx.get(gid)
        if idx is not None:
            bitmap[idx] = True
    os_, od, ow = base_g.host_coo
    ns_, nd, nw = new_g.host_coo
    o_sel = bitmap[os_]
    n_sel = bitmap[ns_]
    # multiset diff over (src, dst, w) rows: +1 for new, -1 for old
    rows = np.stack([
        np.concatenate([ns_[n_sel].astype(np.int64),
                        os_[o_sel].astype(np.int64)]),
        np.concatenate([nd[n_sel].astype(np.int64),
                        od[o_sel].astype(np.int64)]),
        np.concatenate([nw[n_sel], ow[o_sel]]).view(np.int32).astype(
            np.int64),
    ], axis=1)
    sign = np.concatenate([np.ones(int(n_sel.sum()), dtype=np.int64),
                           -np.ones(int(o_sel.sum()), dtype=np.int64)])
    uniq, inv = np.unique(rows, axis=0, return_inverse=True)
    counts = np.zeros(len(uniq), dtype=np.int64)
    np.add.at(counts, inv, sign)
    add_idx = np.repeat(np.arange(len(uniq)), np.maximum(counts, 0))
    rem_idx = np.repeat(np.arange(len(uniq)), np.maximum(-counts, 0))
    w_back = lambda col: col.astype(np.int32).view(np.float32)  # noqa: E731
    added = (uniq[add_idx, 0], uniq[add_idx, 1], w_back(uniq[add_idx, 2]))
    removed = (uniq[rem_idx, 0], uniq[rem_idx, 1], w_back(uniq[rem_idx, 2]))
    return added, removed


def _try_delta_plan(graph: DeviceGraph):
    """Derive this snapshot's MXU state from a predecessor's full plan
    via an O(changed-edges) DeltaPlan. None -> caller does a full build.
    """
    from . import spmv_mxu
    ctx = getattr(graph, "_delta_ctx", None)
    if ctx is None:
        return None
    base_g, changed_gids = ctx
    base_state = getattr(base_g, "_mxu_state", None)
    if base_state is None or base_state[0].wsum is None:
        return None
    base_plan = base_state[0]
    diff = _edge_diff(base_g, graph, changed_gids)
    if diff is None:
        return None
    (a_s, a_d, a_w), (r_s, r_d, r_w) = diff
    n_delta = len(a_s) + len(r_s)
    if n_delta == 0:
        return base_state    # property-only bump: plan still exact
    if n_delta > max(DELTA_RECOMPACT_FRACTION * base_g.n_edges, 1024):
        return None          # recompact: full replan is the better deal
    delta = spmv_mxu.build_delta_plan(base_plan, a_s, a_d, a_w,
                                      r_s, r_d, r_w)
    run = spmv_mxu.make_pagerank_kernel(base_plan, delta=delta)
    return (base_plan, run)


def _pagerank_via_mxu(graph: DeviceGraph, damping, max_iterations, tol,
                      precision: str = "f32", x0=None):
    """Large-graph path: gather-free MXU kernel with the plan cached on
    the (immutable) DeviceGraph snapshot. Successor snapshots of a
    mutated graph refresh O(delta) via DeltaPlan side-nets instead of
    replanning (reference analog: pagerank_online_module.cpp keeps
    incremental state for the same reason)."""
    from . import spmv_mxu
    cached = getattr(graph, "_mxu_state", None)
    if cached is None:
        with _mxu_locks_guard:
            lock = getattr(graph, "_mxu_build_lock", None)
            if lock is None:
                lock = threading.Lock()
                object.__setattr__(graph, "_mxu_build_lock", lock)
        with lock:
            cached = getattr(graph, "_mxu_state", None)
            if cached is None:
                cached = _try_delta_plan(graph)
                if cached is not None:
                    object.__setattr__(graph, "_mxu_state", cached)
            if cached is None:
                # true edges only: padding edges sort to the end (sinks)
                src = np.asarray(graph.src_idx)[:graph.n_edges]
                dst = np.asarray(graph.col_idx)[:graph.n_edges]
                w = np.asarray(graph.weights)[:graph.n_edges]
                plan = spmv_mxu.build_plan(src, dst, w, graph.n_nodes)
                cached = (plan, spmv_mxu.make_pagerank_kernel(plan))
                # DeviceGraph is frozen; bypass its setattr guard
                object.__setattr__(graph, "_mxu_state", cached)
                # full plans anchor future delta refreshes (GraphCache)
                object.__setattr__(graph, "_mxu_base_self", True)
    plan, run = cached
    if precision == "bf16":
        # bf16 Benes routing halves the dominant HBM traffic; cached
        # separately so the f32 kernel (delta-refresh anchor) survives
        run = getattr(graph, "_mxu_run_bf16", None)
        if run is None:
            run = spmv_mxu.make_pagerank_kernel(
                plan, route_dtype=jnp.bfloat16)
            object.__setattr__(graph, "_mxu_run_bf16", run)
    x0_flat = None
    if x0 is not None:
        # warm seed in the plan's OUT labeling (flat node space); the
        # kernel renormalizes nothing — pass unit mass in
        x0 = np.asarray(x0, dtype=np.float32)[:graph.n_nodes]
        total = float(x0.sum())
        if np.isfinite(total) and total > 0.0:
            x0_flat = np.zeros(len(plan.valid_out), dtype=np.float32)
            x0_flat[plan.out_relabel] = x0 / np.float32(total)
    with S.backend_extent("mxu", record_iterate=True):
        # None = uniform start computed on-device (saves a transfer)
        rank, err, iters = run(x0_flat, np.float32(damping),
                               int(max_iterations), np.float32(tol))
    return np.asarray(rank)[plan.out_relabel], float(err), int(iters)


def pagerank(graph: DeviceGraph, damping: float = 0.85,
             max_iterations: int = 100, tol: float = 1e-6, mesh=None,
             precision: str = "f32", x0=None):
    """Returns (ranks[:n_nodes], error, iterations).

    `mesh` routes the computation through the multi-chip layer
    (parallel/analytics.py): a MeshContext, a jax Mesh, a device count,
    or None (→ the MEMGRAPH_TPU_MESH_DEVICES env default; unset keeps
    the single-chip kernels). A mesh-of-1 runs the same sharded code
    path as any other size — single-device is a degeneracy, not a fork.

    `precision` — "f32" (exact), "bf16" (contributions rounded, f32
    accumulation) or "int8" (quantized streaming; segment backend only);
    error bounds: semiring.PRECISION_BOUNDS.

    `x0` — optional (n_nodes,) previous solution; warm-starts the
    fixpoint on every backend (ops/delta.py commit-then-CALL contract:
    PageRank is a contraction, any seed converges to the same answer at
    the same tol — the seed only cuts the iteration count).
    """
    from ..utils.jax_cache import ensure_compile_cache
    ensure_compile_cache()
    # MXU_MIN_EDGES read at call time: tests (and operators) tune the
    # threshold by monkeypatching this module attribute
    backend, ctx = S.route_backend(graph, mesh, semiring="plus_times",
                                   precision=precision,
                                   min_edges=MXU_MIN_EDGES)
    if backend == "mesh":
        from ..parallel.analytics import pagerank_mesh
        with S.backend_extent("mesh"):
            return pagerank_mesh(graph, ctx, damping=damping,
                                 max_iterations=max_iterations, tol=tol,
                                 precision=precision, x0=x0)
    if backend == "mxu":
        return _pagerank_via_mxu(graph, damping, max_iterations, tol,
                                 precision, x0=x0)
    x0_pad = None
    if x0 is not None:
        x0 = np.asarray(x0, dtype=np.float32)[:graph.n_nodes]
        total = float(x0.sum())
        if np.isfinite(total) and total > 0.0:
            buf = np.zeros(graph.n_pad, dtype=np.float32)
            buf[:len(x0)] = x0 / np.float32(total)
            x0_pad = jnp.asarray(buf)
    rank, err, iters = S.fixpoint(
        "plus_times",
        arrays={"src": graph.csc_src, "dst": graph.csc_dst,
                "w": graph.csc_weights,
                "csr_src": graph.src_idx, "csr_w": graph.weights},
        params={"n_nodes": np.int32(graph.n_nodes),
                "damping": np.float32(damping),
                "tol": np.float32(tol)},
        n_out=graph.n_pad, setup=_pagerank_setup,
        epilogue=_pagerank_epilogue, max_iterations=max_iterations,
        sorted=True, precision=precision, x0=x0_pad)
    return rank[:graph.n_nodes], float(err), int(iters)


def _ppr_setup(A, P, n_out):
    """PPR invariants: normalized restart vector + hoisted multipliers."""
    n_nodes = P["n_nodes"]
    valid = (jnp.arange(n_out, dtype=jnp.int32) < n_nodes)
    valid_f = valid.astype(jnp.float32)
    p = A["personalization"] * valid_f
    p = p / jnp.maximum(jnp.sum(p), 1e-30)
    wsum = S.edge_reduce("sum", A["csr_w"], A["csr_src"], n_out,
                         sorted=True)
    inv_wsum = jnp.where(wsum > 0, 1.0 / jnp.maximum(wsum, 1e-30), 0.0)
    dangling_f = (valid & (wsum <= 0)).astype(jnp.float32)
    edge_mult = A["w"] * inv_wsum[A["src"]]
    return {"w": edge_mult, "p": p, "dangling_f": dangling_f, "x0": p}


def _ppr_epilogue(rank, acc, env, P):
    """Fused PPR update: restart mass flows to the personalization
    vector (dangling mass included) instead of uniformly."""
    p = env["p"]
    dangling_mass = jnp.sum(rank * env["dangling_f"])
    new_rank = (1.0 - P["damping"]) * p \
        + P["damping"] * (acc + dangling_mass * p)
    err = jnp.sum(jnp.abs(new_rank - rank))
    return new_rank, err


def personalized_pagerank(graph: DeviceGraph, source_nodes,
                          damping: float = 0.85, max_iterations: int = 100,
                          tol: float = 1e-6, precision: str = "f32",
                          kernel=None, kernel_meta: dict | None = None):
    """PPR with restart mass on `source_nodes` (dense indices).

    Analog of mage/cpp/cugraph_module/algorithms/personalized_pagerank.cu.

    ``kernel`` routes the request through the resident kernel server's
    coalescing PPR plane (a socket path, ``True``/"1" for the default
    socket, or a client object with a ``ppr`` method): concurrent
    requests batch into one multi-source SpMM fixpoint and hit the
    server's change-log-invalidated result cache. ``kernel_meta``
    forwards serving metadata (graph_key / graph_version / delta — see
    server/kernel_server.py). A kernel-plane failure falls back to the
    in-process path LOUDLY.
    """
    if kernel is not None:
        got = _ppr_via_kernel(graph, source_nodes, damping, max_iterations,
                              tol, precision, kernel, kernel_meta)
        if got is not None:
            return got
    p = jnp.zeros(graph.n_pad, dtype=jnp.float32)
    p = p.at[jnp.asarray(source_nodes, dtype=jnp.int32)].set(1.0)
    rank, err, iters = S.fixpoint(
        "plus_times",
        arrays={"src": graph.csc_src, "dst": graph.csc_dst,
                "w": graph.csc_weights,
                "csr_src": graph.src_idx, "csr_w": graph.weights,
                "personalization": p},
        params={"n_nodes": np.int32(graph.n_nodes),
                "damping": np.float32(damping),
                "tol": np.float32(tol)},
        n_out=graph.n_pad, setup=_ppr_setup, epilogue=_ppr_epilogue,
        max_iterations=max_iterations, sorted=True, precision=precision)
    return rank[:graph.n_nodes], float(err), int(iters)


def _ppr_via_kernel(graph, source_nodes, damping, max_iterations, tol,
                    precision, kernel, kernel_meta):
    """Route one PPR through the resident server's coalescing plane.
    Returns (ranks, err, iters) or None (caller runs in-process)."""
    import logging
    from ..observability.metrics import global_metrics
    from ..server import kernel_server as ks
    meta = dict(kernel_meta or {})
    try:
        if hasattr(kernel, "ppr"):
            client = kernel
        else:
            sock = ks.DEFAULT_SOCKET if kernel in (True, "1", "default") \
                else str(kernel)
            client = ks.shared_client(sock)
        send_graph = meta.pop("send_graph", True)
        meta.pop("top_k", None)    # this entry point returns full ranks
        kwargs = {}
        if send_graph:
            src, dst, w = graph.host_coo if graph.host_coo is not None \
                else (np.asarray(graph.src_idx)[:graph.n_edges],
                      np.asarray(graph.col_idx)[:graph.n_edges],
                      np.asarray(graph.weights)[:graph.n_edges])
            kwargs.update(src=np.asarray(src, dtype=np.int64),
                          dst=np.asarray(dst, dtype=np.int64),
                          weights=np.asarray(w, dtype=np.float32))
        meta.setdefault("graph_key",
                        f"ppr:{id(graph)}:{graph.n_nodes}:{graph.n_edges}")
        h, out = client.ppr(
            sources=np.asarray(source_nodes, dtype=np.int32),
            n_nodes=graph.n_nodes, damping=float(damping),
            max_iterations=int(max_iterations), tol=float(tol),
            precision=precision, **meta, **kwargs)
        global_metrics.increment("analytics.kernel_routed_total")
        return (np.asarray(out["ranks"])[:graph.n_nodes],
                float(h.get("err", 0.0)), int(h.get("iters", 0)))
    except (ks.KernelServerError, ConnectionError, OSError) as e:
        global_metrics.increment("analytics.kernel_route_fallback_total")
        logging.getLogger(__name__).warning(
            "kernel-server PPR route failed (%s: %s); falling back to "
            "the in-process path", type(e).__name__, e)
        return None


# --------------------------------------------------------------------------
# batched multi-source PPR (the serving-plane SpMM fixpoint)
# --------------------------------------------------------------------------
#
# N concurrent personalization vectors are ONE (n, B) SpMM per iteration
# ("Accelerating Personalized PageRank Vector Computation", PAPERS.md):
# the edge gather, ⊗-combine and segment-⊕ run once over B lanes, so the
# dominant memory traffic (the edge stream) is amortized across every
# rider of the batch — the coalescing win the PPR serving plane banks on.
# Lanes are INDEPENDENT fixpoints: a converged column freezes (its value
# is the exact iterate whose L1 step error first dipped under tol, same
# as the sequential loop's stopping state), so batched f32 results are
# BIT-EXACT vs sequential `personalized_pagerank` regardless of how
# long slower batchmates keep iterating (tests/test_ppr_serving.py).

_PPR_BATCH_CACHE: dict = {}
_ppr_batch_cache_lock = threading.Lock()

#: batch lanes are padded up to these bucket widths so a serving
#: workload with jittery batch sizes reuses a handful of compiled
#: programs instead of one per size
_PPR_LANE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)


def _bucket_lanes(b: int) -> int:
    for cap in _PPR_LANE_BUCKETS:
        if b <= cap:
            return cap
    return b


def _build_ppr_batch(n_out: int, max_iterations: int, precision: str,
                     warm: bool):
    import jax

    def run(A, P, x0):
        # batched analog of _ppr_setup: identical hoisted invariants,
        # personalization columns normalized per lane
        n_nodes = P["n_nodes"]
        valid = (jnp.arange(n_out, dtype=jnp.int32) < n_nodes)
        valid_f = valid.astype(jnp.float32)
        pm = A["personalization"] * valid_f[:, None]
        pm = pm / jnp.maximum(jnp.sum(pm, axis=0), 1e-30)
        wsum = S.edge_reduce("sum", A["csr_w"], A["csr_src"], n_out,
                             sorted=True)
        inv_wsum = jnp.where(wsum > 0, 1.0 / jnp.maximum(wsum, 1e-30), 0.0)
        dangling_f = (valid & (wsum <= 0)).astype(jnp.float32)
        edge_mult = A["w"] * inv_wsum[A["src"]]
        x_init = x0 if warm else pm
        tol = P["tol"]
        n_lanes = pm.shape[1]

        def body(carry):
            x, done, err, iters, it = carry
            acc = S.spmv("plus_times", x, A["src"], A["dst"], edge_mult,
                         n_out=n_out, sorted=True, precision=precision)
            dangling_mass = jnp.sum(x * dangling_f[:, None], axis=0)
            new_x = (1.0 - P["damping"]) * pm \
                + P["damping"] * (acc + dangling_mass[None, :] * pm)
            new_err = jnp.sum(jnp.abs(new_x - x), axis=0)
            # freeze converged lanes: their retained iterate is exactly
            # the sequential loop's stopping state
            x = jnp.where(done[None, :], x, new_x)
            err = jnp.where(done, err, new_err)
            iters = jnp.where(done, iters, iters + 1)
            done = done | (err <= tol)
            return x, done, err, iters, it + 1

        def cond(carry):
            _x, done, _err, _iters, it = carry
            return (~jnp.all(done)) & (it < max_iterations)

        carry0 = (x_init, jnp.zeros(n_lanes, dtype=jnp.bool_),
                  jnp.full(n_lanes, jnp.inf, dtype=jnp.float32),
                  jnp.zeros(n_lanes, dtype=jnp.int32), jnp.int32(0))
        x, _done, err, iters, _it = jax.lax.while_loop(cond, body, carry0)
        return x, err, iters

    # the warm-start seed matrix is donated back to the (n_pad, B)
    # iterate — the serving plane builds a fresh x0 per batch, so the
    # seed never needs to outlive the call (cold runs pass x0=None:
    # nothing to donate, pm doubles as the start AND the restart vector)
    return jax.jit(run, donate_argnums=(2,))


def personalized_pagerank_batch(graph: DeviceGraph, source_sets,
                                damping: float = 0.85,
                                max_iterations: int = 100,
                                tol: float = 1e-6, precision: str = "f32",
                                x0=None, raw: bool = False):
    """B independent PPR fixpoints as ONE SpMM power iteration.

    ``source_sets`` is a list of dense-index lists (one per lane) or a
    prebuilt (n_pad, B) personalization matrix. ``x0`` optionally seeds
    lanes from cached vectors ((n_pad, B); the serving plane's
    warm-start path — PPR is a contraction, so ANY seed converges to
    the same fixpoint, just in fewer iterations).

    Returns (ranks (B, n_nodes), err (B,), iters (B,)). Lane counts are
    padded up to compile-amortizing buckets; padding lanes restart on
    lane 0's sources and are dropped before returning. ``raw=True``
    instead returns the DEVICE (n_pad, n_lanes) iterate (padding lanes
    included) so the caller can run on-device epilogues (top-k
    extraction) before paying the host transfer.
    """
    from ..utils.jax_cache import ensure_compile_cache
    ensure_compile_cache()
    if getattr(source_sets, "ndim", None) == 2:
        pm = np.asarray(source_sets, dtype=np.float32)
        n_req = pm.shape[1]
    else:
        n_req = len(source_sets)
        pm = np.zeros((graph.n_pad, n_req), dtype=np.float32)
        for lane, sources in enumerate(source_sets):
            pm[np.asarray(sources, dtype=np.int32), lane] = 1.0
    if n_req == 0:
        return (np.zeros((0, graph.n_nodes), dtype=np.float32),
                np.zeros(0, dtype=np.float32), np.zeros(0, dtype=np.int32))
    n_lanes = _bucket_lanes(n_req)
    if n_lanes > n_req:
        pad = np.repeat(pm[:, :1], n_lanes - n_req, axis=1)
        pm = np.concatenate([pm, pad], axis=1)
    warm = x0 is not None
    if warm:
        x0 = np.asarray(x0, dtype=np.float32)
        if x0.shape[1] < n_lanes:
            pad = np.repeat(pm[:, -1:], n_lanes - x0.shape[1], axis=1)
            x0 = np.concatenate([x0, pad], axis=1)
    key = (int(graph.n_pad), int(max_iterations), precision, warm)
    fn = _PPR_BATCH_CACHE.get(key)
    if fn is None:
        with _ppr_batch_cache_lock:
            fn = _PPR_BATCH_CACHE.get(key)
            if fn is None:
                fn = _build_ppr_batch(graph.n_pad, int(max_iterations),
                                      precision, warm)
                _PPR_BATCH_CACHE[key] = fn
    arrays = {"src": graph.csc_src, "dst": graph.csc_dst,
              "w": graph.csc_weights,
              "csr_src": graph.src_idx, "csr_w": graph.weights,
              "personalization": jnp.asarray(pm)}
    with S.backend_extent("segment", record_iterate=True):
        x, err, iters = fn(arrays, {"n_nodes": np.int32(graph.n_nodes),
                                    "damping": np.float32(damping),
                                    "tol": np.float32(tol)},
                           jnp.asarray(x0) if warm else None)
    if raw:
        # DEVICE handles (padding lanes included for x): the serving
        # plane fuses its epilogues (top-k) and pays ONE host transfer
        # for the whole batch — err/iters ride that same device_get
        return x, err, iters
    ranks = np.asarray(x)[: graph.n_nodes, :n_req].T
    return (ranks, np.asarray(err)[:n_req], np.asarray(iters)[:n_req])


_PPR_TOPK_CACHE: dict = {}


def ppr_topk(ranks_matrix, n_nodes: int, k: int, raw: bool = False):
    """Per-lane top-k over a (B, n) rank matrix ON DEVICE — the serving
    plane extracts each request's answer before the reply ships, so a
    top-10 query never pays an O(n) result transfer per rider beyond
    the batch's own cache fill.

    Returns (values (B, k), indices (B, k)) as host arrays, or as
    DEVICE handles with ``raw=True`` so the serving plane can fold them
    into its one fused result transfer per batch (mglint MG009)."""
    import jax
    m = jnp.asarray(ranks_matrix)[:, :n_nodes]
    k = max(1, min(int(k), int(n_nodes)))
    fn = _PPR_TOPK_CACHE.get(k)
    if fn is None:
        fn = _PPR_TOPK_CACHE[k] = jax.jit(
            partial(jax.lax.top_k, k=k))
    vals, idx = fn(m)
    if raw:
        return vals, idx
    return np.asarray(vals), np.asarray(idx)  # mglint: disable=MG009 — host-array return contract for direct callers; the serving plane passes raw=True and folds these into its one fused device_get per chunk
