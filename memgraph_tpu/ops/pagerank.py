"""PageRank as a jitted XLA program over CSR edge arrays.

TPU-native counterpart of the reference's PageRank modules
(/root/reference/mage/cpp/pagerank_module/, CUDA analog
mage/cpp/cugraph_module/algorithms/pagerank.cu, online variant
query_modules/pagerank_module/pagerank_online_module.cpp): weighted power
iteration expressed as per-edge gathers + a segment-sum scatter by
destination — the sparse-matvec formulation XLA compiles well for TPU —
inside a `lax.while_loop` with an L1 convergence check. Dangling-node mass
is redistributed uniformly each round (standard PageRank semantics).

All shapes static; padding edges carry weight 0 into a sink row, so they
contribute nothing.
"""

from __future__ import annotations

import os
import threading
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .csr import DeviceGraph

# Above this edge count the gather-free MXU formulation (ops/spmv_mxu.py)
# wins despite its host-side plan build; below it the segment-sum kernel's
# zero setup cost wins. Plan+kernel are cached on the DeviceGraph snapshot,
# so repeated CALLs on an unchanged graph pay the build once.
MXU_MIN_EDGES = int(os.environ.get("MEMGRAPH_TPU_MXU_MIN_EDGES", 500_000))

# serializes the expensive plan build PER GRAPH so concurrent first CALLs
# on one snapshot don't each run it (~35s host-side at 10M edges), while
# unrelated graphs build in parallel; the registry lock only guards the
# per-graph lock creation
_mxu_locks_guard = threading.Lock()


@partial(jax.jit, static_argnames=("n_pad", "max_iterations"))
def _pagerank_kernel(src, dst, weights, csr_src, csr_weights, n_nodes,
                     n_pad: int, damping, max_iterations: int, tol):
    """src/dst/weights in CSC ((dst, src)-sorted) order; csr_src/csr_weights
    are the same edges in CSR order (src sorted) for the out-weight sums.

    TPU tuning (profiled on v5e): destination-sorted indices let XLA lower
    segment_sum without general scatter (~3x/iteration), and the per-edge
    multiplier `w / wsum[src]` is gathered ONCE outside the loop, leaving a
    single rank gather + one sorted segment-sum per iteration.
    """
    n_f = n_nodes.astype(jnp.float32)
    valid = (jnp.arange(n_pad, dtype=jnp.int32) < n_nodes)
    valid_f = valid.astype(jnp.float32)

    # per-source total outgoing weight (0 ⇒ dangling); CSR order is sorted
    wsum = jax.ops.segment_sum(csr_weights, csr_src, num_segments=n_pad,
                               indices_are_sorted=True)
    inv_wsum = jnp.where(wsum > 0, 1.0 / jnp.maximum(wsum, 1e-30), 0.0)
    dangling = valid & (wsum <= 0)
    dangling_f = dangling.astype(jnp.float32)
    edge_mult = weights * inv_wsum[src]  # hoisted: one gather per run

    rank0 = valid_f / n_f

    def body(carry):
        rank, _, it = carry
        contrib = rank[src] * edge_mult
        acc = jax.ops.segment_sum(contrib, dst, num_segments=n_pad,
                                  indices_are_sorted=True)
        dangling_mass = jnp.sum(rank * dangling_f)
        new_rank = valid_f * ((1.0 - damping) / n_f
                              + damping * (acc + dangling_mass / n_f))
        err = jnp.sum(jnp.abs(new_rank - rank))
        return new_rank, err, it + 1

    def cond(carry):
        _, err, it = carry
        return (err > tol) & (it < max_iterations)

    rank, err, iters = jax.lax.while_loop(
        cond, body, (rank0, jnp.float32(jnp.inf), jnp.int32(0)))
    return rank, err, iters


# a delta larger than this fraction of the base edge set triggers a full
# replan (padding inflation + per-iter delta cost outgrow the saving)
DELTA_RECOMPACT_FRACTION = 0.10


def _edge_diff(base_g: DeviceGraph, new_g: DeviceGraph, changed_gids):
    """Multiset edge diff restricted to vertices in changed_gids.
    Returns (added, removed) as (src, dst, w) tuples of host arrays, or
    None when the diff cannot be derived (node set changed, no host
    arrays kept, ...)."""
    if base_g.host_coo is None or new_g.host_coo is None:
        return None
    if base_g.n_nodes != new_g.n_nodes or \
            not np.array_equal(base_g.node_gids, new_g.node_gids):
        return None     # node set changed: dense ids shifted
    bitmap = np.zeros(new_g.n_nodes, dtype=bool)
    for gid in changed_gids:
        idx = new_g.gid_to_idx.get(gid)
        if idx is not None:
            bitmap[idx] = True
    os_, od, ow = base_g.host_coo
    ns_, nd, nw = new_g.host_coo
    o_sel = bitmap[os_]
    n_sel = bitmap[ns_]
    # multiset diff over (src, dst, w) rows: +1 for new, -1 for old
    rows = np.stack([
        np.concatenate([ns_[n_sel].astype(np.int64),
                        os_[o_sel].astype(np.int64)]),
        np.concatenate([nd[n_sel].astype(np.int64),
                        od[o_sel].astype(np.int64)]),
        np.concatenate([nw[n_sel], ow[o_sel]]).view(np.int32).astype(
            np.int64),
    ], axis=1)
    sign = np.concatenate([np.ones(int(n_sel.sum()), dtype=np.int64),
                           -np.ones(int(o_sel.sum()), dtype=np.int64)])
    uniq, inv = np.unique(rows, axis=0, return_inverse=True)
    counts = np.zeros(len(uniq), dtype=np.int64)
    np.add.at(counts, inv, sign)
    add_idx = np.repeat(np.arange(len(uniq)), np.maximum(counts, 0))
    rem_idx = np.repeat(np.arange(len(uniq)), np.maximum(-counts, 0))
    w_back = lambda col: col.astype(np.int32).view(np.float32)  # noqa: E731
    added = (uniq[add_idx, 0], uniq[add_idx, 1], w_back(uniq[add_idx, 2]))
    removed = (uniq[rem_idx, 0], uniq[rem_idx, 1], w_back(uniq[rem_idx, 2]))
    return added, removed


def _try_delta_plan(graph: DeviceGraph):
    """Derive this snapshot's MXU state from a predecessor's full plan
    via an O(changed-edges) DeltaPlan. None -> caller does a full build.
    """
    from . import spmv_mxu
    ctx = getattr(graph, "_delta_ctx", None)
    if ctx is None:
        return None
    base_g, changed_gids = ctx
    base_state = getattr(base_g, "_mxu_state", None)
    if base_state is None or base_state[0].wsum is None:
        return None
    base_plan = base_state[0]
    diff = _edge_diff(base_g, graph, changed_gids)
    if diff is None:
        return None
    (a_s, a_d, a_w), (r_s, r_d, r_w) = diff
    n_delta = len(a_s) + len(r_s)
    if n_delta == 0:
        return base_state    # property-only bump: plan still exact
    if n_delta > max(DELTA_RECOMPACT_FRACTION * base_g.n_edges, 1024):
        return None          # recompact: full replan is the better deal
    delta = spmv_mxu.build_delta_plan(base_plan, a_s, a_d, a_w,
                                      r_s, r_d, r_w)
    run = spmv_mxu.make_pagerank_kernel(base_plan, delta=delta)
    return (base_plan, run)


def _pagerank_via_mxu(graph: DeviceGraph, damping, max_iterations, tol):
    """Large-graph path: gather-free MXU kernel with the plan cached on
    the (immutable) DeviceGraph snapshot. Successor snapshots of a
    mutated graph refresh O(delta) via DeltaPlan side-nets instead of
    replanning (reference analog: pagerank_online_module.cpp keeps
    incremental state for the same reason)."""
    from . import spmv_mxu
    cached = getattr(graph, "_mxu_state", None)
    if cached is None:
        with _mxu_locks_guard:
            lock = getattr(graph, "_mxu_build_lock", None)
            if lock is None:
                lock = threading.Lock()
                object.__setattr__(graph, "_mxu_build_lock", lock)
        with lock:
            cached = getattr(graph, "_mxu_state", None)
            if cached is None:
                cached = _try_delta_plan(graph)
                if cached is not None:
                    object.__setattr__(graph, "_mxu_state", cached)
            if cached is None:
                # true edges only: padding edges sort to the end (sinks)
                src = np.asarray(graph.src_idx)[:graph.n_edges]
                dst = np.asarray(graph.col_idx)[:graph.n_edges]
                w = np.asarray(graph.weights)[:graph.n_edges]
                plan = spmv_mxu.build_plan(src, dst, w, graph.n_nodes)
                cached = (plan, spmv_mxu.make_pagerank_kernel(plan))
                # DeviceGraph is frozen; bypass its setattr guard
                object.__setattr__(graph, "_mxu_state", cached)
                # full plans anchor future delta refreshes (GraphCache)
                object.__setattr__(graph, "_mxu_base_self", True)
    plan, run = cached
    # None = uniform start computed on-device (saves a node-flat transfer)
    rank, err, iters = run(None, np.float32(damping),
                           int(max_iterations), np.float32(tol))
    return np.asarray(rank)[plan.out_relabel], float(err), int(iters)


def pagerank(graph: DeviceGraph, damping: float = 0.85,
             max_iterations: int = 100, tol: float = 1e-6, mesh=None):
    """Returns (ranks[:n_nodes], error, iterations).

    `mesh` routes the computation through the multi-chip layer
    (parallel/analytics.py): a MeshContext, a jax Mesh, a device count,
    or None (→ the MEMGRAPH_TPU_MESH_DEVICES env default; unset keeps
    the single-chip kernels). A mesh-of-1 runs the same sharded code
    path as any other size — single-device is a degeneracy, not a fork.
    """
    from ..utils.jax_cache import ensure_compile_cache
    ensure_compile_cache()
    from ..parallel.mesh import resolve_mesh
    ctx = resolve_mesh(mesh)
    if ctx is not None:
        from ..parallel.analytics import pagerank_mesh
        return pagerank_mesh(graph, ctx, damping=damping,
                             max_iterations=max_iterations, tol=tol)
    if graph.n_edges >= MXU_MIN_EDGES and (
            jax.default_backend() != "cpu"
            or os.environ.get("MEMGRAPH_TPU_FORCE_MXU")):
        return _pagerank_via_mxu(graph, damping, max_iterations, tol)
    rank, err, iters = _pagerank_kernel(
        graph.csc_src, graph.csc_dst, graph.csc_weights,
        graph.src_idx, graph.weights,
        np.int32(graph.n_nodes), graph.n_pad,
        np.float32(damping), max_iterations, np.float32(tol))
    return rank[:graph.n_nodes], float(err), int(iters)


@partial(jax.jit, static_argnames=("n_pad", "max_iterations"))
def _personalized_kernel(src, dst, weights, csr_src, csr_weights, n_nodes,
                         n_pad: int, personalization, damping,
                         max_iterations: int, tol):
    """src/dst/weights in CSC order (see _pagerank_kernel)."""
    valid = (jnp.arange(n_pad, dtype=jnp.int32) < n_nodes)
    valid_f = valid.astype(jnp.float32)
    p = personalization * valid_f
    p = p / jnp.maximum(jnp.sum(p), 1e-30)

    wsum = jax.ops.segment_sum(csr_weights, csr_src, num_segments=n_pad,
                               indices_are_sorted=True)
    inv_wsum = jnp.where(wsum > 0, 1.0 / jnp.maximum(wsum, 1e-30), 0.0)
    dangling_f = (valid & (wsum <= 0)).astype(jnp.float32)
    edge_mult = weights * inv_wsum[src]

    rank0 = p

    def body(carry):
        rank, _, it = carry
        contrib = rank[src] * edge_mult
        acc = jax.ops.segment_sum(contrib, dst, num_segments=n_pad,
                                  indices_are_sorted=True)
        dangling_mass = jnp.sum(rank * dangling_f)
        new_rank = (1.0 - damping) * p + damping * (acc + dangling_mass * p)
        err = jnp.sum(jnp.abs(new_rank - rank))
        return new_rank, err, it + 1

    def cond(carry):
        _, err, it = carry
        return (err > tol) & (it < max_iterations)

    rank, err, iters = jax.lax.while_loop(
        cond, body, (rank0, jnp.float32(jnp.inf), jnp.int32(0)))
    return rank, err, iters


def personalized_pagerank(graph: DeviceGraph, source_nodes,
                          damping: float = 0.85, max_iterations: int = 100,
                          tol: float = 1e-6):
    """PPR with restart mass on `source_nodes` (dense indices).

    Analog of mage/cpp/cugraph_module/algorithms/personalized_pagerank.cu.
    """
    p = jnp.zeros(graph.n_pad, dtype=jnp.float32)
    p = p.at[jnp.asarray(source_nodes, dtype=jnp.int32)].set(1.0)
    rank, err, iters = _personalized_kernel(
        graph.csc_src, graph.csc_dst, graph.csc_weights,
        graph.src_idx, graph.weights,
        np.int32(graph.n_nodes), graph.n_pad, p,
        np.float32(damping), max_iterations, np.float32(tol))
    return rank[:graph.n_nodes], float(err), int(iters)
