"""mgdelta: incremental semiring fixpoints on a device-resident graph.

Every analytics CALL used to rebuild the CSR from storage (a Python
MVCC walk over ALL edges), re-shard it (a global lexsort), and recompute
the fixpoint from a cold start — so results went stale the moment write
traffic flowed, and the only incremental path was the pagerank-MXU-only
``DeltaPlan`` (ops/spmv_mxu.py). This module generalizes that side-net
idea to the whole semiring core:

  * :class:`EdgeDelta` — one commit range's change-log entries compiled
    into added/removed edge COO blocks over DENSE node indices (plus the
    per-node out-weight adjustments they imply). The generalization of
    DeltaPlan's signed side-nets: instead of routing the delta through a
    separate Benes net, the delta is SPLICED into the resident
    partition-centric layout, so every backend (mesh / MXU / segment)
    sees the exact updated graph through unchanged kernels.
  * :func:`apply_edge_delta` — the O(delta + affected shard rows)
    refresh of a resident :class:`~.csr.ShardedCSR`: removed edges are
    matched inside their owning shard row (binary search on the
    (dst, src) sort), added edges merge-insert in order, padding and
    ``block_ptr`` are repaired per affected row only. Unaffected shard
    rows are untouched; the global re-sort of a full rebuild never runs.
  * :class:`ResidentGraph` — one device-resident generation keyed
    ``(graph_key, base_version)``: the DeviceGraph snapshot, its host
    ShardedCSR variants, and the per-algorithm last solutions that seed
    warm-started fixpoints. Bounded delta accumulation: once the edges
    applied since the last full build exceed
    ``DELTA_COMPACT_FRACTION`` of the edge count, the next delta
    triggers a compacting rebuild (restoring per-row padding slack).
  * Warm-start contracts (:data:`WARM_START_POLICY`): pagerank / PPR /
    katz iterate contractions with a unique fixpoint — ANY seed
    converges to the same answer at the same tol, so the previous
    solution is always a valid x0 (residual-equivalent to cold,
    enforced by tests/test_delta.py). WCC's min-label propagation and
    labelprop's election are only warm-safe when the delta is
    monotone (edge ADDITIONS only — components can merge but never
    split, labels can only be re-elected over a superset); a delta with
    removals forces a LOUD cold start (``delta.cold_start_total``).

The warm-start framing follows "Accelerating Personalized PageRank
Vector Computation" (PAPERS.md): after a small perturbation the residual
of the previous solution is O(delta), so the fixpoint needs the few
iterations the perturbation actually costs, not the cold count.

Metrics (STAT_NAMES, surfaced under ``GET /stats`` → ``delta``):
``delta.applied_total`` / ``delta.compacted_total`` /
``delta.fallback_rebuild_total`` counters, ``delta.edge_count`` and
``delta.warm_start_iterations`` histograms, the
``delta.resident_generations`` gauge, and
``delta.warm_start_total`` / ``delta.cold_start_total``.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass

import numpy as np

from ..observability.metrics import global_metrics
from .csr import DeviceGraph, ShardedCSR, from_coo, shard_edges

log = logging.getLogger(__name__)

#: once the edges applied since the last full build exceed this fraction
#: of the resident edge count, the next delta triggers a compacting
#: rebuild (padding slack restored, per-row capacity re-sized)
DELTA_COMPACT_FRACTION = float(
    os.environ.get("MEMGRAPH_TPU_DELTA_COMPACT_FRACTION", "0.25"))

#: a single delta larger than this fraction of the edge set skips the
#: splice outright — the full rebuild is cheaper per edge at that size
DELTA_MAX_FRACTION = float(
    os.environ.get("MEMGRAPH_TPU_DELTA_MAX_FRACTION", "0.25"))

#: per-algorithm warm-start contracts (see module docstring):
#:   "always"     — contraction with a unique fixpoint; any seed is
#:                  residual-equivalent to cold at the same tol
#:   "adds_only"  — monotone iteration; warm only when the cumulative
#:                  delta since the seed solution added edges but never
#:                  removed any, else LOUD cold start
WARM_START_POLICY = {
    "pagerank": "always",
    "ppr": "always",
    "katz": "always",
    "wcc": "adds_only",
    "labelprop": "adds_only",
}


# --------------------------------------------------------------------------
# EdgeDelta: the compiled change-log side-net
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class EdgeDelta:
    """Added/removed edge COO blocks over dense node indices.

    The node set must be unchanged across the covered range — node
    additions/removals shift the dense relabeling and require a full
    re-export (the compiler returns None there). Weight updates are a
    remove + add of the same (src, dst) pair.
    """

    base_version: int
    version: int
    add_src: np.ndarray        # (a,) int64 dense indices
    add_dst: np.ndarray
    add_w: np.ndarray          # (a,) float32
    rem_src: np.ndarray        # (r,) int64 dense indices
    rem_dst: np.ndarray
    rem_w: np.ndarray          # (r,) float32

    @property
    def n_delta(self) -> int:
        return len(self.add_src) + len(self.rem_src)

    @property
    def adds_only(self) -> bool:
        """True iff the delta is monotone (no removed edges) — the
        warm-start precondition for WCC / labelprop."""
        return len(self.rem_src) == 0

    def doubled(self) -> "EdgeDelta":
        """Both edge directions (the undirected view labelprop's
        dst-owned doubled ShardedCSR iterates over)."""
        return EdgeDelta(
            base_version=self.base_version, version=self.version,
            add_src=np.concatenate([self.add_src, self.add_dst]),
            add_dst=np.concatenate([self.add_dst, self.add_src]),
            add_w=np.concatenate([self.add_w, self.add_w]),
            rem_src=np.concatenate([self.rem_src, self.rem_dst]),
            rem_dst=np.concatenate([self.rem_dst, self.rem_src]),
            rem_w=np.concatenate([self.rem_w, self.rem_w]))

    def wsum_adjust(self, n_nodes: int) -> np.ndarray:
        """Per-node out-weight-sum adjustment the delta implies — the
        degree/weight rescale vector of the DeltaPlan formulation (the
        mesh kernels recompute wsum from the spliced rows in-kernel, so
        this is exposed for the MXU side-net path and for tests)."""
        adj = np.zeros(n_nodes, dtype=np.float64)
        if len(self.add_src):
            np.add.at(adj, self.add_src, self.add_w.astype(np.float64))
        if len(self.rem_src):
            np.subtract.at(adj, self.rem_src,
                           self.rem_w.astype(np.float64))
        return adj

    def touched_nodes(self) -> np.ndarray:
        """Unique dense indices incident to the delta (the invalidation
        set serving-plane caches demote by)."""
        return np.unique(np.concatenate([
            self.add_src, self.add_dst, self.rem_src, self.rem_dst]))

    def to_arrays(self) -> dict:
        """Socket-shippable arrays (kernel-server request payload)."""
        return {"delta_add_src": self.add_src.astype(np.int64),
                "delta_add_dst": self.add_dst.astype(np.int64),
                "delta_add_w": self.add_w.astype(np.float32),
                "delta_rem_src": self.rem_src.astype(np.int64),
                "delta_rem_dst": self.rem_dst.astype(np.int64),
                "delta_rem_w": self.rem_w.astype(np.float32)}

    @classmethod
    def from_arrays(cls, base_version: int, version: int,
                    arrays: dict) -> "EdgeDelta | None":
        need = ("delta_add_src", "delta_add_dst", "delta_add_w",
                "delta_rem_src", "delta_rem_dst", "delta_rem_w")
        if any(k not in arrays for k in need):
            return None
        return cls(
            base_version=int(base_version), version=int(version),
            add_src=np.asarray(arrays["delta_add_src"], dtype=np.int64),
            add_dst=np.asarray(arrays["delta_add_dst"], dtype=np.int64),
            add_w=np.asarray(arrays["delta_add_w"], dtype=np.float32),
            rem_src=np.asarray(arrays["delta_rem_src"], dtype=np.int64),
            rem_dst=np.asarray(arrays["delta_rem_dst"], dtype=np.int64),
            rem_w=np.asarray(arrays["delta_rem_w"], dtype=np.float32))


def empty_delta(base_version: int, version: int) -> EdgeDelta:
    z = np.zeros(0, dtype=np.int64)
    zf = np.zeros(0, dtype=np.float32)
    return EdgeDelta(base_version, version, z, z, zf, z.copy(), z.copy(),
                     zf.copy())


# --------------------------------------------------------------------------
# delta compilation: change-log gids -> EdgeDelta
# --------------------------------------------------------------------------


def incident_edges(src, dst, w, bitmap: np.ndarray):
    """Edges with at least one endpoint in ``bitmap`` (dense bool mask).
    One vectorized pass over the COO arrays."""
    src = np.asarray(src)
    dst = np.asarray(dst)
    sel = bitmap[src] | bitmap[dst]
    return (src[sel].astype(np.int64), dst[sel].astype(np.int64),
            np.asarray(w, dtype=np.float32)[sel])


def multiset_edge_diff(old_edges, new_edges):
    """Multiset diff of two (src, dst, w) edge lists.

    Returns ((add_src, add_dst, add_w), (rem_src, rem_dst, rem_w)).
    Weights compare bit-exactly (a weight update is a remove + add).
    One lexsort + run-length net-count pass — O(m log m) with memcpy
    constants (the np.unique(axis=0) formulation's void-view sort cost
    dominated the whole delta pipeline at bench scale).
    """
    o_s, o_d, o_w = (np.asarray(a) for a in old_edges)
    n_s, n_d, n_w = (np.asarray(a) for a in new_edges)
    if len(o_s) + len(n_s) == 0:
        z = np.zeros(0, dtype=np.int64)
        zf = np.zeros(0, dtype=np.float32)
        return (z, z.copy(), zf), (z.copy(), z.copy(), zf.copy())
    src = np.concatenate([n_s.astype(np.int64), o_s.astype(np.int64)])
    dst = np.concatenate([n_d.astype(np.int64), o_d.astype(np.int64)])
    wb = np.concatenate([n_w.astype(np.float32),
                         o_w.astype(np.float32)]).view(np.int32) \
        .astype(np.int64)
    sign = np.concatenate([np.ones(len(n_s), dtype=np.int64),
                           -np.ones(len(o_s), dtype=np.int64)])
    order = np.lexsort((wb, dst, src))
    s2, d2, w2, sg = src[order], dst[order], wb[order], sign[order]
    boundary = (s2[1:] != s2[:-1]) | (d2[1:] != d2[:-1]) \
        | (w2[1:] != w2[:-1])
    starts = np.concatenate([[0], np.nonzero(boundary)[0] + 1])
    net = np.add.reduceat(sg, starts)
    add_rep = np.repeat(starts, np.maximum(net, 0))
    rem_rep = np.repeat(starts, np.maximum(-net, 0))

    def w_back(col):
        return col.astype(np.int32).view(np.float32)

    added = (s2[add_rep], d2[add_rep], w_back(w2[add_rep]))
    removed = (s2[rem_rep], d2[rem_rep], w_back(w2[rem_rep]))
    return added, removed


def diff_incident(prev_coo, changed_idx, inc_src, inc_dst, inc_w,
                  n_nodes: int, base_version: int,
                  version: int) -> EdgeDelta:
    """EdgeDelta from the CURRENT incident edges of the changed
    vertices (the route layer ships exactly these — O(delta
    neighborhood) on the wire, never the full edge list): the previous
    incident set is extracted from the resident snapshot's COO, the two
    are multiset-diffed. Edges between unchanged vertices are identical
    by the change-log contract and never compared."""
    bitmap = np.zeros(n_nodes, dtype=bool)
    ci = np.asarray(changed_idx, dtype=np.int64)
    if len(ci):
        bitmap[ci] = True
    old_inc = incident_edges(*prev_coo, bitmap)
    inc_src = np.asarray(inc_src, dtype=np.int64)
    inc_dst = np.asarray(inc_dst, dtype=np.int64)
    inc_w = (np.ones(len(inc_src), dtype=np.float32) if inc_w is None
             else np.asarray(inc_w, dtype=np.float32))
    (a_s, a_d, a_w), (r_s, r_d, r_w) = multiset_edge_diff(
        old_inc, (inc_src, inc_dst, inc_w))
    return EdgeDelta(base_version, version, a_s, a_d, a_w, r_s, r_d, r_w)


def diff_changed_coo(prev_coo, cur_coo, changed_idx, n_nodes: int,
                     base_version: int, version: int) -> EdgeDelta:
    """EdgeDelta between two COO snapshots of the SAME node set,
    restricted to edges incident to ``changed_idx`` (the dense indices
    the change log reported)."""
    bitmap = np.zeros(n_nodes, dtype=bool)
    ci = np.asarray(changed_idx, dtype=np.int64)
    if len(ci):
        bitmap[ci] = True
    cur = incident_edges(*cur_coo, bitmap)
    return diff_incident(prev_coo, changed_idx, cur[0], cur[1], cur[2],
                         n_nodes, base_version, version)


def incident_from_storage(accessor, gid_to_idx, changed_gids,
                          weight_property=None):
    """CURRENT visible edges incident to the changed vertices, read
    straight from MVCC in O(changed x degree) — the serving-plane delta
    payload without any snapshot export (the same per-vertex read
    export_csr_delta does, permission-free). Dense-index (src, dst, w)
    arrays, or None when the node set moved (a changed vertex joined or
    left the view: dense ids shifted, full re-export required)."""
    from ..storage.common import View
    from ..storage.storage import EdgeAccessor, VertexAccessor
    from .csr import _coerce_weight
    storage = accessor.storage
    changed = list(changed_gids)
    changed_set = set(changed)
    has_w = weight_property is not None
    out_s: list = []
    out_d: list = []
    out_w: list = []
    def _edge_visible(edge) -> bool:
        # fast path first (same contract as export_csr): an object with
        # no delta chain needs no MVCC materialization
        if edge.delta is None:
            return not edge.deleted
        return EdgeAccessor(edge, accessor).is_visible(View.OLD)

    def _edge_weight(edge) -> float:
        if not has_w:
            return 1.0
        if edge.delta is None:
            props = edge.properties
        else:
            props = EdgeAccessor(edge, accessor).properties(View.OLD)
        return _coerce_weight(props.get(weight_property))

    for gid in changed:
        idx = gid_to_idx.get(gid)
        vertex = storage._vertices.get(gid)
        if idx is None or vertex is None:
            return None
        if vertex.delta is None:
            if vertex.deleted:
                return None
            v_out, v_in = vertex.out_edges, vertex.in_edges
        else:
            va = VertexAccessor(vertex, accessor)
            if not va.is_visible(View.OLD):
                return None
            st = accessor._vertex_state(vertex, View.OLD)
            v_out, v_in = st.out_edges, st.in_edges
        for (_etype, _other, edge) in v_out:
            if not _edge_visible(edge):
                continue
            di = gid_to_idx.get(edge.to_vertex.gid)
            if di is None:
                return None
            out_s.append(idx)
            out_d.append(di)
            out_w.append(_edge_weight(edge))
        for (_etype, _other, edge) in v_in:
            if edge.from_vertex.gid in changed_set:
                continue               # its changed src emitted it above
            if not _edge_visible(edge):
                continue
            si = gid_to_idx.get(edge.from_vertex.gid)
            if si is None:
                return None
            out_s.append(si)
            out_d.append(idx)
            out_w.append(_edge_weight(edge))
    return (np.asarray(out_s, dtype=np.int64),
            np.asarray(out_d, dtype=np.int64),
            np.asarray(out_w, dtype=np.float32))


def compile_edge_delta(storage, prev_graph: DeviceGraph,
                       cur_graph: DeviceGraph, base_version: int,
                       version: int):
    """Compile the change-log entries covering (base_version, version]
    into an :class:`EdgeDelta` between two already-exported snapshots.

    Returns the delta, a falsy ``ChangeLogUnknowable`` when the bounded
    log wrapped past the range (callers fall back to a full rebuild,
    LOUDLY), or None when the node set changed (dense ids shifted — a
    delta over stale indices would corrupt the resident layout).
    """
    from ..storage.storage import ChangeLogUnknowable
    if base_version == version:
        return empty_delta(base_version, version)
    changed = storage.changes_between(base_version, version)
    if isinstance(changed, ChangeLogUnknowable):
        return changed
    if prev_graph.host_coo is None or cur_graph.host_coo is None:
        return None
    if prev_graph.n_nodes != cur_graph.n_nodes or \
            not np.array_equal(prev_graph.node_gids,
                               cur_graph.node_gids):
        return None
    changed_idx = [cur_graph.gid_to_idx[g] for g in changed
                   if g in cur_graph.gid_to_idx]
    if len(changed_idx) != len(changed):
        return None               # a changed vertex left/joined the view
    return diff_changed_coo(prev_graph.host_coo, cur_graph.host_coo,
                            changed_idx, cur_graph.n_nodes,
                            base_version, version)


# --------------------------------------------------------------------------
# O(delta) refresh of a resident ShardedCSR
# --------------------------------------------------------------------------


def _row_real_count(dst_row: np.ndarray, sink: int) -> int:
    """Real edges in a (dst, src)-sorted shard row (padding entries all
    carry dst == sink and sort to the tail)."""
    return int(np.searchsorted(dst_row, sink, side="left"))


def _match_removals(row_src, row_dst, row_w, rem_src, rem_dst, rem_w,
                    n_pad2: int):
    """Indices of row positions matching each removal triple, or None if
    any removal has no match (inconsistent delta -> caller rebuilds).
    The row is (dst, src)-sorted, so each (dst, src) run is a binary
    search; weight matching scans the (tiny) run."""
    key_row = row_dst.astype(np.int64) * n_pad2 + row_src
    out = []
    used: set = set()
    for s, d, w in zip(rem_src, rem_dst, rem_w):
        k = int(d) * n_pad2 + int(s)
        lo = int(np.searchsorted(key_row, k, side="left"))
        hi = int(np.searchsorted(key_row, k, side="right"))
        hit = -1
        for i in range(lo, hi):
            if i not in used and row_w[i] == w:
                hit = i
                break
        if hit < 0:
            # tolerate weight drift: match any unused duplicate of the
            # (src, dst) pair — NO: a miss means the delta and the
            # resident rows disagree; a silent partial apply would
            # corrupt the generation. Rebuild instead.
            return None
        used.add(hit)
        out.append(hit)
    return out


def apply_edge_delta(scsr: ShardedCSR, delta: EdgeDelta):
    """Splice an EdgeDelta into a HOST-side ShardedCSR.

    O(delta) index work plus O(row) merge cost for AFFECTED shard rows
    only — unaffected rows (arrays and block_ptr) are reused untouched,
    and the full rebuild's global lexsort never runs. Returns the new
    host ShardedCSR, or None when the splice cannot preserve the layout
    (a row overflows its ``per`` capacity, or a removal doesn't match
    the resident rows) — the caller falls back to a compacting rebuild.
    """
    if not isinstance(scsr.src, np.ndarray):
        raise ValueError("apply_edge_delta needs the HOST-side layout; "
                         "splice then re-place with .to_device(ctx)")
    if delta.n_delta == 0:
        return scsr
    block, n_shards, per = scsr.block, scsr.n_shards, scsr.per
    sink = scsr.n_nodes
    key = "src" if scsr.by == "src" else "dst"
    add_owner = (delta.add_src if key == "src" else delta.add_dst) // block
    rem_owner = (delta.rem_src if key == "src" else delta.rem_dst) // block
    affected = np.union1d(np.unique(add_owner), np.unique(rem_owner))
    if len(affected) and (affected.min() < 0
                          or affected.max() >= n_shards):
        return None               # delta references nodes outside layout

    src_b = scsr.src.copy()
    dst_b = scsr.dst.copy()
    w_b = scsr.weights.copy()
    block_ptr = scsr.block_ptr.copy()
    shard_bounds = np.arange(n_shards + 1, dtype=np.int64) * block

    for p in affected:
        p = int(p)
        rc = _row_real_count(dst_b[p], sink)
        r_sel = rem_owner == p
        a_sel = add_owner == p
        row_s = src_b[p, :rc]
        row_d = dst_b[p, :rc]
        row_w = w_b[p, :rc]
        keep = np.ones(rc, dtype=bool)
        if r_sel.any():
            hits = _match_removals(
                row_s, row_d, row_w, delta.rem_src[r_sel],
                delta.rem_dst[r_sel], delta.rem_w[r_sel], scsr.n_pad2)
            if hits is None:
                return None
            keep[hits] = False
        a_s = delta.add_src[a_sel]
        a_d = delta.add_dst[a_sel]
        a_w = delta.add_w[a_sel]
        new_rc = int(keep.sum()) + len(a_s)
        if new_rc > per:
            return None           # capacity overflow -> compaction
        k_s, k_d, k_w = row_s[keep], row_d[keep], row_w[keep]
        if len(a_s):
            order = np.lexsort((a_s, a_d))
            a_s, a_d, a_w = a_s[order], a_d[order], a_w[order]
            # merge-insert into the (dst, src)-sorted survivors
            kept_key = k_d.astype(np.int64) * scsr.n_pad2 + k_s
            add_key = a_d.astype(np.int64) * scsr.n_pad2 + a_s
            pos = np.searchsorted(kept_key, add_key, side="left")
            k_s = np.insert(k_s, pos, a_s.astype(np.int32))
            k_d = np.insert(k_d, pos, a_d.astype(np.int32))
            k_w = np.insert(k_w, pos, a_w)
        src_b[p, :new_rc] = k_s
        dst_b[p, :new_rc] = k_d
        w_b[p, :new_rc] = k_w
        src_b[p, new_rc:] = np.int32(p * block)   # padding convention
        dst_b[p, new_rc:] = np.int32(sink)
        w_b[p, new_rc:] = 0.0
        block_ptr[p] = np.searchsorted(dst_b[p], shard_bounds)

    n_edges = scsr.n_edges + len(delta.add_src) - len(delta.rem_src)
    return ShardedCSR(src=src_b, dst=dst_b, weights=w_b,
                      block_ptr=block_ptr, n_nodes=scsr.n_nodes,
                      n_edges=n_edges, n_shards=n_shards, block=block,
                      n_pad2=scsr.n_pad2, per=per, by=scsr.by)


def splice_coo(coo, delta: EdgeDelta, n_nodes: int):
    """Apply an EdgeDelta to a host COO triple. Removal matching is
    vectorized over the incident subset (the non-incident edges are
    untouched by construction). Returns the new (src, dst, w) or None
    when a removal doesn't match."""
    src, dst, w = (np.asarray(a) for a in coo)
    w = w.astype(np.float32, copy=False)
    keep = np.ones(len(src), dtype=bool)
    if len(delta.rem_src):
        bitmap = np.zeros(n_nodes, dtype=bool)
        bitmap[delta.rem_src] = True
        bitmap[delta.rem_dst] = True
        cand = np.nonzero(bitmap[src] | bitmap[dst])[0]
        c_key = (src[cand].astype(np.int64) * n_nodes
                 + dst[cand].astype(np.int64))
        c_w = w[cand]
        order = np.argsort(c_key, kind="stable")
        c_key, c_w, cand = c_key[order], c_w[order], cand[order]
        used = np.zeros(len(cand), dtype=bool)
        for s, d, rw in zip(delta.rem_src, delta.rem_dst, delta.rem_w):
            k = int(s) * n_nodes + int(d)
            lo = int(np.searchsorted(c_key, k, side="left"))
            hi = int(np.searchsorted(c_key, k, side="right"))
            hit = -1
            for i in range(lo, hi):
                if not used[i] and c_w[i] == rw:
                    hit = i
                    break
            if hit < 0:
                return None
            used[hit] = True
            keep[cand[hit]] = False
    new_src = np.concatenate([src[keep].astype(np.int64),
                              delta.add_src])
    new_dst = np.concatenate([dst[keep].astype(np.int64),
                              delta.add_dst])
    new_w = np.concatenate([w[keep], delta.add_w])
    return new_src, new_dst, new_w


def refresh_device_graph(prev: DeviceGraph, delta: EdgeDelta):
    """New DeviceGraph snapshot = resident snapshot + delta, node set
    preserved. The COO splice is vectorized and the CSR/CSC build rides
    the native counting-sort builder — no Python MVCC walk, no storage
    access. Returns None when the splice fails (caller re-imports)."""
    if prev.host_coo is None:
        return None
    coo = splice_coo(prev.host_coo, delta, prev.n_nodes)
    if coo is None:
        return None
    src, dst, w = coo
    return from_coo(src, dst, w, n_nodes=prev.n_nodes,
                    node_gids=prev.node_gids, pad=True)


# --------------------------------------------------------------------------
# warm-start contracts
# --------------------------------------------------------------------------


def warm_start_decision(algo: str, monotone_ok: bool):
    """(warm: bool, reason: str) for seeding ``algo`` from a previous
    solution whose graph moved by a delta with ``monotone_ok`` =
    "every covered delta added edges only, and none was unknowable".

    Callers must treat a False verdict for an ``adds_only`` algorithm
    as a LOUD cold start (log + ``delta.cold_start_total``)."""
    policy = WARM_START_POLICY.get(algo)
    if policy == "always":
        return True, "contraction"
    if policy == "adds_only":
        if monotone_ok:
            return True, "monotone_adds_only"
        return False, "monotone_unsafe"
    return False, "no_policy"


def record_warm_start(algo: str, iters: int) -> None:
    global_metrics.increment("delta.warm_start_total")
    global_metrics.observe("delta.warm_start_iterations", float(iters))
    log.debug("delta: warm-started %s converged in %d iterations",
              algo, iters)


def record_cold_start(algo: str, reason: str) -> None:
    """The LOUD cold start of the warm-start contract: monotone-unsafe
    deltas (or unknowable change-log ranges) must never warm-start a
    non-contraction algorithm silently."""
    global_metrics.increment("delta.cold_start_total")
    log.warning("delta: COLD start for %s (%s) — previous solution "
                "cannot seed this fixpoint", algo, reason)


# --------------------------------------------------------------------------
# resident generations
# --------------------------------------------------------------------------


@dataclass
class _Solution:
    x: np.ndarray
    version: int
    params_key: tuple
    monotone_ok: bool = True
    err: float | None = None
    iters: int | None = None
    max_iterations: int | None = None


class ResidentGraph:
    """One device-resident graph generation for a ``graph_key``.

    Owned by a single dispatcher thread (the kernel server's dispatch
    lock / the procedures' warm pool lock) — no internal locking, same
    contract as the server's graph LRU.

    The snapshot is LAZY: the canonical state is the host COO (spliced
    O(delta) per commit) plus the partition-centric host variants; the
    DeviceGraph (CSR/CSC arrays, a native O(E) counting-sort build) is
    only materialized when a consumer actually reads it (the segment /
    PPR-SpMM paths) — the mesh-served path never pays it per commit.
    """

    __slots__ = ("graph_key", "version", "host_variants", "solutions",
                 "delta_edges", "base_edges", "tiers", "_graph", "_coo",
                 "_n_nodes", "_node_gids", "_gid_to_idx", "_placed")

    def __init__(self, graph_key, version: int,
                 graph: DeviceGraph) -> None:
        self.graph_key = graph_key
        self.version = int(version)
        self._graph = graph
        if graph.host_coo is None:
            raise ValueError("ResidentGraph needs a snapshot with host "
                             "COO arrays (from_coo keeps them)")
        self._coo = graph.host_coo
        self._n_nodes = int(graph.n_nodes)
        self._node_gids = graph.node_gids
        self._gid_to_idx = graph.gid_to_idx
        self._placed = not isinstance(graph.row_ptr, np.ndarray)
        #: (by, doubled) -> host-side ShardedCSR (the splice substrate)
        self.host_variants: dict = {}
        #: (precision, block_bytes) -> TierCSR (out-of-core paging plan)
        self.tiers: dict = {}
        #: algo -> _Solution (the warm-start seeds)
        self.solutions: dict = {}
        self.delta_edges = 0
        self.base_edges = int(graph.n_edges)

    # --- lazy snapshot -----------------------------------------------------

    @property
    def coo(self):
        """Canonical host (src, dst, w) COO of the CURRENT generation
        (the diff substrate)."""
        return self._coo

    @property
    def n_nodes(self) -> int:
        return self._n_nodes

    @property
    def n_edges(self) -> int:
        return len(self._coo[0])

    @property
    def node_gids(self):
        return self._node_gids

    @property
    def gid_to_idx(self):
        return self._gid_to_idx

    @property
    def graph(self) -> DeviceGraph:
        """The DeviceGraph snapshot — materialized on first read after
        a delta (native counting-sort build + placement matching the
        original import). Mesh-only consumers never trigger this."""
        if self._graph is None:
            g = from_coo(self._coo[0].astype(np.int64),
                         self._coo[1].astype(np.int64),
                         np.asarray(self._coo[2], dtype=np.float32),
                         n_nodes=self._n_nodes,
                         node_gids=self._node_gids)
            self._graph = g.to_device() if self._placed else g
        return self._graph

    # --- sharded variants --------------------------------------------------

    def ensure_sharded(self, ctx, by: str = "src",
                       doubled: bool = False) -> ShardedCSR:
        """Device-resident partition-centric variant for ``ctx``; the
        host layout is kept as the splice substrate and the placed copy
        is cached per mesh context so the serving path never re-sorts
        or re-transfers an unchanged generation.

        The blocking + placement extent attributes to the active
        mgtrace span / mgstat stage accumulator exactly like the
        GraphCache path's ``_shard_traced`` — PROFILE on a resident-
        served query still shows where transfer seconds went (cache
        hits show as ~zero-duration extents, itself useful signal)."""
        import time as _time
        from ..observability import stats as mgstats
        from ..observability import trace as mgtrace
        t0 = _time.perf_counter()
        with mgtrace.span("device.transfer") as sp:
            hv = self.host_variants.get((by, doubled))
            if hv is None:
                hv = self._reshard(by, doubled, ctx.n_shards)
                self.host_variants[(by, doubled)] = hv
            dev = self._install(ctx, by, doubled, hv)
            if sp:
                sp.set(n_shards=ctx.n_shards, by=by,
                       n_nodes=int(self._n_nodes), resident=True)
        mgstats.record_stage("device_transfer",
                             _time.perf_counter() - t0)
        return dev

    def ensure_tier(self, precision: str = "f32",
                    block_bytes: int | None = None):
        """Host-pinned streamed paging plan (``ops/tier.py``) for this
        generation — the out-of-core analogue of :meth:`ensure_sharded`
        for graphs whose edges exceed the HBM budget. Nothing places:
        the plan's compressed wire blocks stay pinned host-side and the
        execution plane streams them per sweep. Committed deltas splice
        into the plan through :meth:`apply` (only touched rows
        re-encode), so a churned beyond-HBM graph never re-ships cold."""
        from . import tier as mgtier
        key = (precision, block_bytes)
        t = self.tiers.get(key)
        if t is None:
            src, dst, w = self._coo
            t = mgtier.plan_tier(
                src.astype(np.int64), dst.astype(np.int64),
                np.asarray(w, dtype=np.float32), self._n_nodes,
                precision=precision, block_bytes=block_bytes)
            self.tiers[key] = t
        return t

    def _install(self, ctx, by, doubled, host_scsr) -> ShardedCSR:
        # device placements ride the materialized-or-not snapshot? No:
        # they live on the HOST variant object itself (one placement per
        # mesh context), so laziness of the snapshot never matters here
        cache = getattr(host_scsr, "_placed_cache", None)
        key = (ctx.cache_key,)
        if cache is None:
            cache = {}
            object.__setattr__(host_scsr, "_placed_cache", cache)
        dev = cache.get(key)
        if dev is None:
            dev = host_scsr.to_device(ctx)
            cache[key] = dev
        return dev

    # --- delta application -------------------------------------------------

    def apply(self, delta: EdgeDelta, ctx=None) -> bool:
        """Advance this generation by one EdgeDelta.

        Splices the canonical COO and every host variant O(delta +
        affected rows) and DEFERS the snapshot rebuild; a failed
        splice, or accumulated deltas past ``DELTA_COMPACT_FRACTION``
        of the edge count, triggers the compacting rebuild instead
        (counted ``delta.compacted_total``). Returns False only when
        even the rebuild is impossible (caller must re-import the graph
        from storage).
        """
        if delta.n_delta == 0:
            # property-only bump: the edge set is unchanged — advance
            # the version, keep every warm seed monotone-valid
            self._note_moved(delta)
            global_metrics.increment("delta.applied_total")
            global_metrics.observe("delta.edge_count", 0.0)
            return True
        if delta.n_delta > max(DELTA_MAX_FRACTION * max(self.base_edges,
                                                        1), 1024):
            return self._compact(delta, ctx, why="oversized delta")
        new_coo = splice_coo(self._coo, delta, self._n_nodes)
        if new_coo is None:
            global_metrics.increment("delta.fallback_rebuild_total")
            log.warning("delta: splice failed for %s (removal mismatch) "
                        "— generation must be re-imported",
                        self.graph_key)
            return False
        self._coo = (new_coo[0].astype(np.int32),
                     new_coo[1].astype(np.int32),
                     new_coo[2].astype(np.float32))
        self._graph = None                     # snapshot: rebuilt lazily
        self.delta_edges += delta.n_delta
        if self.delta_edges > DELTA_COMPACT_FRACTION * max(
                self.base_edges, 1):
            # accumulated padding debt: rebuild the variants fresh from
            # the spliced COO (the COO itself is already exact)
            self._note_moved(delta)
            return self._compact(None, ctx, why="accumulated deltas")
        # variant splice: each layout variant moves by the same delta
        # (doubled variants by the doubled delta)
        new_variants = {}
        for (by, doubled), hv in self.host_variants.items():
            d = delta.doubled() if doubled else delta
            nv = apply_edge_delta(hv, d)
            if nv is None:
                global_metrics.increment("delta.compacted_total")
                log.info("delta: variant (%s, doubled=%s) of %s "
                         "overflowed its row capacity — recompacting",
                         by, doubled, self.graph_key)
                nv = self._reshard(by, doubled, hv.n_shards)
            new_variants[(by, doubled)] = nv
        self.host_variants = new_variants
        # streamed paging plans move by the same splice; a row overflow
        # drops the plan (ensure_tier rebuilds it from the exact COO)
        new_tiers = {}
        for key, t in self.tiers.items():
            nt = t.apply_delta(delta)
            if nt is None:
                global_metrics.increment("delta.compacted_total")
                log.info("delta: tier %s of %s overflowed its row "
                         "capacity — dropping for lazy rebuild", key,
                         self.graph_key)
            else:
                new_tiers[key] = nt
        self.tiers = new_tiers
        if ctx is not None:
            for (by, doubled), hv in new_variants.items():
                self._install(ctx, by, doubled, hv)
        self._note_moved(delta)
        global_metrics.increment("delta.applied_total")
        global_metrics.observe("delta.edge_count", float(delta.n_delta))
        return True

    def _reshard(self, by, doubled, n_shards) -> ShardedCSR:
        src, dst, w = self._coo
        src = src.astype(np.int64)
        dst = dst.astype(np.int64)
        if doubled:
            src, dst = (np.concatenate([src, dst]),
                        np.concatenate([dst, src]))
            w = np.concatenate([w, w])
        return shard_edges(src, dst, w, self._n_nodes, n_shards, by=by)

    def _compact(self, delta, ctx, why: str) -> bool:
        """Full rebuild of the variants from the updated COO — the
        bounded-accumulation escape hatch (the snapshot stays lazy)."""
        if delta is not None:
            new_coo = splice_coo(self._coo, delta, self._n_nodes)
            if new_coo is None:
                global_metrics.increment("delta.fallback_rebuild_total")
                return False
            self._coo = (new_coo[0].astype(np.int32),
                         new_coo[1].astype(np.int32),
                         new_coo[2].astype(np.float32))
            self._graph = None
            self._note_moved(delta)
        shards = {(by, doubled): hv.n_shards
                  for (by, doubled), hv in self.host_variants.items()}
        self.host_variants = {
            key: self._reshard(key[0], key[1], n)
            for key, n in shards.items()}
        if ctx is not None:
            for (by, doubled), hv in self.host_variants.items():
                self._install(ctx, by, doubled, hv)
        self.tiers = {}                        # lazily rebuilt, exact
        self.delta_edges = 0
        self.base_edges = self.n_edges
        global_metrics.increment("delta.compacted_total")
        log.info("delta: compacted generation %s (%s)", self.graph_key,
                 why)
        return True

    def _note_moved(self, delta: EdgeDelta) -> None:
        self.version = int(delta.version)
        for sol in self.solutions.values():
            sol.monotone_ok = sol.monotone_ok and delta.adds_only

    # --- warm-start seeds --------------------------------------------------

    def note_solution(self, algo: str, params_key: tuple,
                      x: np.ndarray, err: float | None = None,
                      iters: int | None = None,
                      max_iterations: int | None = None) -> None:
        self.solutions[algo] = _Solution(
            x=np.asarray(x), version=self.version,
            params_key=tuple(params_key), monotone_ok=True,
            err=err, iters=iters, max_iterations=max_iterations)

    def cached_result(self, algo: str, params_key: tuple,
                      max_iterations=None):
        """The stored solution VERBATIM when the generation hasn't
        moved since it was computed and the request parameters match —
        result-cache semantics (same contract as the PPR result cache):
        identical repeated requests get identical bytes, never a
        re-iterated answer drifting in the low-order bits."""
        sol = self.solutions.get(algo)
        if sol is None or sol.params_key != tuple(params_key) \
                or sol.version != self.version:
            return None
        if max_iterations is not None and sol.max_iterations is not None \
                and int(max_iterations) != int(sol.max_iterations):
            return None
        return sol

    def warm_x0(self, algo: str, params_key: tuple):
        """(x0, reason) — x0 is None for a cold start; a loud cold
        (monotone-unsafe seed discarded) is already counted here."""
        sol = self.solutions.get(algo)
        if sol is None or sol.params_key != tuple(params_key):
            return None, "no_seed"
        warm, reason = warm_start_decision(algo, sol.monotone_ok)
        if not warm:
            record_cold_start(algo, reason)
            self.solutions.pop(algo, None)
            return None, reason
        return sol.x, reason


class ResidentRegistry:
    """Bounded graph_key -> ResidentGraph LRU (the kernel server's
    ``_graphs`` replacement). Callers serialize through the dispatcher
    (same single-thread contract the old DeviceGraph LRU had)."""

    def __init__(self, capacity: int = 8) -> None:
        from collections import OrderedDict
        self.capacity = capacity
        self._gens: "OrderedDict[object, ResidentGraph]" = OrderedDict()

    def get(self, graph_key) -> ResidentGraph | None:
        gen = self._gens.get(graph_key)
        if gen is not None:
            self._gens.move_to_end(graph_key)
        return gen

    def put(self, gen: ResidentGraph) -> None:
        self._gens[gen.graph_key] = gen
        self._gens.move_to_end(gen.graph_key)
        while len(self._gens) > self.capacity:
            self._gens.popitem(last=False)
        self._gauge()

    def pop(self, graph_key) -> None:
        self._gens.pop(graph_key, None)
        self._gauge()

    def __len__(self) -> int:
        return len(self._gens)

    def _gauge(self) -> None:
        global_metrics.set_gauge("delta.resident_generations",
                             float(len(self._gens)))


# --------------------------------------------------------------------------
# in-process warm pool (commit-then-CALL without a kernel server)
# --------------------------------------------------------------------------


class LocalWarmPool:
    """Per-storage warm-start state for the in-process analytics path.

    GraphCache already makes the re-export O(changed); this pool closes
    the other half of commit-then-CALL: the previous solution (and the
    COO snapshot it was computed on) is kept per storage so the next
    CALL seeds its fixpoint and — for the monotone-gated algorithms —
    the adds-only precondition is verified against the real edge diff.
    """

    def __init__(self) -> None:
        import weakref
        from ..utils.locks import tracked_lock
        self._lock = tracked_lock("LocalWarmPool._lock")
        self._pool: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()

    def _entry(self, storage):
        return self._pool.get(storage)

    def prepare(self, storage, graph: DeviceGraph, version: int,
                algo: str, params_key: tuple):
        """(cached_result, warm_seed) — at most one is non-None.

        ``cached_result`` is the stored solution VERBATIM when the
        graph hasn't moved since it was computed (result-cache
        semantics: identical repeated CALLs return identical bytes,
        never a re-iterated answer drifting in the low-order bits).
        ``warm_seed`` is the (n_nodes,) x0 for a moved graph under the
        per-algorithm warm-start contract; the monotone-unsafe loud
        cold is counted/logged here."""
        from ..storage.storage import ChangeLogUnknowable
        with self._lock:
            entry = self._entry(storage)
            if entry is None:
                return None, None
            sol = entry["solutions"].get(algo)
            if sol is None or sol.params_key != tuple(params_key):
                return None, None
            if not np.array_equal(entry["node_gids"], graph.node_gids):
                return None, None  # dense ids shifted: seed meaningless
            if version == sol.version:
                return np.asarray(sol.x), None
            monotone_ok = sol.monotone_ok
            if version != entry["version"]:
                changed = storage.changes_between(entry["version"],
                                                  version)
                if isinstance(changed, ChangeLogUnknowable) \
                        or graph.host_coo is None:
                    monotone_ok = False
                else:
                    changed_idx = [graph.gid_to_idx[g] for g in changed
                                   if g in graph.gid_to_idx]
                    d = diff_changed_coo(
                        entry["host_coo"], graph.host_coo, changed_idx,
                        graph.n_nodes, entry["version"], version)
                    monotone_ok = monotone_ok and d.adds_only
            warm, reason = warm_start_decision(algo, monotone_ok)
            if not warm:
                record_cold_start(algo, reason)
                entry["solutions"].pop(algo, None)
                return None, None
            return None, np.asarray(sol.x)

    def store(self, storage, graph: DeviceGraph, version: int,
              algo: str, params_key: tuple, x) -> None:
        if graph.host_coo is None:
            return
        from ..storage.storage import ChangeLogUnknowable
        with self._lock:
            entry = self._entry(storage)
            if entry is None or not np.array_equal(
                    entry["node_gids"], graph.node_gids):
                entry = {"version": int(version),
                         "host_coo": graph.host_coo,
                         "node_gids": graph.node_gids,
                         "solutions": {}}
            elif entry["version"] != version:
                # the pool snapshot moves to this version: fold the step
                # delta into every retained solution's monotone flag
                changed = storage.changes_between(entry["version"],
                                                  version)
                if isinstance(changed, ChangeLogUnknowable):
                    for s in entry["solutions"].values():
                        s.monotone_ok = False
                else:
                    changed_idx = [graph.gid_to_idx[g] for g in changed
                                   if g in graph.gid_to_idx]
                    d = diff_changed_coo(
                        entry["host_coo"], graph.host_coo, changed_idx,
                        graph.n_nodes, entry["version"], version)
                    if not d.adds_only:
                        for s in entry["solutions"].values():
                            s.monotone_ok = False
                entry["version"] = int(version)
                entry["host_coo"] = graph.host_coo
            entry["solutions"][algo] = _Solution(
                x=np.asarray(x), version=int(version),
                params_key=tuple(params_key), monotone_ok=True)
            self._pool[storage] = entry

    def clear(self) -> None:
        import weakref
        with self._lock:
            self._pool = weakref.WeakKeyDictionary()


GLOBAL_WARM_POOL = LocalWarmPool()
