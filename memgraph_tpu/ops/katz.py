"""Katz centrality / HITS / degree centrality on the semiring core.

Counterpart of /root/reference/query_modules/katz_centrality_module/ and
mage/cpp/cugraph_module/algorithms/katz.cu: fixed-point iteration
x_{t+1} = alpha * A^T x_t + beta as a plus-times semiring fixpoint with
the update + L-infinity convergence check fused into the matvec body.
Converges for alpha < 1/lambda_max(A).  On accelerator hosts with large
graphs the dispatch routes through the gather-free MXU backend
(semiring.mxu_fixpoint, normalize=False — a win katz never had before
the r10 core: pagerank's fast path is now every plus-times algorithm's).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import semiring as S
from .csr import DeviceGraph


def _katz_setup(A, P, n_out):
    valid_f = (jnp.arange(n_out, dtype=jnp.int32)
               < P["n_nodes"]).astype(jnp.float32)
    return {"valid_f": valid_f,
            "x0": jnp.zeros(n_out, dtype=jnp.float32)}


def _katz_epilogue(x, acc, env, P):
    """Fused katz update: new = valid * (alpha * A^T x + beta), with the
    L-infinity convergence partial in the same body."""
    new_x = env["valid_f"] * (P["alpha"] * acc + P["beta"])
    err = jnp.max(jnp.abs(new_x - x))
    return new_x, err


def _katz_mxu_epilogue(x, acc, env, P):
    """The same update on the MXU backend's out-labeled accumulator."""
    new_x = env["valid"] * (P["alpha"] * acc + P["beta"])
    err = jnp.max(jnp.abs(new_x - x))
    return new_x, err


def _katz_normalized(x, normalized: bool):
    if not normalized:
        return x
    x = jnp.asarray(x)
    norm = jnp.sqrt(jnp.sum(x * x))
    return x / jnp.maximum(norm, 1e-30)


def katz_centrality(graph: DeviceGraph, alpha: float = 0.2, beta: float = 1.0,
                    max_iterations: int = 100, tol: float = 1e-6,
                    normalized: bool = False, mesh=None,
                    precision: str = "f32", x0=None):
    """Returns (centralities[:n_nodes], error, iterations).

    `mesh` (MeshContext | Mesh | int | None) routes through the
    multi-chip layer; `precision` selects the f32/bf16/int8 variants
    (see ops.pagerank.pagerank). `x0` warm-starts from a previous
    solution (contraction for alpha < 1/λ_max — same fixpoint at the
    same tol from any seed; ops/delta.py commit-then-CALL contract)."""
    backend, ctx = S.route_backend(graph, mesh, semiring="plus_times",
                                   precision=precision)
    if backend == "mesh":
        from ..parallel.analytics import katz_mesh
        with S.backend_extent("mesh"):
            return katz_mesh(graph, ctx, alpha=alpha, beta=beta,
                             max_iterations=max_iterations, tol=tol,
                             normalized=normalized, precision=precision,
                             x0=x0)
    if backend == "mxu":
        x, err, iters = S.mxu_fixpoint(
            graph, epilogue=_katz_mxu_epilogue,
            params={"alpha": np.float32(alpha), "beta": np.float32(beta)},
            max_iterations=max_iterations, tol=tol, normalize=False,
            precision=precision, cache_tag="katz", x0=x0)
        # mxu_fixpoint already shipped host values; the asarray below
        # only undoes the jnp normalize (one transfer, not a split)
        return (np.asarray(_katz_normalized(x, normalized))[:graph.n_nodes],  # mglint: disable=MG009 — x/err/iters are host values from mxu_fixpoint; this is the single normalize readback
                float(err), int(iters))  # mglint: disable=MG009 — host floats from mxu_fixpoint
    x0_pad = None
    if x0 is not None:
        buf = np.zeros(graph.n_pad, dtype=np.float32)
        arr = np.asarray(x0, dtype=np.float32)[:graph.n_nodes]
        buf[:len(arr)] = arr
        x0_pad = jnp.asarray(buf)
    x, err, iters = S.fixpoint(
        "plus_times",
        arrays={"src": graph.csc_src, "dst": graph.csc_dst,
                "w": graph.csc_weights},
        params={"n_nodes": np.int32(graph.n_nodes),
                "alpha": np.float32(alpha), "beta": np.float32(beta),
                "tol": np.float32(tol)},
        n_out=graph.n_pad, setup=_katz_setup, epilogue=_katz_epilogue,
        max_iterations=max_iterations, sorted=True, precision=precision,
        x0=x0_pad)
    x = _katz_normalized(x, normalized)
    # one fused host transfer for the whole result tuple (MG009)
    x_h, err_h, iters_h = jax.device_get((x[:graph.n_nodes], err, iters))  # mglint: disable=MG009 — results must ship host; this IS the single fused transfer for the whole tuple
    return x_h, float(err_h), int(iters_h)


def _hits_step(x, A, env, P, n_out):
    """One HITS round: two plus-times matvecs (authority then hub), each
    L2-normalized — a custom step over a (hub, auth) state pair.
    src is CSR order (sorted by src) → both reductions sorted: auth by
    dst rides the CSC mirror passed as (csrc, cdst)."""
    hub, _auth = x
    valid_f = env["valid_f"]
    new_auth = S.spmv("plus_times", hub, A["csrc"], A["cdst"], A["cw"],
                      n_out=n_out, sorted=True) * valid_f
    new_auth = new_auth / jnp.maximum(
        jnp.sqrt(jnp.sum(new_auth ** 2)), 1e-30)
    new_hub = S.spmv("plus_times", new_auth, A["dst"], A["src"], A["w"],
                     n_out=n_out, sorted=True) * valid_f
    new_hub = new_hub / jnp.maximum(
        jnp.sqrt(jnp.sum(new_hub ** 2)), 1e-30)
    return new_hub, new_auth


def _hits_setup(A, P, n_out):
    valid_f = (jnp.arange(n_out, dtype=jnp.int32)
               < P["n_nodes"]).astype(jnp.float32)
    return {"valid_f": valid_f, "x0": (valid_f, valid_f)}


def _hits_epilogue(x, acc, env, P):
    hub, auth = x
    new_hub, new_auth = acc
    err = jnp.max(jnp.abs(new_auth - auth)) + jnp.max(jnp.abs(new_hub - hub))
    return (new_hub, new_auth), err


def hits(graph: DeviceGraph, max_iterations: int = 100, tol: float = 1e-6):
    """HITS hubs/authorities (analog of cugraph_module/algorithms/hits.cu)."""
    (hub, auth), err, iters = S.fixpoint(
        "plus_times",
        arrays={"src": graph.src_idx, "dst": graph.col_idx,
                "w": graph.weights,
                "csrc": graph.csc_src, "cdst": graph.csc_dst,
                "cw": graph.csc_weights},
        params={"n_nodes": np.int32(graph.n_nodes),
                "tol": np.float32(tol)},
        n_out=graph.n_pad, setup=_hits_setup, step=_hits_step,
        epilogue=_hits_epilogue, max_iterations=max_iterations)
    return hub[:graph.n_nodes], auth[:graph.n_nodes], float(err), int(iters)


def degree_centrality(graph: DeviceGraph, direction: str = "total"):
    """Degree centrality (analog of mage/cpp/degree_centrality_module)."""
    n_pad = graph.n_pad
    mask = (jnp.arange(graph.e_pad) < graph.n_edges).astype(jnp.float32)
    out_deg = S.edge_reduce("sum", mask, graph.src_idx, n_pad)
    in_deg = S.edge_reduce("sum", mask, graph.col_idx, n_pad)
    denom = jnp.maximum(graph.n_nodes - 1, 1)
    if direction == "in":
        d = in_deg
    elif direction == "out":
        d = out_deg
    else:
        d = in_deg + out_deg
    return (d / denom)[:graph.n_nodes]
