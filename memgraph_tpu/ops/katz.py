"""Katz centrality on TPU.

Counterpart of /root/reference/query_modules/katz_centrality_module/ and
mage/cpp/cugraph_module/algorithms/katz.cu: fixed-point iteration
x_{t+1} = alpha * A^T x_t + beta, expressed as gather + segment-sum, with an
L-infinity convergence check. Converges for alpha < 1/lambda_max(A).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .csr import DeviceGraph


@partial(jax.jit, static_argnames=("n_pad", "max_iterations"))
def _katz_kernel(src, dst, weights, n_nodes, n_pad: int, alpha, beta,
                 max_iterations: int, tol, normalized):
    valid_f = (jnp.arange(n_pad, dtype=jnp.int32) < n_nodes).astype(jnp.float32)
    x0 = jnp.zeros(n_pad, dtype=jnp.float32)

    def body(carry):
        x, _, it = carry
        acc = jax.ops.segment_sum(x[src] * weights, dst, num_segments=n_pad,
                                  indices_are_sorted=True)
        new_x = valid_f * (alpha * acc + beta)
        err = jnp.max(jnp.abs(new_x - x))
        return new_x, err, it + 1

    def cond(carry):
        _, err, it = carry
        return (err > tol) & (it < max_iterations)

    x, err, iters = jax.lax.while_loop(
        cond, body, (x0, jnp.float32(jnp.inf), jnp.int32(0)))
    norm = jnp.sqrt(jnp.sum(x * x))
    x = jnp.where(normalized, x / jnp.maximum(norm, 1e-30), x)
    return x, err, iters


def katz_centrality(graph: DeviceGraph, alpha: float = 0.2, beta: float = 1.0,
                    max_iterations: int = 100, tol: float = 1e-6,
                    normalized: bool = False, mesh=None):
    """Returns (centralities[:n_nodes], error, iterations).

    `mesh` (MeshContext | Mesh | int | None) routes through the
    multi-chip layer; see ops.pagerank.pagerank."""
    from ..parallel.mesh import resolve_mesh
    ctx = resolve_mesh(mesh)
    if ctx is not None:
        from ..parallel.analytics import katz_mesh
        return katz_mesh(graph, ctx, alpha=alpha, beta=beta,
                         max_iterations=max_iterations, tol=tol,
                         normalized=normalized)
    x, err, iters = _katz_kernel(
        graph.csc_src, graph.csc_dst, graph.csc_weights,
        jnp.int32(graph.n_nodes), graph.n_pad,
        jnp.float32(alpha), jnp.float32(beta), max_iterations,
        jnp.float32(tol), jnp.bool_(normalized))
    return x[:graph.n_nodes], float(err), int(iters)


@partial(jax.jit, static_argnames=("n_pad", "max_iterations"))
def _hits_kernel(src, dst, weights, csrc, cdst, cweights, n_nodes,
                 n_pad: int, max_iterations: int, tol):
    valid_f = (jnp.arange(n_pad, dtype=jnp.int32) < n_nodes).astype(jnp.float32)
    hub0 = valid_f
    auth0 = valid_f

    def body(carry):
        hub, auth, _, it = carry
        # src here is CSR order (sorted by src) → both reductions sorted:
        # auth by dst uses the CSC mirror passed as (csrc, cdst)
        new_auth = jax.ops.segment_sum(hub[csrc] * cweights, cdst,
                                       num_segments=n_pad,
                                       indices_are_sorted=True) * valid_f
        new_auth = new_auth / jnp.maximum(jnp.sqrt(jnp.sum(new_auth ** 2)), 1e-30)
        new_hub = jax.ops.segment_sum(new_auth[dst] * weights, src,
                                      num_segments=n_pad,
                                      indices_are_sorted=True) * valid_f
        new_hub = new_hub / jnp.maximum(jnp.sqrt(jnp.sum(new_hub ** 2)), 1e-30)
        err = jnp.max(jnp.abs(new_auth - auth)) + jnp.max(jnp.abs(new_hub - hub))
        return new_hub, new_auth, err, it + 1

    def cond(carry):
        _, _, err, it = carry
        return (err > tol) & (it < max_iterations)

    hub, auth, err, iters = jax.lax.while_loop(
        cond, body, (hub0, auth0, jnp.float32(jnp.inf), jnp.int32(0)))
    return hub, auth, err, iters


def hits(graph: DeviceGraph, max_iterations: int = 100, tol: float = 1e-6):
    """HITS hubs/authorities (analog of cugraph_module/algorithms/hits.cu)."""
    hub, auth, err, iters = _hits_kernel(
        graph.src_idx, graph.col_idx, graph.weights,
        graph.csc_src, graph.csc_dst, graph.csc_weights,
        jnp.int32(graph.n_nodes), graph.n_pad, max_iterations,
        jnp.float32(tol))
    return hub[:graph.n_nodes], auth[:graph.n_nodes], float(err), int(iters)


def degree_centrality(graph: DeviceGraph, direction: str = "total"):
    """Degree centrality (analog of mage/cpp/degree_centrality_module)."""
    n_pad = graph.n_pad
    mask = (jnp.arange(graph.e_pad) < graph.n_edges).astype(jnp.float32)
    out_deg = jax.ops.segment_sum(mask, graph.src_idx, num_segments=n_pad)
    in_deg = jax.ops.segment_sum(mask, graph.col_idx, num_segments=n_pad)
    denom = jnp.maximum(graph.n_nodes - 1, 1)
    if direction == "in":
        d = in_deg
    elif direction == "out":
        d = out_deg
    else:
        d = in_deg + out_deg
    return (d / denom)[:graph.n_nodes]
