"""Node similarity: Jaccard / overlap / cosine over neighborhoods.

Counterpart of /root/reference/mage/cpp/node_similarity_module/. Two
regimes:
  - dense MXU path (n_nodes <= dense_limit): boolean adjacency as a
    bfloat16 matrix; common-neighbor counts are one A @ A^T matmul — the
    formulation TPUs are built for
  - host path: per-pair neighbor-set intersection for specific pairs
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .csr import DeviceGraph

DENSE_LIMIT = 8192


@partial(jax.jit, static_argnames=("n", "mode"))
def _dense_similarity(src, dst, e_mask, n: int, mode: str):
    adj = jnp.zeros((n, n), dtype=jnp.float32)
    adj = adj.at[src, dst].max(e_mask)  # boolean adjacency (out-neighbors)
    common = jax.lax.dot_general(
        adj.astype(jnp.bfloat16), adj.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
    deg = jnp.sum(adj, axis=1)
    if mode == "jaccard":
        union = deg[:, None] + deg[None, :] - common
        return jnp.where(union > 0, common / jnp.maximum(union, 1e-9), 0.0)
    if mode == "overlap":
        m = jnp.minimum(deg[:, None], deg[None, :])
        return jnp.where(m > 0, common / jnp.maximum(m, 1e-9), 0.0)
    # cosine
    denom = jnp.sqrt(deg[:, None] * deg[None, :])
    return jnp.where(denom > 0, common / jnp.maximum(denom, 1e-9), 0.0)


def similarity_matrix(graph: DeviceGraph, mode: str = "jaccard"):
    """(n, n) similarity matrix via the MXU (n_nodes <= DENSE_LIMIT)."""
    if graph.n_nodes > DENSE_LIMIT:
        raise ValueError(
            f"dense similarity limited to {DENSE_LIMIT} nodes; "
            f"use pairwise_similarity for larger graphs")
    e_mask = (jnp.arange(graph.e_pad) < graph.n_edges).astype(jnp.float32)
    # clip sink ids into range for the scatter; masked entries write 0
    src = jnp.minimum(graph.src_idx, graph.n_nodes - 1)
    dst = jnp.minimum(graph.col_idx, graph.n_nodes - 1)
    return _dense_similarity(src, dst, e_mask, graph.n_nodes, mode)


def pairwise_similarity(graph: DeviceGraph, pairs, mode: str = "jaccard"):
    """[(i, j, score)] for explicit node-index pairs (host set ops)."""
    row_ptr = np.asarray(graph.row_ptr)
    col = np.asarray(graph.col_idx)

    def neigh(v):
        return set(col[row_ptr[v]:row_ptr[v + 1]].tolist())

    out = []
    cache: dict[int, set] = {}
    for (i, j) in pairs:
        si = cache.setdefault(i, neigh(i))
        sj = cache.setdefault(j, neigh(j))
        inter = len(si & sj)
        if mode == "jaccard":
            denom = len(si | sj)
        elif mode == "overlap":
            denom = min(len(si), len(sj))
        else:
            denom = (len(si) * len(sj)) ** 0.5
        out.append((i, j, inter / denom if denom else 0.0))
    return out
