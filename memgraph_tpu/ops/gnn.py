"""GraphSAGE in pure JAX — the GNN kernel behind link prediction and node
classification query modules.

Counterpart of the reference's DGL/PyTorch GNN stack
(mage/python/link_prediction.py, node_classification.py, mage/gnn.py) —
re-designed for TPU instead of translated: mean-aggregation is a sorted
segment_sum over the CSC edge arrays (the same ~3x-over-scatter layout the
analytics kernels use), the dense feature transforms are MXU matmuls, and
training steps are jitted end-to-end with optax.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

from . import semiring as S
from .csr import DeviceGraph


def init_sage_params(rng, in_dim, hidden_dim, out_dim, n_layers=2):
    """[(W_self, W_neigh, b)] per layer, Glorot-initialized."""
    dims = [in_dim] + [hidden_dim] * (n_layers - 1) + [out_dim]
    params = []
    for k in range(n_layers):
        rng, k1, k2 = jax.random.split(rng, 3)
        scale = jnp.sqrt(2.0 / (dims[k] + dims[k + 1]))
        params.append((
            jax.random.normal(k1, (dims[k], dims[k + 1])) * scale,
            jax.random.normal(k2, (dims[k], dims[k + 1])) * scale,
            jnp.zeros((dims[k + 1],)),
        ))
    return params


def _mean_aggregate(feats, csc_src, csc_dst, n_pad):
    """Undirected mean of neighbor features per node: a plus-first
    semiring SpMM (one sorted core pass per direction; csc_dst is
    sorted, the transpose direction costs a second reduction on swapped
    indices)."""
    summed = S.spmv("plus_first", feats, csc_src, csc_dst, n_out=n_pad,
                    sorted=True)
    summed = summed + S.spmv("plus_first", feats, csc_dst, csc_src,
                             n_out=n_pad)
    deg = S.edge_reduce("sum", jnp.ones_like(csc_dst, dtype=feats.dtype),
                        csc_dst, n_pad, sorted=True)
    deg = deg + S.edge_reduce(
        "sum", jnp.ones_like(csc_src, dtype=feats.dtype), csc_src, n_pad)
    return summed / jnp.maximum(deg, 1.0)[:, None]


def sage_forward(params, feats, csc_src, csc_dst, n_pad):
    """2-layer (or deeper) GraphSAGE embedding, bf16 matmuls on the MXU."""
    h = feats
    for k, (w_self, w_neigh, b) in enumerate(params):
        agg = _mean_aggregate(h, csc_src, csc_dst, n_pad)
        h = (h.astype(jnp.bfloat16) @ w_self.astype(jnp.bfloat16)
             + agg.astype(jnp.bfloat16) @ w_neigh.astype(jnp.bfloat16)
             ).astype(jnp.float32) + b
        if k < len(params) - 1:
            h = jax.nn.relu(h)
    return h


def _edge_scores(emb, src, dst):
    return jnp.sum(emb[src] * emb[dst], axis=-1)


@partial(jax.jit, static_argnames=("n_pad",))
def _link_loss(params, feats, csc_src, csc_dst, n_pad,
               pos_src, pos_dst, neg_src, neg_dst):
    emb = sage_forward(params, feats, csc_src, csc_dst, n_pad)
    pos = _edge_scores(emb, pos_src, pos_dst)
    neg = _edge_scores(emb, neg_src, neg_dst)
    scores = jnp.concatenate([pos, neg])
    labels = jnp.concatenate([jnp.ones_like(pos), jnp.zeros_like(neg)])
    return optax.sigmoid_binary_cross_entropy(scores, labels).mean()


@partial(jax.jit, static_argnames=("n_pad",))
def _classify_loss(params, feats, csc_src, csc_dst, n_pad,
                   label_idx, labels):
    logits = sage_forward(params, feats, csc_src, csc_dst, n_pad)
    sel = logits[label_idx]
    return optax.softmax_cross_entropy_with_integer_labels(
        sel, labels).mean()


def degree_features(graph: DeviceGraph, dim: int = 16):
    """Default node features when no properties are given: [log-degree,
    sin/cos positional bins] — cheap, deterministic, shape (n_pad, dim)."""
    deg = np.zeros(graph.n_pad, dtype=np.float32)
    m = graph.n_edges
    np.add.at(deg, np.asarray(graph.src_idx[:m]), 1.0)
    np.add.at(deg, np.asarray(graph.col_idx[:m]), 1.0)
    feats = np.zeros((graph.n_pad, dim), dtype=np.float32)
    feats[:, 0] = np.log1p(deg)
    idx = np.arange(graph.n_pad, dtype=np.float32)
    for k in range(1, dim):
        if k % 2:
            feats[:, k] = np.sin(idx / (10_000 ** (k / dim)))
        else:
            feats[:, k] = np.cos(idx / (10_000 ** (k / dim)))
    return jnp.asarray(feats)


def train_link_prediction(graph: DeviceGraph, feats=None, hidden_dim=64,
                          out_dim=32, n_layers=2, epochs=50, lr=1e-2,
                          neg_ratio=1, seed=0):
    """Returns (params, feats, [per-epoch {epoch, loss, auc}]).

    Positives are the graph's edges; negatives are uniform random pairs
    resampled per epoch (the reference's per-epoch negative sampling,
    link_prediction.py)."""
    if epochs <= 0:
        raise ValueError("epochs must be a positive integer")
    rng = jax.random.PRNGKey(seed)
    if feats is None:
        feats = degree_features(graph)
    rng, init_rng = jax.random.split(rng)
    params = init_sage_params(init_rng, feats.shape[1], hidden_dim,
                              out_dim, n_layers)
    opt = optax.adam(lr)
    opt_state = opt.init(params)
    m = graph.n_edges
    pos_src = graph.csc_src[:m]
    pos_dst = graph.csc_dst[:m]
    grad_fn = jax.value_and_grad(_link_loss)
    history = []
    for epoch in range(epochs):
        rng, k1, k2 = jax.random.split(rng, 3)
        neg_src = jax.random.randint(k1, (m * neg_ratio,), 0,
                                     graph.n_nodes)
        neg_dst = jax.random.randint(k2, (m * neg_ratio,), 0,
                                     graph.n_nodes)
        loss, grads = grad_fn(params, feats, graph.csc_src, graph.csc_dst,
                              graph.n_pad, pos_src, pos_dst,
                              neg_src, neg_dst)
        updates, opt_state = opt.update(grads, opt_state)
        params = optax.apply_updates(params, updates)
        history.append({"epoch": epoch + 1, "loss": float(loss)})
    emb = sage_forward(params, feats, graph.csc_src, graph.csc_dst,
                       graph.n_pad)
    history[-1]["auc"] = _auc(emb, pos_src, pos_dst, graph.n_nodes, rng)
    return params, feats, history


def _auc(emb, pos_src, pos_dst, n_nodes, rng):
    """Rank-based AUC (Mann-Whitney U / (n_pos * n_neg)) — O(m log m),
    no pairwise matrix."""
    k1, k2 = jax.random.split(rng)
    n = len(pos_src)
    neg_src = jax.random.randint(k1, (n,), 0, n_nodes)
    neg_dst = jax.random.randint(k2, (n,), 0, n_nodes)
    pos = np.asarray(_edge_scores(emb, pos_src, pos_dst))
    neg = np.asarray(_edge_scores(emb, neg_src, neg_dst))
    scores = np.concatenate([pos, neg])
    order = np.argsort(scores, kind="stable")
    ranks = np.empty_like(order, dtype=np.float64)
    ranks[order] = np.arange(1, len(scores) + 1)
    # average ties so equal scores contribute 0.5
    sorted_scores = scores[order]
    start = 0
    for end in range(1, len(scores) + 1):
        if end == len(scores) or sorted_scores[end] != sorted_scores[start]:
            if end - start > 1:
                ranks[order[start:end]] = (start + 1 + end) / 2.0
            start = end
    u = ranks[:n].sum() - n * (n + 1) / 2.0
    return float(u / (n * n)) if n else 0.0


def train_node_classification(graph: DeviceGraph, label_idx, labels,
                              feats=None, hidden_dim=64, n_layers=2,
                              epochs=100, lr=1e-2, seed=0):
    """Returns (params, feats, n_classes, [per-epoch {epoch, loss, acc}])."""
    if epochs <= 0:
        raise ValueError("epochs must be a positive integer")
    rng = jax.random.PRNGKey(seed)
    if feats is None:
        feats = degree_features(graph)
    n_classes = int(np.max(labels)) + 1
    rng, init_rng = jax.random.split(rng)
    params = init_sage_params(init_rng, feats.shape[1], hidden_dim,
                              n_classes, n_layers)
    opt = optax.adam(lr)
    opt_state = opt.init(params)
    label_idx = jnp.asarray(label_idx, dtype=jnp.int32)
    labels = jnp.asarray(labels, dtype=jnp.int32)
    grad_fn = jax.value_and_grad(_classify_loss)
    history = []
    for epoch in range(epochs):
        loss, grads = grad_fn(params, feats, graph.csc_src, graph.csc_dst,
                              graph.n_pad, label_idx, labels)
        updates, opt_state = opt.update(grads, opt_state)
        params = optax.apply_updates(params, updates)
        history.append({"epoch": epoch + 1, "loss": float(loss)})
    logits = sage_forward(params, feats, graph.csc_src, graph.csc_dst,
                          graph.n_pad)
    pred = np.asarray(jnp.argmax(logits[label_idx], axis=-1))
    history[-1]["acc"] = float(np.mean(pred == np.asarray(labels)))
    return params, feats, n_classes, history
