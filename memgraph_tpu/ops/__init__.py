"""TPU analytics kernels over immutable CSR graph snapshots.

This is the TPU-native analog of the reference's MAGE algorithm layer
(/root/reference/mage/cpp, mage/cpp/cugraph_module/algorithms/*.cu): instead
of C++/CUDA modules walking an adjacency-list snapshot, the graph is exported
once into device-resident CSR arrays (csr.py) and algorithms run as jitted
XLA programs built from segment reductions (`jax.ops.segment_sum`-style),
`lax.while_loop` iteration, and MXU matmuls for the dense paths (kNN,
embeddings). Static shapes throughout: edge/vertex arrays are padded to
bucketed sizes so recompilation is amortized across graph mutations.
"""

from .csr import DeviceGraph, ShardedCSR, export_csr, shard_csr, GraphCache

# --------------------------------------------------------------------------
# SpMV-shaped algorithm registry (semiring-core + mesh coverage contract)
# --------------------------------------------------------------------------
# Every algorithm whose inner loop is an SpMV shape (per-edge gather +
# segment reduction inside a while_loop) rides the semiring kernel core
# (ops/semiring.py) and inherits the multi-chip mesh path from the
# shared partition-centric kernels — unless it declares a justified
# exemption here. mglint's MG005 registry-coverage rule enforces the
# contract every way:
#   * each entry declares "core": the SEMIRINGS key its inner loop
#     iterates (or "blocks" when it composes the core's edge_reduce /
#     spmv building blocks in a custom round, e.g. labelprop's sorted
#     run-length election) — validated against ops/semiring.py;
#   * each entry needs exactly one of "sharded" (a "module:function"
#     target that must statically resolve) or "exempt" (a real
#     justification, not a stub);
#   * every ops/ module whose AST shows the SpMV shape OR that imports
#     the semiring core must be covered by some entry, so a new
#     algorithm cannot silently miss the mesh; and
#   * NO ops/ module outside the core may hand-roll a segment_* +
#     while_loop pipeline (the "spmv-handrolled" sweep) — new code goes
#     through the core or it fails the gate.
# tests/test_sharded_analytics.py resolves every "sharded" target at
# runtime and tier-1 runs sharded-vs-single equivalence for the core
# algorithms; tests/test_semiring.py pins old-vs-new f32 bit-exactness.
SPMV_ALGORITHMS = {
    "pagerank": {
        "entry": "memgraph_tpu.ops.pagerank:pagerank",
        "core": "plus_times",
        "sharded": "memgraph_tpu.parallel.analytics:pagerank_mesh",
    },
    "personalized_pagerank": {
        "entry": "memgraph_tpu.ops.pagerank:personalized_pagerank",
        "core": "plus_times",
        "exempt": "per-user restart vectors belong to the batched-PPR "
                  "serving lane (ROADMAP item 3): one query's work is "
                  "latency-bound, and the mesh axis there is the batch "
                  "of personalization vectors, not edges",
    },
    "katz": {
        "entry": "memgraph_tpu.ops.katz:katz_centrality",
        "core": "plus_times",
        "sharded": "memgraph_tpu.parallel.analytics:katz_mesh",
    },
    "hits": {
        "entry": "memgraph_tpu.ops.katz:hits",
        "core": "plus_times",
        "exempt": "two interleaved L2-normalized reductions per round "
                  "(hub and authority) cost >= 2 collectives each "
                  "iteration; below the mesh win threshold even with "
                  "the r10 core (the normalizations are global sums)",
    },
    "labelprop": {
        "entry": "memgraph_tpu.ops.labelprop:label_propagation",
        "core": "blocks",
        "sharded": "memgraph_tpu.parallel.analytics:label_propagation_mesh",
    },
    "components": {
        "entry": "memgraph_tpu.ops.components:weakly_connected_components",
        "core": "min_first",
        "sharded": "memgraph_tpu.parallel.analytics:components_mesh",
    },
    "scc": {
        "entry": "memgraph_tpu.ops.components:strongly_connected_components",
        "core": "min_first",
        "exempt": "host-driven multi-round FW-BW coloring; the round "
                  "count is data-dependent and each round already runs "
                  "the jitted masked min-first propagation, so the mesh "
                  "story needs the device-resident frontier work first",
    },
    "sssp": {
        "entry": "memgraph_tpu.ops.traversal:sssp",
        "core": "min_plus",
        "sharded": "memgraph_tpu.parallel.analytics:sssp_mesh",
    },
    "bfs_layers": {
        "entry": "memgraph_tpu.ops.traversal:bfs_levels",
        "core": "min_plus",
        "sharded": "memgraph_tpu.parallel.analytics:bfs_mesh",
    },
    "betweenness": {
        "entry": "memgraph_tpu.ops.betweenness:betweenness_centrality",
        "core": "plus_first",
        "exempt": "Brandes is a batch over SOURCES (forward + backward "
                  "sweep per source); the profitable mesh axis is the "
                  "source batch, planned with the batched-PPR lane "
                  "(ROADMAP item 3), not the edge axis",
    },
    "gnn": {
        "entry": "memgraph_tpu.ops.gnn:sage_forward",
        "core": "plus_first",
        "exempt": "GraphSAGE aggregation is a plus-first SpMM over "
                  "dense (n, d) feature blocks; its mesh axis is the "
                  "2D data x model embedding-training mesh "
                  "(parallel.mesh.make_mesh_2d), not the edge axis the "
                  "partition-centric kernels shard",
    },
    # ---- mglane: compiled Cypher read pipelines (query/plan/lane.py) ----
    "lane_agg": {
        "entry": "memgraph_tpu.ops.pipeline:masked_aggregate",
        "core": "blocks",
        "exempt": "OLTP read-lane aggregate epilogue: a single fused "
                  "masked-reduction program per plan-cache fingerprint; "
                  "one query's columns are latency-bound and fit one "
                  "device, so the mesh axis (concurrent queries) is the "
                  "serving plane's batcher, not edge sharding",
    },
    "lane_hops": {
        "entry": "memgraph_tpu.ops.pipeline:hop_counts",
        "core": "plus_first",
        "exempt": "1-2 hop masked frontier counts for the compiled read "
                  "lane; a fixed-depth (non-iterating) spmv chain whose "
                  "per-query latency budget is OLTP-scale — sharding "
                  "one query's two matvecs across chips costs more in "
                  "collectives than it saves",
    },
    "lane_topk": {
        "entry": "memgraph_tpu.ops.pipeline:masked_topk",
        "core": "blocks",
        "exempt": "ORDER BY LIMIT k as one fused mask + stable argsort "
                  "program; single-device by construction (the sort is "
                  "over one query's filtered column, not the graph's "
                  "edge axis the mesh kernels shard)",
    },
}

__all__ = ["DeviceGraph", "ShardedCSR", "export_csr", "shard_csr",
           "GraphCache", "SPMV_ALGORITHMS"]
