"""TPU analytics kernels over immutable CSR graph snapshots.

This is the TPU-native analog of the reference's MAGE algorithm layer
(/root/reference/mage/cpp, mage/cpp/cugraph_module/algorithms/*.cu): instead
of C++/CUDA modules walking an adjacency-list snapshot, the graph is exported
once into device-resident CSR arrays (csr.py) and algorithms run as jitted
XLA programs built from segment reductions (`jax.ops.segment_sum`-style),
`lax.while_loop` iteration, and MXU matmuls for the dense paths (kNN,
embeddings). Static shapes throughout: edge/vertex arrays are padded to
bucketed sizes so recompilation is amortized across graph mutations.
"""

from .csr import DeviceGraph, export_csr, GraphCache

__all__ = ["DeviceGraph", "export_csr", "GraphCache"]
