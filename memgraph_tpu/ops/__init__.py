"""TPU analytics kernels over immutable CSR graph snapshots.

This is the TPU-native analog of the reference's MAGE algorithm layer
(/root/reference/mage/cpp, mage/cpp/cugraph_module/algorithms/*.cu): instead
of C++/CUDA modules walking an adjacency-list snapshot, the graph is exported
once into device-resident CSR arrays (csr.py) and algorithms run as jitted
XLA programs built from segment reductions (`jax.ops.segment_sum`-style),
`lax.while_loop` iteration, and MXU matmuls for the dense paths (kNN,
embeddings). Static shapes throughout: edge/vertex arrays are padded to
bucketed sizes so recompilation is amortized across graph mutations.
"""

from .csr import DeviceGraph, ShardedCSR, export_csr, shard_csr, GraphCache

# --------------------------------------------------------------------------
# SpMV-shaped algorithm registry (mesh-path coverage contract)
# --------------------------------------------------------------------------
# Every algorithm whose inner loop is an SpMV shape (per-edge gather +
# segment reduction inside a while_loop) inherits the multi-chip mesh
# path from the shared partition-centric core — unless it declares a
# justified exemption here. mglint's MG005 registry-coverage rule
# enforces the contract both ways:
#   * each entry needs exactly one of "sharded" (a "module:function"
#     target that must statically resolve) or "exempt" (a real
#     justification, not a stub), and
#   * every ops/ module whose AST shows the SpMV shape must be covered
#     by some entry, so a new algorithm cannot silently miss the mesh.
# tests/test_sharded_analytics.py resolves every "sharded" target at
# runtime and tier-1 runs sharded-vs-single equivalence for the core
# four (pagerank / katz / labelprop / components).
SPMV_ALGORITHMS = {
    "pagerank": {
        "entry": "memgraph_tpu.ops.pagerank:pagerank",
        "sharded": "memgraph_tpu.parallel.analytics:pagerank_mesh",
    },
    "personalized_pagerank": {
        "entry": "memgraph_tpu.ops.pagerank:personalized_pagerank",
        "exempt": "per-user restart vectors belong to the batched-PPR "
                  "serving lane (ROADMAP item 3): one query's work is "
                  "latency-bound, and the mesh axis there is the batch "
                  "of personalization vectors, not edges",
    },
    "katz": {
        "entry": "memgraph_tpu.ops.katz:katz_centrality",
        "sharded": "memgraph_tpu.parallel.analytics:katz_mesh",
    },
    "hits": {
        "entry": "memgraph_tpu.ops.katz:hits",
        "exempt": "two interleaved L2-normalized reductions per round "
                  "(hub and authority) cost >= 2 collectives each "
                  "iteration; below the mesh win threshold until the "
                  "fused-normalization core lands (ROADMAP item 2)",
    },
    "labelprop": {
        "entry": "memgraph_tpu.ops.labelprop:label_propagation",
        "sharded": "memgraph_tpu.parallel.analytics:label_propagation_mesh",
    },
    "components": {
        "entry": "memgraph_tpu.ops.components:weakly_connected_components",
        "sharded": "memgraph_tpu.parallel.analytics:components_mesh",
    },
    "scc": {
        "entry": "memgraph_tpu.ops.components:strongly_connected_components",
        "exempt": "host-driven multi-round FW-BW coloring; the round "
                  "count is data-dependent and each round already runs "
                  "the jitted min-propagation, so the mesh story needs "
                  "the device-resident frontier work first",
    },
    "sssp": {
        "entry": "memgraph_tpu.ops.traversal:sssp",
        "sharded": "memgraph_tpu.parallel.analytics:sssp_mesh",
    },
    "bfs_layers": {
        "entry": "memgraph_tpu.ops.traversal:bfs_levels",
        "exempt": "frontier-based traversal: per-level frontiers are "
                  "sparse and tiny relative to the edge set; edge-mesh "
                  "sharding adds a collective per level for no win at "
                  "current scales",
    },
    "betweenness": {
        "entry": "memgraph_tpu.ops.betweenness:betweenness_centrality",
        "exempt": "Brandes is a batch over SOURCES (forward + backward "
                  "sweep per source); the profitable mesh axis is the "
                  "source batch, planned with the batched-PPR lane "
                  "(ROADMAP item 3), not the edge axis",
    },
}

__all__ = ["DeviceGraph", "ShardedCSR", "export_csr", "shard_csr",
           "GraphCache", "SPMV_ALGORITHMS"]
