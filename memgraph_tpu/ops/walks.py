"""Batched random walks on device (node2vec and friends).

Counterpart of /root/reference/mage/python/node2vec.py +
query_modules/node2vec_online_module/: instead of per-walk host loops, all B
walks advance one step per `lax.scan` iteration — a (B,) gather into CSR plus
vectorized sampling. Second-order (p, q) bias uses rejection sampling
(the alias-free formulation used by large-scale walk engines), with edge
membership tested by binary search inside the CSR row (rows are sorted by
destination — csr.py exports in (src, dst) lexicographic order).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .csr import DeviceGraph


def _row_degree(row_ptr, v):
    return row_ptr[v + 1] - row_ptr[v]


def _sample_neighbor(row_ptr, col_idx, v, u):
    """Uniform neighbor of v (u ~ U[0,1)); returns v itself if no neighbors."""
    deg = _row_degree(row_ptr, v)
    off = jnp.minimum((u * deg.astype(jnp.float32)).astype(jnp.int32),
                      jnp.maximum(deg - 1, 0))
    nxt = col_idx[row_ptr[v] + off]
    return jnp.where(deg > 0, nxt, v)


def _has_edge(row_ptr, col_idx, v, t):
    """Binary search for edge v->t (rows sorted by destination).

    Fixed-iteration lower_bound (32 steps cover any e_pad < 2^32) so the
    loop unrolls/pipelines cleanly under vmap."""
    lo = row_ptr[v]
    hi = row_ptr[v + 1]

    def body(_, c):
        lo, hi = c
        mid = (lo + hi) // 2
        go_right = col_idx[mid] < t
        active = lo < hi
        return (jnp.where(active & go_right, mid + 1, lo),
                jnp.where(active & ~go_right, mid, hi))

    lo, _ = jax.lax.fori_loop(0, 32, body, (lo, hi))
    safe = jnp.minimum(lo, col_idx.shape[0] - 1)
    return (lo < row_ptr[v + 1]) & (col_idx[safe] == t)


@partial(jax.jit, static_argnames=("length", "n_pad"))
def _walk_kernel(row_ptr, col_idx, starts, key, length: int, n_pad: int,
                 p, q):
    """(B, length+1) node2vec walks. p = return parameter, q = in-out.
    p = q = 1 reduces to uniform DeepWalk sampling (fast path taken by the
    same code: the rejection test always accepts)."""
    B = starts.shape[0]
    max_prob = jnp.maximum(1.0, jnp.maximum(1.0 / p, 1.0 / q))

    def step(carry, key_step):
        cur, prev = carry
        k1, k2, k3 = jax.random.split(key_step, 3)
        u1 = jax.random.uniform(k1, (B,))
        cand = jax.vmap(_sample_neighbor, in_axes=(None, None, 0, 0))(
            row_ptr, col_idx, cur, u1)
        # rejection test for 2nd-order bias
        back = cand == prev
        connected = jax.vmap(_has_edge, in_axes=(None, None, 0, 0))(
            row_ptr, col_idx, prev, cand)
        alpha = jnp.where(back, 1.0 / p, jnp.where(connected, 1.0, 1.0 / q))
        accept = jax.random.uniform(k2, (B,)) <= alpha / max_prob
        # on reject, resample uniformly (single retry keeps shapes static;
        # bias error is negligible for p,q in the usual [0.25, 4] range)
        u2 = jax.random.uniform(k3, (B,))
        cand2 = jax.vmap(_sample_neighbor, in_axes=(None, None, 0, 0))(
            row_ptr, col_idx, cur, u2)
        nxt = jnp.where(accept, cand, cand2)
        return (nxt, cur), nxt

    keys = jax.random.split(key, length)
    (_, _), path = jax.lax.scan(step, (starts, starts), keys)
    return jnp.concatenate([starts[None, :], path], axis=0).T


def random_walks(graph: DeviceGraph, starts, length: int, key=None,
                 p: float = 1.0, q: float = 1.0):
    """Batched (possibly node2vec-biased) random walks.

    starts: (B,) dense node indices. Returns (B, length+1) int32 walks;
    walks stall (self-repeat) at sink nodes, matching common practice.
    """
    if key is None:
        key = jax.random.PRNGKey(0)
    starts = jnp.asarray(starts, dtype=jnp.int32)
    return _walk_kernel(graph.row_ptr, graph.col_idx, starts, key, length,
                        graph.n_pad, jnp.float32(p), jnp.float32(q))


@partial(jax.jit, static_argnames=("window",))
def walks_to_skipgram_pairs(walks, window: int = 5):
    """Expand walks (B, L) into (center, context) pairs within `window`,
    flattened to ((2*window)*B*L, 2) with -1 padding where out of range."""
    B, L = walks.shape
    pairs = []
    for off in range(1, window + 1):
        left = jnp.stack([walks[:, off:], walks[:, :-off]], axis=-1)
        right = jnp.stack([walks[:, :-off], walks[:, off:]], axis=-1)
        pad = jnp.full((B, off, 2), -1, dtype=walks.dtype)
        pairs.append(jnp.concatenate([left, pad], axis=1))
        pairs.append(jnp.concatenate([right, pad], axis=1))
    return jnp.concatenate(pairs, axis=1).reshape(-1, 2)
