"""Streaming ingestion: sources → transformation → retried transactions.

Counterpart of the reference's stream subsystem
(/root/reference/src/query/stream/streams.hpp:82 + src/integrations/
{kafka,pulsar}/): a stream couples a message source with a transformation
that turns message batches into parameterized queries, executed in a
conflict-retried transaction loop (interpreter config analog of
memgraph.cpp:652-653).

Sources are pluggable:
  kafka  — librdkafka-equivalent client, gated on an importable client lib
  pulsar — gated likewise
  file   — JSONL file tail (always available; the test/e2e source)

Transformations are Python callables registered with
@mgp.transformation (procedures/mgp.py), receiving a list of Message and
returning [{query, parameters}] — the same contract as the reference's
transformation modules.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from dataclasses import dataclass, field

from ..exceptions import QueryException

log = logging.getLogger(__name__)

TRANSFORMATIONS: dict = {}


def register_transformation(name: str, fn) -> None:
    TRANSFORMATIONS[name.lower()] = fn


@dataclass
class Message:
    payload: bytes
    topic: str = ""
    key: bytes | None = None
    timestamp: int = 0
    offset: int = 0

    def payload_str(self) -> str:
        return self.payload.decode("utf-8", errors="replace")


class FileSource:
    """JSONL file tail: each appended line is one message.

    Offset discipline (same as the Kafka source): poll() re-reads from
    the last COMMITTED offset; commit() — called only after the ingest
    transaction commits — advances it. A failed batch is redelivered on
    the next poll, so each line enters the graph exactly once per
    committed batch (reference: integrations/kafka/consumer.hpp:99
    TestStream/Check commit semantics)."""

    def __init__(self, path: str, topic: str = "file",
                 start_offset: int = 0):
        self.path = path
        self.topic = topic
        self._committed = start_offset
        self._pending = start_offset
        self._torn_tail: bytes | None = None   # unterminated tail seen

    def poll(self, batch_size: int, timeout_sec: float) -> list[Message]:
        out: list[Message] = []
        # a torn tail counts as stable only if seen by a PREVIOUS poll
        # call — the in-poll 50ms retry must not promote a mid-append
        # fragment (the producer may just be slow between writes)
        prev_tail = self._torn_tail
        seen_tail = None
        deadline = time.time() + timeout_sec
        while not out and time.time() < deadline:
            try:
                with open(self.path, "rb") as f:
                    f.seek(self._committed)
                    pending = self._committed
                    seen_tail = None
                    while len(out) < batch_size:
                        line = f.readline()
                        if not line:
                            break
                        if not line.endswith(b"\n"):
                            # unterminated: mid-append OR a finished file
                            # without a final newline
                            if line == prev_tail:
                                pending = f.tell()
                                if line.strip():
                                    out.append(Message(
                                        line.strip(), self.topic,
                                        offset=pending))
                            else:
                                seen_tail = line
                            break
                        pending = f.tell()
                        if line.strip():
                            out.append(Message(line.strip(), self.topic,
                                               offset=pending))
                    self._pending = pending if out else self._committed
            except FileNotFoundError:
                pass
            if not out:
                time.sleep(0.05)
        self._torn_tail = seen_tail
        return out

    def commit(self) -> None:
        self._committed = self._pending

    def rollback(self) -> None:
        self._pending = self._committed

    @property
    def committed_offset(self) -> int:
        return self._committed

    def close(self) -> None:
        pass


class KafkaSource:
    """Kafka consumer with EXACTLY-ONCE-per-committed-batch offsets:
    auto-commit is disabled; offsets are committed to the broker only
    after the ingest transaction commits, and a failed batch seeks back
    so the broker redelivers it (reference:
    /root/reference/src/integrations/kafka/consumer.hpp:99).

    client_module: confluent_kafka by default; tests inject a fake with
    the same Consumer/TopicPartition surface.
    """

    def __init__(self, topics, bootstrap_servers, consumer_group,
                 client_module=None):
        if client_module is None:
            try:
                import confluent_kafka as client_module
            except ImportError as e:
                raise QueryException(
                    "no Kafka client library available in this "
                    "environment; use a FILE stream or install "
                    "confluent-kafka") from e
        self._ck = client_module
        self._consumer = client_module.Consumer({
            "bootstrap.servers": bootstrap_servers,
            "group.id": consumer_group or "memgraph-tpu",
            "auto.offset.reset": "earliest",
            # offsets move ONLY via commit() after txn success
            "enable.auto.commit": False})
        self._consumer.subscribe(list(topics))
        self._batch_start: dict = {}    # (topic, partition) -> first offset

    def poll(self, batch_size: int, timeout_sec: float) -> list[Message]:
        msgs = self._consumer.consume(batch_size, timeout=timeout_sec)
        out = []
        self._batch_start = {}
        for m in msgs or []:
            if m.error():
                continue
            tp = (m.topic(), m.partition())
            if tp not in self._batch_start:
                self._batch_start[tp] = m.offset()
            out.append(Message(m.value(), m.topic(), m.key(),
                               m.timestamp()[1], m.offset()))
        return out

    def commit(self) -> None:
        if self._batch_start:
            self._consumer.commit(asynchronous=False)
            self._batch_start = {}

    def rollback(self) -> None:
        # seek back to each partition's batch start: the broker
        # redelivers the exact same batch on the next poll
        for (topic, partition), offset in self._batch_start.items():
            try:
                self._consumer.seek(
                    self._ck.TopicPartition(topic, partition, offset))
            except Exception:  # pragma: no cover - client-specific
                log.exception("kafka seek-back failed")
        self._batch_start = {}

    def close(self) -> None:
        self._consumer.close()


class PulsarSource:  # pragma: no cover - requires pulsar client lib
    def __init__(self, topics, service_url, consumer_group):
        try:
            import pulsar
        except ImportError as e:
            raise QueryException(
                "no Pulsar client library available in this environment; "
                "use a FILE stream or install pulsar-client") from e
        self._client = pulsar.Client(service_url)
        self._consumer = self._client.subscribe(
            list(topics), consumer_group or "memgraph-tpu")
        self._unacked = []

    def poll(self, batch_size, timeout_sec):
        out = []
        self._unacked = []
        deadline = time.time() + timeout_sec
        while len(out) < batch_size and time.time() < deadline:
            try:
                m = self._consumer.receive(
                    timeout_millis=int(timeout_sec * 1000))
            # mglint: disable=MG003 — the pulsar client raises its own
            # client-specific timeout type; a timeout just ends the batch
            except Exception:
                break
            out.append(Message(m.data(), m.topic_name()))
            self._unacked.append(m)
        return out

    def commit(self):
        for m in self._unacked:
            self._consumer.acknowledge(m)
        self._unacked = []

    def rollback(self):
        for m in self._unacked:
            self._consumer.negative_acknowledge(m)
        self._unacked = []

    def close(self):
        self._client.close()


@dataclass
class StreamSpec:
    name: str
    kind: str                 # 'kafka' | 'pulsar' | 'file'
    topics: list[str]
    transform: str
    batch_size: int = 100
    batch_interval_sec: float = 0.1
    bootstrap_servers: str = ""
    service_url: str = ""
    consumer_group: str = ""


class Stream:
    def __init__(self, spec: StreamSpec, interpreter_context):
        self.spec = spec
        self.ictx = interpreter_context
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.running = False
        self.processed_batches = 0
        self.processed_messages = 0
        self.last_error: str | None = None

    def _make_source(self):
        spec = self.spec
        if spec.kind == "file":
            return FileSource(spec.topics[0],
                              start_offset=self._restore_offset())
        if spec.kind == "kafka":
            return KafkaSource(spec.topics, spec.bootstrap_servers,
                               spec.consumer_group)
        if spec.kind == "pulsar":
            return PulsarSource(spec.topics, spec.service_url,
                                spec.consumer_group)
        raise QueryException(f"unknown stream kind {spec.kind}")

    def start(self) -> None:
        if self.running:
            raise QueryException(f"stream {self.spec.name!r} already running")
        transform = TRANSFORMATIONS.get(self.spec.transform.lower())
        if transform is None:
            raise QueryException(
                f"unknown transformation {self.spec.transform!r}")
        source = self._make_source()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, args=(source, transform), daemon=True)
        self.running = True
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.running = False

    def _loop(self, source, transform) -> None:
        from .interpreter import Interpreter
        from ..exceptions import SerializationError
        consecutive_failures = 0
        try:
            while not self._stop.is_set():
                batch = source.poll(self.spec.batch_size,
                                    self.spec.batch_interval_sec)
                if not batch:
                    continue
                try:
                    actions = transform(batch)
                except Exception as e:
                    # a transformation error stops the stream (reference
                    # semantics): skipping would silently drop data,
                    # redelivering would loop on the poison batch
                    source.rollback()
                    self.last_error = f"transform failed: {e}"
                    log.exception("stream %s transform failed; stopping",
                                  self.spec.name)
                    self.running = False
                    return
                # conflict-retried transaction (reference: retry interval
                # config, memgraph.cpp:652)
                committed = False
                for attempt in range(10):
                    interp = Interpreter(self.ictx, system=True)
                    try:
                        interp.execute("BEGIN")
                        for action in actions:
                            interp.execute(action["query"],
                                           action.get("parameters"))
                        interp.execute("COMMIT")
                        committed = True
                        break
                    except SerializationError:
                        interp.abort()
                        self.last_error = ("batch exhausted serialization "
                                           "retries")
                        time.sleep(0.01 * (attempt + 1))
                    except Exception as e:
                        interp.abort()
                        self.last_error = str(e)
                        log.exception("stream %s batch failed",
                                      self.spec.name)
                        break
                if committed:
                    # offsets advance ONLY now: a crash between COMMIT
                    # and commit() redelivers (at-least-once floor), a
                    # failed txn never advances (no message loss)
                    source.commit()
                    self._persist_offset(source)
                    consecutive_failures = 0
                    self.last_error = None
                    self.processed_batches += 1
                    self.processed_messages += len(batch)
                else:
                    source.rollback()
                    consecutive_failures += 1
                    if consecutive_failures >= 3:
                        log.error(
                            "stream %s: batch failed %d times; stopping",
                            self.spec.name, consecutive_failures)
                        self.running = False
                        return
        finally:
            source.close()

    def _persist_offset(self, source) -> None:
        committed = getattr(source, "committed_offset", None)
        kv = getattr(self.ictx, "kvstore", None)
        if committed is not None and kv is not None:
            kv.put(f"streams:offset:{self.spec.name}", str(committed))

    def _restore_offset(self) -> int:
        kv = getattr(self.ictx, "kvstore", None)
        if kv is None:
            return 0
        raw = kv.get_str(f"streams:offset:{self.spec.name}")
        return int(raw) if raw else 0


class Streams:
    """Registry of streams (reference: query/stream/streams.hpp Streams)."""

    def __init__(self, interpreter_context):
        self.ictx = interpreter_context
        self._lock = threading.Lock()
        self._streams: dict[str, Stream] = {}
        self._kv = getattr(interpreter_context, "kvstore", None)
        if self._kv is not None:
            self._restore()

    def _restore(self) -> None:
        """Reload persisted stream definitions (reference: RestoreStreams,
        memgraph.cpp:929). Streams come back in the stopped state."""
        import dataclasses
        for key, raw in self._kv.items_with_prefix("stream:"):
            data = json.loads(raw.decode("utf-8"))
            spec = StreamSpec(**data)
            self._streams[spec.name] = Stream(spec, self.ictx)

    def _persist(self, spec: StreamSpec) -> None:
        if self._kv is not None:
            import dataclasses
            self._kv.put(f"stream:{spec.name}",
                         json.dumps(dataclasses.asdict(spec)))

    def create(self, spec: StreamSpec) -> None:
        with self._lock:
            if spec.name in self._streams:
                raise QueryException(
                    f"stream {spec.name!r} already exists")
            self._streams[spec.name] = Stream(spec, self.ictx)
            self._persist(spec)

    def drop(self, name: str) -> None:
        with self._lock:
            stream = self._streams.pop(name, None)
            if stream is not None and self._kv is not None:
                self._kv.delete(f"stream:{name}")
                # a recreated stream of the same name must NOT resume at
                # the dropped stream's byte offset
                self._kv.delete(f"streams:offset:{name}")
        if stream is None:
            raise QueryException(f"stream {name!r} does not exist")
        if stream.running:
            stream.stop()

    def start(self, name: str) -> None:
        self._get(name).start()

    def stop(self, name: str) -> None:
        self._get(name).stop()

    def start_all(self) -> None:
        with self._lock:
            streams = list(self._streams.values())
        for s in streams:
            if not s.running:
                s.start()

    def stop_all(self) -> None:
        with self._lock:
            streams = list(self._streams.values())
        for s in streams:
            if s.running:
                s.stop()

    def _get(self, name: str) -> Stream:
        with self._lock:
            stream = self._streams.get(name)
        if stream is None:
            raise QueryException(f"stream {name!r} does not exist")
        return stream

    def show(self) -> list[list]:
        with self._lock:
            streams = list(self._streams.values())
        return [[s.spec.name, s.spec.kind, "|".join(s.spec.topics),
                 s.spec.transform, s.spec.batch_size,
                 "running" if s.running else "stopped",
                 s.processed_messages, s.last_error]  # mglint: disable=MG006 — s is a Stream, not Telemetry: field-name collision on last_error (unique-owner resolution)
                for s in sorted(streams, key=lambda s: s.spec.name)]


import weakref

_REGISTRY: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_REGISTRY_LOCK = threading.Lock()


def streams_of(interpreter_context) -> Streams:
    with _REGISTRY_LOCK:
        s = _REGISTRY.get(interpreter_context)
        if s is None:
            s = Streams(interpreter_context)
            _REGISTRY[interpreter_context] = s
        return s


# --- builtin transformations -------------------------------------------------

def _cypher_jsonl_transform(messages):
    """Each message: {"query": "...", "parameters": {...}} JSON."""
    actions = []
    for m in messages:
        obj = json.loads(m.payload_str())
        actions.append({"query": obj["query"],
                        "parameters": obj.get("parameters")})
    return actions


def _node_jsonl_transform(messages):
    """Each message: {"labels": [...], "properties": {...}} → CREATE."""
    actions = []
    for m in messages:
        obj = json.loads(m.payload_str())
        labels = "".join(f":{l}" for l in obj.get("labels", []))
        actions.append({
            "query": f"CREATE (n{labels} $props)",
            "parameters": {"props": obj.get("properties", {})}})
    return actions


register_transformation("transform.cypher", _cypher_jsonl_transform)
register_transformation("transform.nodes", _node_jsonl_transform)
