"""Streaming ingestion: sources → transformation → retried transactions.

Counterpart of the reference's stream subsystem
(/root/reference/src/query/stream/streams.hpp:82 + src/integrations/
{kafka,pulsar}/): a stream couples a message source with a transformation
that turns message batches into parameterized queries, executed in a
conflict-retried transaction loop (interpreter config analog of
memgraph.cpp:652-653).

Sources are pluggable:
  kafka  — librdkafka-equivalent client, gated on an importable client lib
  pulsar — gated likewise
  file   — JSONL file tail (always available; the test/e2e source)

Transformations are Python callables registered with
@mgp.transformation (procedures/mgp.py), receiving a list of Message and
returning [{query, parameters}] — the same contract as the reference's
transformation modules.

Exactly-once (r17): each batch's source position is staged into the
ingest transaction itself and WAL-framed as an OP_STREAM_OFFSET record
inside the same commit — replayed on recovery and shipped over
replication. The consumer-side ``source.commit()`` ack that follows is
an optimization (it saves redundant redelivery work), NOT the
correctness boundary: a crash anywhere between the data commit and the
ack resumes from ``storage.stream_offsets`` with zero duplicates. The
consumer loop is supervised (RetryPolicy-backed reconnect, typed
per-batch outcomes, bounded poison-batch retries that end in a
dead-letter buffer instead of a wedged loop) and backpressured (polling
pauses while the saturation plane reports downstream pressure).
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field

from ..exceptions import QueryException
from ..utils import faultinject as FI
from ..utils.retry import RetryPolicy

log = logging.getLogger(__name__)

TRANSFORMATIONS: dict = {}


def register_transformation(name: str, fn) -> None:
    TRANSFORMATIONS[name.lower()] = fn


@dataclass
class Message:
    payload: bytes
    topic: str = ""
    key: bytes | None = None
    timestamp: int = 0
    offset: int = 0

    def payload_str(self) -> str:
        return self.payload.decode("utf-8", errors="replace")


class FileSource:
    """JSONL file tail: each appended line is one message.

    Offset discipline (same as the Kafka source): poll() re-reads from
    the last COMMITTED offset; commit() — called only after the ingest
    transaction commits — advances it. A failed batch is redelivered on
    the next poll, so each line enters the graph exactly once per
    committed batch (reference: integrations/kafka/consumer.hpp:99
    TestStream/Check commit semantics)."""

    def __init__(self, path: str, topic: str = "file",
                 start_offset: int = 0):
        self.path = path
        self.topic = topic
        self._committed = start_offset
        self._pending = start_offset
        self._torn_tail: bytes | None = None   # unterminated tail seen

    def poll(self, batch_size: int, timeout_sec: float) -> list[Message]:
        out: list[Message] = []
        # a torn tail counts as stable only if seen by a PREVIOUS poll
        # call — the in-poll 50ms retry must not promote a mid-append
        # fragment (the producer may just be slow between writes)
        prev_tail = self._torn_tail
        seen_tail = None
        deadline = time.time() + timeout_sec
        while not out and time.time() < deadline:
            try:
                with open(self.path, "rb") as f:
                    f.seek(self._committed)
                    pending = self._committed
                    seen_tail = None
                    while len(out) < batch_size:
                        line = f.readline()
                        if not line:
                            break
                        if not line.endswith(b"\n"):
                            # unterminated: mid-append OR a finished file
                            # without a final newline
                            if line == prev_tail:
                                pending = f.tell()
                                if line.strip():
                                    out.append(Message(
                                        line.strip(), self.topic,
                                        offset=pending))
                            else:
                                seen_tail = line
                            break
                        pending = f.tell()
                        if line.strip():
                            out.append(Message(line.strip(), self.topic,
                                               offset=pending))
                    self._pending = pending if out else self._committed
            except FileNotFoundError:
                pass
            if not out:
                time.sleep(0.05)
        self._torn_tail = seen_tail
        return out

    def commit(self) -> None:
        self._committed = self._pending

    def rollback(self) -> None:
        self._pending = self._committed

    @property
    def committed_offset(self) -> int:
        return self._committed

    def pending_position(self) -> int:
        """The byte offset that becomes durable with the current batch
        (staged into the ingest transaction as its WAL offset record)."""
        return self._pending

    def lag(self) -> float:
        """Bytes between the committed offset and the file tail — the
        source backlog the ``stream.lag.*`` gauge / health check report."""
        try:
            return float(max(0, os.path.getsize(self.path)
                             - self._committed))
        except OSError:
            return 0.0

    def close(self) -> None:
        pass


class KafkaSource:
    """Kafka consumer with EXACTLY-ONCE-per-committed-batch offsets:
    auto-commit is disabled; offsets are committed to the broker only
    after the ingest transaction commits, and a failed batch seeks back
    so the broker redelivers it (reference:
    /root/reference/src/integrations/kafka/consumer.hpp:99).

    client_module: confluent_kafka by default; tests inject a fake with
    the same Consumer/TopicPartition surface.
    """

    def __init__(self, topics, bootstrap_servers, consumer_group,
                 client_module=None, start_positions=None):
        if client_module is None:
            try:
                import confluent_kafka as client_module
            except ImportError as e:
                raise QueryException(
                    "no Kafka client library available in this "
                    "environment; use a FILE stream or install "
                    "confluent-kafka") from e
        self._ck = client_module
        self._consumer = client_module.Consumer({
            "bootstrap.servers": bootstrap_servers,
            "group.id": consumer_group or "memgraph-tpu",
            "auto.offset.reset": "earliest",
            # offsets move ONLY via commit() after txn success
            "enable.auto.commit": False})
        self._consumer.subscribe(list(topics))
        self._batch_start: dict = {}    # (topic, partition) -> first offset
        # "topic:partition" -> next-offset-to-ingest, durably committed.
        # Seeded from the WAL-recovered storage.stream_offsets table:
        # messages below these broker offsets were already ingested in a
        # committed transaction and are dropped on redelivery, which is
        # what makes a crash between the data commit and the broker ack
        # exactly-once instead of at-least-once.
        self._positions: dict[str, int] = dict(start_positions or {})
        self._batch_next: dict[str, int] = {}

    def poll(self, batch_size: int, timeout_sec: float) -> list[Message]:
        msgs = self._consumer.consume(batch_size, timeout=timeout_sec)
        out = []
        self._batch_start = {}
        self._batch_next = {}
        for m in msgs or []:
            if m.error():
                continue
            key = f"{m.topic()}:{m.partition()}"
            if m.offset() < self._positions.get(key, -1):
                continue   # already durably ingested (recovered offset)
            tp = (m.topic(), m.partition())
            if tp not in self._batch_start:
                self._batch_start[tp] = m.offset()
            self._batch_next[key] = m.offset() + 1
            out.append(Message(m.value(), m.topic(), m.key(),
                               m.timestamp()[1], m.offset()))
        return out

    def pending_position(self) -> dict | None:
        """Per-partition next offsets that become durable with the
        current batch (merged over everything already committed)."""
        merged = dict(self._positions)
        merged.update(self._batch_next)
        return merged or None

    def commit(self) -> None:
        if self._batch_start:
            self._consumer.commit(asynchronous=False)
            self._positions.update(self._batch_next)
            self._batch_start = {}
            self._batch_next = {}

    def rollback(self) -> None:
        # seek back to each partition's batch start: the broker
        # redelivers the exact same batch on the next poll
        for (topic, partition), offset in self._batch_start.items():
            try:
                self._consumer.seek(
                    self._ck.TopicPartition(topic, partition, offset))
            except Exception:  # pragma: no cover - client-specific
                log.exception("kafka seek-back failed")
        self._batch_start = {}
        self._batch_next = {}

    def close(self) -> None:
        self._consumer.close()


class PulsarSource:  # pragma: no cover - requires pulsar client lib
    def __init__(self, topics, service_url, consumer_group):
        try:
            import pulsar
        except ImportError as e:
            raise QueryException(
                "no Pulsar client library available in this environment; "
                "use a FILE stream or install pulsar-client") from e
        self._client = pulsar.Client(service_url)
        self._consumer = self._client.subscribe(
            list(topics), consumer_group or "memgraph-tpu")
        self._unacked = []

    def poll(self, batch_size, timeout_sec):
        out = []
        self._unacked = []
        deadline = time.time() + timeout_sec
        while len(out) < batch_size and time.time() < deadline:
            try:
                m = self._consumer.receive(
                    timeout_millis=int(timeout_sec * 1000))
            # mglint: disable=MG003 — the pulsar client raises its own
            # client-specific timeout type; a timeout just ends the batch
            except Exception:
                break
            out.append(Message(m.data(), m.topic_name()))
            self._unacked.append(m)
        return out

    def commit(self):
        for m in self._unacked:
            self._consumer.acknowledge(m)
        self._unacked = []

    def rollback(self):
        for m in self._unacked:
            self._consumer.negative_acknowledge(m)
        self._unacked = []

    def close(self):
        self._client.close()


@dataclass
class StreamSpec:
    name: str
    kind: str                 # 'kafka' | 'pulsar' | 'file'
    topics: list[str]
    transform: str
    batch_size: int = 100
    batch_interval_sec: float = 0.1
    bootstrap_servers: str = ""
    service_url: str = ""
    consumer_group: str = ""
    # supervised-loop knobs (r17): a batch that keeps failing is retried
    # this many times, then quarantined into the dead-letter buffer (its
    # offset advances transactionally) instead of wedging the stream
    max_batch_retries: int = 3
    dead_letter_limit: int = 100


class BatchOutcome:
    """Typed per-batch outcomes of the supervised consumer loop."""
    COMMITTED = "committed"
    REDELIVERED = "redelivered"          # rolled back, will be re-polled
    DEAD_LETTERED = "dead_lettered"      # quarantined, offset advanced
    TRANSFORM_ERROR = "transform_error"
    TXN_ERROR = "txn_error"
    SERIALIZATION_EXHAUSTED = "serialization_exhausted"


class _StreamStopped(Exception):
    """Internal: the supervised loop must unwind and stop the stream."""


class Stream:
    def __init__(self, spec: StreamSpec, interpreter_context):
        self.spec = spec
        self.ictx = interpreter_context
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.running = False
        self.paused = False
        self.processed_batches = 0
        self.processed_messages = 0
        self.last_error: str | None = None
        self.last_outcome: str | None = None
        # poison-batch quarantine: (first-offset key, payloads, reason)
        # tuples, bounded — inspectable via SHOW STREAMS / stream stats
        self.dead_letter: collections.deque = collections.deque(
            maxlen=max(1, spec.dead_letter_limit))
        self._batch_failures = 0
        self._failed_batch_key = None
        self._last_pressure_check = 0.0
        self._pressure_reason: str | None = None

    def _make_source(self):
        spec = self.spec
        if spec.kind == "file":
            return FileSource(spec.topics[0],
                              start_offset=self._restore_offset())
        if spec.kind == "kafka":
            positions = self._recovered_position()
            return KafkaSource(spec.topics, spec.bootstrap_servers,
                               spec.consumer_group,
                               start_positions=positions
                               if isinstance(positions, dict) else None)
        if spec.kind == "pulsar":
            return PulsarSource(spec.topics, spec.service_url,
                                spec.consumer_group)
        raise QueryException(f"unknown stream kind {spec.kind}")

    def start(self) -> None:
        if self.running:
            raise QueryException(f"stream {self.spec.name!r} already running")
        transform = TRANSFORMATIONS.get(self.spec.transform.lower())
        if transform is None:
            raise QueryException(
                f"unknown transformation {self.spec.transform!r}")
        source = self._make_source()
        self._stop.clear()
        self._batch_failures = 0
        self._failed_batch_key = None
        self._thread = threading.Thread(
            target=self._loop, args=(source, transform), daemon=True)
        self.running = True
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.running = False

    def kill(self) -> None:
        """Chaos hook: die like a SIGKILLed consumer — stop the loop
        WITHOUT the graceful source ack/offset persistence. Everything
        durably committed stays committed; everything else redelivers."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._thread = None
        self.running = False

    # --- supervised consumer loop -------------------------------------------

    def _loop(self, source, transform) -> None:
        from ..observability.metrics import global_metrics
        try:
            while not self._stop.is_set():
                if self._backpressured():
                    continue
                try:
                    FI.fire("stream.poll")
                    batch = source.poll(self.spec.batch_size,
                                        self.spec.batch_interval_sec)
                except Exception as e:   # broker/file gone: reconnect
                    source = self._reconnect(source, e)
                    continue
                self._update_lag(source)
                if not batch:
                    continue
                t0 = time.perf_counter()
                outcome = self._process_batch(source, transform, batch)
                global_metrics.observe("stream.batch_latency_sec",
                                       time.perf_counter() - t0)
                self.last_outcome = outcome
        except _StreamStopped:
            pass
        finally:
            self.running = False
            if self.paused:
                self.paused = False
                global_metrics.set_gauge("stream.paused", 0.0)
            try:
                source.close()
            except Exception as e:  # noqa: BLE001 — best-effort close
                log.warning("stream %s source close failed: %s",
                            self.spec.name, e)

    def _backpressured(self) -> bool:
        """Pause polling while the saturation plane reports downstream
        pressure (replication lag, WAL fsync backlog, wedged analytics
        daemon): ingesting more would amplify the overload. Throttled —
        the probe reads a metrics snapshot, not per-iteration free."""
        from ..observability import stats as mgstats
        from ..observability.metrics import global_metrics
        now = time.monotonic()
        if now - self._last_pressure_check >= 0.25:
            self._last_pressure_check = now
            self._pressure_reason = \
                mgstats.global_saturation.ingest_pressure()
        if self._pressure_reason is None:
            if self.paused:
                self.paused = False
                global_metrics.set_gauge("stream.paused", 0.0)
                log.info("stream %s: downstream pressure cleared — "
                         "resuming polls", self.spec.name)
            return False
        if not self.paused:
            self.paused = True
            global_metrics.set_gauge("stream.paused", 1.0)
            global_metrics.increment("stream.pauses_total")
            log.warning("stream %s: pausing polls (downstream pressure: "
                        "%s)", self.spec.name, self._pressure_reason)
        self._stop.wait(0.05)
        return True

    def _reconnect(self, source, err):
        """RetryPolicy-backed source reconnect with backoff; exhausting
        the budget stops the stream with a loud typed error."""
        from ..observability.metrics import global_metrics
        global_metrics.increment("stream.poll_errors_total")
        self.last_error = f"poll failed: {err}"
        log.warning("stream %s: poll failed (%s) — reconnecting",
                    self.spec.name, err)
        try:
            source.close()
        except Exception as e:  # noqa: BLE001 — the source is already bad
            log.debug("stream %s: close of failed source: %s",
                      self.spec.name, e)
        last = err
        for delay in RetryPolicy(base_delay=0.05, max_delay=2.0,
                                 max_retries=6).delays():
            if self._stop.wait(delay):
                raise _StreamStopped
            try:
                fresh = self._make_source()
                global_metrics.increment("stream.reconnects_total")
                log.info("stream %s: reconnected", self.spec.name)
                return fresh
            except Exception as e:  # noqa: BLE001 — retried, then loud
                last = e
        self.last_error = f"reconnect budget exhausted: {last}"
        log.error("stream %s: reconnect budget exhausted (%s); stopping",
                  self.spec.name, last)
        raise _StreamStopped

    def _process_batch(self, source, transform, batch) -> str:
        from ..exceptions import SerializationError
        from ..observability.metrics import global_metrics
        try:
            FI.fire("stream.transform")
            actions = transform(batch)
        except Exception as e:
            self.last_error = f"transform failed: {e}"
            log.exception("stream %s transform failed", self.spec.name)
            return self._handle_failure(source, batch,
                                        BatchOutcome.TRANSFORM_ERROR)
        # conflict-retried transaction (reference: retry interval
        # config, memgraph.cpp:652)
        failure = BatchOutcome.SERIALIZATION_EXHAUSTED
        for attempt in range(10):
            try:
                self._commit_batch(source, actions)
                self._ack(source)
                self._batch_failures = 0
                self._failed_batch_key = None
                self.last_error = None
                self.processed_batches += 1
                self.processed_messages += len(batch)
                global_metrics.increment("stream.batches_total")
                global_metrics.increment("stream.records_total",
                                         len(batch))
                return BatchOutcome.COMMITTED
            except SerializationError:
                self.last_error = "batch exhausted serialization retries"
                time.sleep(0.01 * (attempt + 1))
            except _StreamStopped:
                raise
            except Exception as e:
                self.last_error = str(e)
                log.exception("stream %s batch failed", self.spec.name)
                failure = BatchOutcome.TXN_ERROR
                break
        return self._handle_failure(source, batch, failure)

    def _commit_batch(self, source, actions) -> None:
        """One ingest transaction: BEGIN → actions → stage the source's
        pending position (WAL OP_STREAM_OFFSET in the SAME commit) →
        COMMIT. The offset is durable iff the data is."""
        from .interpreter import Interpreter
        interp = Interpreter(self.ictx, system=True)
        try:
            interp.execute("BEGIN")
            for action in actions:
                interp.execute(action["query"],
                               action.get("parameters"))
            position = self._pending_position(source)
            if position is not None:
                interp.stage_stream_offset(self.spec.name, position)
            interp.execute("COMMIT")
        except BaseException:
            interp.abort()
            raise

    def _ack(self, source) -> None:
        """Consumer-side ack AFTER the transactional commit: purely an
        optimization (saves redelivery-dedup work on restart) — failure
        here never loses or duplicates data."""
        from ..observability.metrics import global_metrics
        try:
            FI.fire("stream.commit")
            source.commit()
            self._persist_offset(source)
        except Exception as e:
            global_metrics.increment("stream.ack_failures_total")
            log.warning("stream %s: source ack failed after durable "
                        "commit (%s) — the WAL offset record makes "
                        "redelivery exactly-once", self.spec.name, e)

    def _handle_failure(self, source, batch, outcome: str) -> str:
        """Bounded retries, then quarantine: the poison batch goes to
        the dead-letter buffer and its offset advances transactionally
        (an offset-only commit) so the stream never wedges."""
        from ..observability.metrics import global_metrics
        key = (batch[0].topic, batch[0].offset, len(batch))
        if key != self._failed_batch_key:
            self._failed_batch_key = key
            self._batch_failures = 0
        self._batch_failures += 1
        if self._batch_failures <= self.spec.max_batch_retries:
            source.rollback()
            global_metrics.increment("stream.redeliveries_total")
            log.warning("stream %s: batch at %s failed (%s, attempt "
                        "%d/%d) — rolled back for redelivery",
                        self.spec.name, key[:2], outcome,
                        self._batch_failures, self.spec.max_batch_retries)
            self._stop.wait(0.05 * self._batch_failures)
            return BatchOutcome.REDELIVERED
        # quarantine: capture the batch's end position BEFORE any
        # rollback, commit it as an offset-only transaction, then ack
        position = self._pending_position(source)
        try:
            if position is not None:
                self._commit_batch(source, [])
            self._ack(source)
        except Exception as e:  # noqa: BLE001 — quarantine must not wedge
            log.exception("stream %s: dead-letter offset advance failed "
                          "(%s) — batch will redeliver", self.spec.name, e)
            source.rollback()
            return BatchOutcome.REDELIVERED
        self.dead_letter.append(
            (key[:2], [m.payload for m in batch], outcome))
        self._batch_failures = 0
        self._failed_batch_key = None
        global_metrics.increment("stream.dead_letter_total")
        log.error("stream %s: batch at %s exhausted %d retries (%s) — "
                  "QUARANTINED to the dead-letter buffer (%d entries); "
                  "offset advanced past it", self.spec.name, key[:2],
                  self.spec.max_batch_retries, outcome,
                  len(self.dead_letter))
        return BatchOutcome.DEAD_LETTERED

    # --- offsets ------------------------------------------------------------

    def _pending_position(self, source):
        fn = getattr(source, "pending_position", None)
        return fn() if fn is not None else None

    def _update_lag(self, source) -> None:
        from ..observability.metrics import global_metrics
        fn = getattr(source, "lag", None)
        if fn is not None:
            global_metrics.set_gauge(f"stream.lag.{self.spec.name}",
                                     float(fn()))

    def _persist_offset(self, source) -> None:
        committed = getattr(source, "committed_offset", None)
        kv = getattr(self.ictx, "kvstore", None)
        if committed is not None and kv is not None:
            kv.put(f"streams:offset:{self.spec.name}", str(committed))

    def _recovered_position(self):
        """The WAL/snapshot-recovered durable position for this stream
        (None when the storage has none — e.g. a fresh database)."""
        storage = getattr(self.ictx, "storage", None)
        offsets = getattr(storage, "stream_offsets", None)
        if offsets is None:
            return None
        return offsets.get(self.spec.name)

    def _restore_offset(self) -> int:
        """FILE streams: resume from the newest durable byte offset —
        the WAL-recovered position (authoritative) vs the kvstore copy
        (a lagging optimization that may miss the final pre-crash
        batches), whichever is further."""
        kv = getattr(self.ictx, "kvstore", None)
        raw = kv.get_str(f"streams:offset:{self.spec.name}") \
            if kv is not None else None
        kv_offset = int(raw) if raw else 0
        recovered = self._recovered_position()
        if isinstance(recovered, int):
            return max(kv_offset, recovered)
        return kv_offset


class Streams:
    """Registry of streams (reference: query/stream/streams.hpp Streams)."""

    def __init__(self, interpreter_context):
        self.ictx = interpreter_context
        self._lock = threading.Lock()
        self._streams: dict[str, Stream] = {}
        self._kv = getattr(interpreter_context, "kvstore", None)
        if self._kv is not None:
            self._restore()

    def _restore(self) -> None:
        """Reload persisted stream definitions (reference: RestoreStreams,
        memgraph.cpp:929). Streams come back in the stopped state."""
        import dataclasses
        for key, raw in self._kv.items_with_prefix("stream:"):
            data = json.loads(raw.decode("utf-8"))
            spec = StreamSpec(**data)
            self._streams[spec.name] = Stream(spec, self.ictx)

    def _persist(self, spec: StreamSpec) -> None:
        if self._kv is not None:
            import dataclasses
            self._kv.put(f"stream:{spec.name}",
                         json.dumps(dataclasses.asdict(spec)))

    def create(self, spec: StreamSpec) -> None:
        with self._lock:
            if spec.name in self._streams:
                raise QueryException(
                    f"stream {spec.name!r} already exists")
            self._streams[spec.name] = Stream(spec, self.ictx)
            self._persist(spec)

    def drop(self, name: str) -> None:
        with self._lock:
            stream = self._streams.pop(name, None)
            if stream is not None and self._kv is not None:
                self._kv.delete(f"stream:{name}")
                # a recreated stream of the same name must NOT resume at
                # the dropped stream's byte offset
                self._kv.delete(f"streams:offset:{name}")
        if stream is None:
            raise QueryException(f"stream {name!r} does not exist")
        if stream.running:
            stream.stop()

    def start(self, name: str) -> None:
        self._get(name).start()

    def stop(self, name: str) -> None:
        self._get(name).stop()

    def start_all(self) -> None:
        with self._lock:
            streams = list(self._streams.values())
        for s in streams:
            if not s.running:
                s.start()

    def stop_all(self) -> None:
        with self._lock:
            streams = list(self._streams.values())
        for s in streams:
            if s.running:
                s.stop()

    def _get(self, name: str) -> Stream:
        with self._lock:
            stream = self._streams.get(name)
        if stream is None:
            raise QueryException(f"stream {name!r} does not exist")
        return stream

    def show(self) -> list[list]:
        with self._lock:
            streams = list(self._streams.values())
        return [[s.spec.name, s.spec.kind, "|".join(s.spec.topics),
                 s.spec.transform, s.spec.batch_size,
                 "running" if s.running else "stopped",
                 s.processed_messages, s.last_error]  # mglint: disable=MG006 — s is a Stream, not Telemetry: field-name collision on last_error (unique-owner resolution)
                for s in sorted(streams, key=lambda s: s.spec.name)]


import weakref

_REGISTRY: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_REGISTRY_LOCK = threading.Lock()


def streams_of(interpreter_context) -> Streams:
    with _REGISTRY_LOCK:
        s = _REGISTRY.get(interpreter_context)
        if s is None:
            s = Streams(interpreter_context)
            _REGISTRY[interpreter_context] = s
        return s


# --- builtin transformations -------------------------------------------------

def _cypher_jsonl_transform(messages):
    """Each message: {"query": "...", "parameters": {...}} JSON."""
    actions = []
    for m in messages:
        obj = json.loads(m.payload_str())
        actions.append({"query": obj["query"],
                        "parameters": obj.get("parameters")})
    return actions


def _node_jsonl_transform(messages):
    """Each message: {"labels": [...], "properties": {...}} → CREATE."""
    actions = []
    for m in messages:
        obj = json.loads(m.payload_str())
        labels = "".join(f":{l}" for l in obj.get("labels", []))
        actions.append({
            "query": f"CREATE (n{labels} $props)",
            "parameters": {"props": obj.get("properties", {})}})
    return actions


register_transformation("transform.cypher", _cypher_jsonl_transform)
register_transformation("transform.nodes", _node_jsonl_transform)
