"""DUMP DATABASE — stream the graph as cypherl statements.

Counterpart of /root/reference/src/query/dump.cpp: emits index/constraint
DDL, CREATE statements for vertices (keyed by an internal id property) and
edges, then drops the helper index.
"""

from __future__ import annotations

from ..storage.common import View
from ..utils.point import Point
from ..utils.temporal import (Date, Duration, LocalDateTime, LocalTime,
                              ZonedDateTime)

INTERNAL_ID = "__mg_id__"


def _escape_string(s: str) -> str:
    return '"' + s.replace("\\", "\\\\").replace('"', '\\"') + '"'


def _escape_name(name: str) -> str:
    if name.isidentifier():
        return name
    return "`" + name.replace("`", "``") + "`"


def value_to_cypher(v) -> str:
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, int):
        return str(v)
    if isinstance(v, float):
        return repr(v)
    if isinstance(v, str):
        return _escape_string(v)
    if isinstance(v, (list, tuple)):
        return "[" + ", ".join(value_to_cypher(x) for x in v) + "]"
    if isinstance(v, dict):
        return "{" + ", ".join(f"{_escape_name(k)}: {value_to_cypher(x)}"
                               for k, x in v.items()) + "}"
    if isinstance(v, Date):
        return f'DATE("{v}")'
    if isinstance(v, LocalTime):
        return f'LOCALTIME("{v}")'
    if isinstance(v, LocalDateTime):
        return f'LOCALDATETIME("{v}")'
    if isinstance(v, ZonedDateTime):
        return f'DATETIME("{v}")'
    if isinstance(v, Duration):
        return f'DURATION("{v}")'
    if isinstance(v, Point):
        inner = ", ".join(f"{k}: {value_to_cypher(val)}"
                          for k, val in v.to_map().items())
        return f"POINT({{{inner}}})"
    raise TypeError(f"cannot dump value of type {type(v)!r}")


def dump_database(accessor):
    """Yield cypherl lines reproducing the accessor's visible graph."""
    storage = accessor.storage
    lm = storage.label_mapper
    pm = storage.property_mapper
    tm = storage.edge_type_mapper

    # DDL first
    for lid in storage.indices.label.labels():
        yield f"CREATE INDEX ON :{_escape_name(lm.id_to_name(lid))};"
    for (lid, pids) in storage.indices.label_property.keys():
        props = ", ".join(_escape_name(pm.id_to_name(p)) for p in pids)
        yield (f"CREATE INDEX ON :{_escape_name(lm.id_to_name(lid))}"
               f"({props});")
    for (lid, pid) in storage.constraints.existence.all():
        yield (f"CREATE CONSTRAINT ON (u:{_escape_name(lm.id_to_name(lid))}) "
               f"ASSERT EXISTS (u.{_escape_name(pm.id_to_name(pid))});")
    for (lid, pids) in storage.constraints.unique.all():
        props = ", ".join(f"u.{_escape_name(pm.id_to_name(p))}" for p in pids)
        yield (f"CREATE CONSTRAINT ON (u:{_escape_name(lm.id_to_name(lid))}) "
               f"ASSERT {props} IS UNIQUE;")

    yield f"CREATE INDEX ON :__mg_vertex__({INTERNAL_ID});"

    for va in accessor.vertices(View.OLD):
        labels = "".join(f":{_escape_name(lm.id_to_name(l))}"
                         for l in va.labels(View.OLD))
        props = va.properties(View.OLD)
        items = [f"{INTERNAL_ID}: {va.gid}"]
        items += [f"{_escape_name(pm.id_to_name(k))}: {value_to_cypher(v)}"
                  for k, v in sorted(props.items())]
        yield (f"CREATE (:__mg_vertex__{labels} "
               f"{{{', '.join(items)}}});")

    for ea in accessor.edges(View.OLD):
        props = ea.properties(View.OLD)
        prop_str = ""
        if props:
            items = [f"{_escape_name(pm.id_to_name(k))}: {value_to_cypher(v)}"
                     for k, v in sorted(props.items())]
            prop_str = " {" + ", ".join(items) + "}"
        yield (f"MATCH (u:__mg_vertex__), (v:__mg_vertex__) "
               f"WHERE u.{INTERNAL_ID} = {ea.from_vertex().gid} AND "
               f"v.{INTERNAL_ID} = {ea.to_vertex().gid} "
               f"CREATE (u)-[:{_escape_name(tm.id_to_name(ea.edge_type))}"
               f"{prop_str}]->(v);")

    yield f"DROP INDEX ON :__mg_vertex__({INTERNAL_ID});"
    yield f"MATCH (u) REMOVE u:__mg_vertex__, u.{INTERNAL_ID};"
