"""Builtin scalar/list/string/temporal/spatial function library.

Counterpart of the reference's ~190 builtins
(/root/reference/src/query/interpret/awesome_memgraph_functions.cpp).
Each function takes (evaluator, args) and follows openCypher null
propagation unless noted. Aggregates live in the executor, not here.
"""

from __future__ import annotations

import math
import random as _random
import re
import uuid as _uuid

from ..exceptions import TypeException
from ..storage.common import View
from ..storage.storage import EdgeAccessor, VertexAccessor
from ..utils.point import Point
from ..utils.temporal import (Date, Duration, LocalDateTime, LocalTime,
                              ZonedDateTime)
from . import values as V
from .values import Path

FUNCTIONS: dict = {}


def register(name, min_args=None, max_args=None, propagate_null=True):
    def deco(fn):
        def wrapper(ev, args):
            if min_args is not None and len(args) < min_args:
                raise TypeException(f"{name}() requires at least {min_args} argument(s)")
            if max_args is not None and len(args) > max_args:
                raise TypeException(f"{name}() takes at most {max_args} argument(s)")
            if propagate_null and any(a is None for a in args):
                return None
            return fn(ev, args)
        FUNCTIONS[name] = wrapper
        return fn
    return deco


def _num(name, v):
    if not V.is_numeric(v):
        raise TypeException(f"{name}() requires a number, got {V.type_name(v)}")
    return v


def _str(name, v):
    if not isinstance(v, str):
        raise TypeException(f"{name}() requires a string, got {V.type_name(v)}")
    return v


def _list(name, v):
    if not isinstance(v, (list, tuple)):
        raise TypeException(f"{name}() requires a list, got {V.type_name(v)}")
    return v


# --- scalar ------------------------------------------------------------------

@register("coalesce", 1, propagate_null=False)
def fn_coalesce(ev, args):
    for a in args:
        if a is not None:
            return a
    return None


@register("id", 1, 1)
def fn_id(ev, args):
    v = args[0]
    if isinstance(v, (VertexAccessor, EdgeAccessor)):
        return v.gid
    raise TypeException("id() requires a node or relationship")


@register("type", 1, 1)
def fn_type(ev, args):
    v = args[0]
    if isinstance(v, EdgeAccessor):
        return ev.ctx.storage.edge_type_mapper.id_to_name(v.edge_type)
    raise TypeException("type() requires a relationship")


@register("labels", 1, 1)
def fn_labels(ev, args):
    v = args[0]
    if not isinstance(v, VertexAccessor):
        raise TypeException("labels() requires a node")
    st = ev.checked_state(v)
    mapper = ev.ctx.storage.label_mapper
    return [mapper.id_to_name(l) for l in sorted(st.labels)]


@register("properties", 1, 1)
def fn_properties(ev, args):
    v = args[0]
    if isinstance(v, dict):
        return dict(v)
    if isinstance(v, (VertexAccessor, EdgeAccessor)):
        st = ev.checked_state(v)
        mapper = ev.ctx.storage.property_mapper
        return {mapper.id_to_name(k): val
                for k, val in st.properties.items()}
    raise TypeException("properties() requires a node, relationship or map")


@register("keys", 1, 1)
def fn_keys(ev, args):
    v = args[0]
    if isinstance(v, dict):
        return list(v.keys())
    if isinstance(v, (VertexAccessor, EdgeAccessor)):
        st = ev.checked_state(v)
        mapper = ev.ctx.storage.property_mapper
        return [mapper.id_to_name(k) for k in st.properties]
    raise TypeException("keys() requires a node, relationship or map")


@register("startnode", 1, 1)
def fn_startnode(ev, args):
    if not isinstance(args[0], EdgeAccessor):
        raise TypeException("startNode() requires a relationship")
    return args[0].from_vertex()


@register("endnode", 1, 1)
def fn_endnode(ev, args):
    if not isinstance(args[0], EdgeAccessor):
        raise TypeException("endNode() requires a relationship")
    return args[0].to_vertex()


@register("degree", 1, 1)
def fn_degree(ev, args):
    v = args[0]
    if not isinstance(v, VertexAccessor):
        raise TypeException("degree() requires a node")
    return v.in_degree(ev.ctx.view) + v.out_degree(ev.ctx.view)


@register("indegree", 1, 1)
def fn_indegree(ev, args):
    if not isinstance(args[0], VertexAccessor):
        raise TypeException("inDegree() requires a node")
    return args[0].in_degree(ev.ctx.view)


@register("outdegree", 1, 1)
def fn_outdegree(ev, args):
    if not isinstance(args[0], VertexAccessor):
        raise TypeException("outDegree() requires a node")
    return args[0].out_degree(ev.ctx.view)


@register("timestamp", 0, 0, propagate_null=False)
def fn_timestamp(ev, args):
    import time
    return int(time.time() * 1_000_000)


@register("valuetype", 1, 1, propagate_null=False)
def fn_valuetype(ev, args):
    return V.type_name(args[0])


@register("tointeger", 1, 1)
def fn_tointeger(ev, args):
    v = args[0]
    if isinstance(v, bool):
        # InvalidArgumentValue per TCK TypeConversionFunctions (the
        # bool-accepting variant is toIntegerOrNull/toBooleanList)
        raise TypeException("toInteger() can't convert Boolean")
    if isinstance(v, int):
        return v
    if isinstance(v, float):
        return int(v)
    if isinstance(v, str):
        try:
            return int(float(v)) if ("." in v or "e" in v.lower()) else int(v, 0)
        except ValueError:
            return None
    raise TypeException(f"toInteger() can't convert {V.type_name(v)}")


@register("tofloat", 1, 1)
def fn_tofloat(ev, args):
    v = args[0]
    if V.is_numeric(v):
        return float(v)
    if isinstance(v, str):
        try:
            return float(v)
        except ValueError:
            return None
    raise TypeException(f"toFloat() can't convert {V.type_name(v)}")


@register("toboolean", 1, 1)
def fn_toboolean(ev, args):
    v = args[0]
    if isinstance(v, bool):
        return v
    if isinstance(v, int):
        # InvalidArgumentValue per TCK TypeConversionFunctions
        raise TypeException("toBoolean() can't convert Integer")
    if isinstance(v, str):
        low = v.strip().lower()
        if low == "true":
            return True
        if low == "false":
            return False
        return None
    raise TypeException(f"toBoolean() can't convert {V.type_name(v)}")


@register("tostring", 1, 1)
def fn_tostring(ev, args):
    v = args[0]
    if isinstance(v, str):
        return v
    if isinstance(v, bool):
        return "true" if v else "false"
    if V.is_numeric(v):
        if isinstance(v, float) and v.is_integer():
            return f"{v:.1f}"
        return str(v)
    if isinstance(v, (Date, Duration, LocalDateTime, LocalTime,
                      ZonedDateTime, Point)):
        return str(v)
    # lists/maps/graph entities are invalid (TCK TypeConversionFunctions
    # InvalidArgumentValue; reference: awesome_memgraph_functions ToString)
    raise TypeException(f"toString() can't convert {V.type_name(v)}")


# --- math --------------------------------------------------------------------

def _math1(name, fn):
    @register(name, 1, 1)
    def f(ev, args, _fn=fn, _name=name):
        return _fn(_num(_name, args[0]))
    return f


_math1("abs", abs)
_math1("ceil", lambda v: float(math.ceil(v)))
_math1("floor", lambda v: float(math.floor(v)))
_math1("sqrt", lambda v: math.sqrt(v) if v >= 0 else math.nan)
_math1("exp", math.exp)
_math1("log", lambda v: math.log(v) if v > 0 else math.nan)
_math1("log10", lambda v: math.log10(v) if v > 0 else math.nan)
_math1("log2", lambda v: math.log2(v) if v > 0 else math.nan)
_math1("sin", math.sin)
_math1("cos", math.cos)
_math1("tan", math.tan)
_math1("cot", lambda v: 1.0 / math.tan(v) if math.tan(v) != 0 else math.inf)
_math1("asin", lambda v: math.asin(v) if -1 <= v <= 1 else math.nan)
_math1("acos", lambda v: math.acos(v) if -1 <= v <= 1 else math.nan)
_math1("atan", math.atan)
_math1("sign", lambda v: (v > 0) - (v < 0))
_math1("degrees", math.degrees)
_math1("radians", math.radians)


@register("round", 1, 2)
def fn_round(ev, args):
    v = _num("round", args[0])
    digits = 0
    if len(args) == 2:
        digits = int(_num("round", args[1]))
    # half away from zero (Cypher), not banker's rounding
    scale = 10 ** digits
    return float(math.floor(abs(v) * scale + 0.5) / scale * ((v > 0) - (v < 0))
                 if v != 0 else 0.0)


@register("atan2", 2, 2)
def fn_atan2(ev, args):
    return math.atan2(_num("atan2", args[0]), _num("atan2", args[1]))


@register("pi", 0, 0, propagate_null=False)
def fn_pi(ev, args):
    return math.pi


@register("e", 0, 0, propagate_null=False)
def fn_e(ev, args):
    return math.e


@register("rand", 0, 0, propagate_null=False)
def fn_rand(ev, args):
    return _random.random()


@register("random", 0, 0, propagate_null=False)
def fn_random(ev, args):
    return _random.random()


# --- strings -----------------------------------------------------------------

@register("tolower", 1, 1)
@register("lower", 1, 1)      # openCypher M09 pre-rename alias
def fn_tolower(ev, args):
    return _str("toLower", args[0]).lower()


@register("toupper", 1, 1)
@register("upper", 1, 1)
def fn_toupper(ev, args):
    return _str("toUpper", args[0]).upper()


@register("trim", 1, 1)
def fn_trim(ev, args):
    return _str("trim", args[0]).strip()


@register("ltrim", 1, 1)
def fn_ltrim(ev, args):
    return _str("lTrim", args[0]).lstrip()


@register("rtrim", 1, 1)
def fn_rtrim(ev, args):
    return _str("rTrim", args[0]).rstrip()


@register("reverse", 1, 1)
def fn_reverse(ev, args):
    v = args[0]
    if isinstance(v, str):
        return v[::-1]
    if isinstance(v, (list, tuple)):
        return list(reversed(v))
    raise TypeException("reverse() requires a string or list")


@register("left", 2, 2)
def fn_left(ev, args):
    s = _str("left", args[0])
    n = int(_num("left", args[1]))
    if n < 0:
        raise TypeException("left() requires a non-negative length")
    return s[:n]


@register("right", 2, 2)
def fn_right(ev, args):
    s = _str("right", args[0])
    n = int(_num("right", args[1]))
    if n < 0:
        raise TypeException("right() requires a non-negative length")
    return s[len(s) - min(n, len(s)):]


@register("substring", 2, 3)
def fn_substring(ev, args):
    s = _str("substring", args[0])
    start = int(_num("substring", args[1]))
    if len(args) == 3:
        length = int(_num("substring", args[2]))
        return s[start:start + length]
    return s[start:]


@register("split", 2, 2)
def fn_split(ev, args):
    return _str("split", args[0]).split(_str("split", args[1]))


@register("replace", 3, 3)
def fn_replace(ev, args):
    return _str("replace", args[0]).replace(_str("replace", args[1]),
                                            _str("replace", args[2]))


@register("size", 1, 1)
def fn_size(ev, args):
    v = args[0]
    if isinstance(v, str) or isinstance(v, (list, tuple)):
        return len(v)
    if isinstance(v, dict):
        return len(v)
    if isinstance(v, Path):
        return len(v)
    raise TypeException(f"size() not supported for {V.type_name(v)}")


@register("length", 1, 1)
def fn_length(ev, args):
    v = args[0]
    if isinstance(v, Path):
        return len(v)
    if isinstance(v, (str, list, tuple)):
        return len(v)
    raise TypeException("length() requires a path, string or list")


@register("chartoascii", 1, 1)
def fn_chartoascii(ev, args):
    s = _str("charToAscii", args[0])
    if not s:
        raise TypeException("charToAscii() requires a non-empty string")
    return ord(s[0])


@register("asciitochar", 1, 1)
def fn_asciitochar(ev, args):
    return chr(int(_num("asciiToChar", args[0])))


# --- lists -------------------------------------------------------------------

@register("range", 2, 3)
def fn_range(ev, args):
    lo = int(_num("range", args[0]))
    hi = int(_num("range", args[1]))
    step = int(_num("range", args[2])) if len(args) == 3 else 1
    if step == 0:
        raise TypeException("range() step must not be zero")
    if step > 0:
        return list(range(lo, hi + 1, step))
    return list(range(lo, hi - 1, step))


@register("head", 1, 1)
def fn_head(ev, args):
    lst = _list("head", args[0])
    return lst[0] if lst else None


@register("last", 1, 1)
def fn_last(ev, args):
    lst = _list("last", args[0])
    return lst[-1] if lst else None


@register("tail", 1, 1)
def fn_tail(ev, args):
    return list(_list("tail", args[0])[1:])


@register("nodes", 1, 1)
def fn_nodes(ev, args):
    if not isinstance(args[0], Path):
        raise TypeException("nodes() requires a path")
    return args[0].vertices()


@register("relationships", 1, 1)
def fn_relationships(ev, args):
    if not isinstance(args[0], Path):
        raise TypeException("relationships() requires a path")
    return args[0].edges()


@register("uniformsample", 2, 2)
def fn_uniformsample(ev, args):
    lst = _list("uniformSample", args[0])
    n = int(_num("uniformSample", args[1]))
    if not lst or n <= 0:
        return []
    return [_random.choice(lst) for _ in range(n)]


# --- temporal ----------------------------------------------------------------

@register("date", 0, 1, propagate_null=False)
def fn_date(ev, args):
    if not args or args[0] is None:
        return Date.today()
    v = args[0]
    if isinstance(v, str):
        return Date.parse(v)
    if isinstance(v, dict):
        return Date.from_parts(int(v.get("year", 1970)),
                               int(v.get("month", 1)), int(v.get("day", 1)))
    if isinstance(v, Date):
        return v
    if isinstance(v, LocalDateTime):
        return v.date()
    raise TypeException("date() argument must be a string or map")


@register("localtime", 0, 1, propagate_null=False)
def fn_localtime(ev, args):
    if not args or args[0] is None:
        import datetime
        return LocalTime(datetime.datetime.now().time())
    v = args[0]
    if isinstance(v, str):
        return LocalTime.parse(v)
    if isinstance(v, dict):
        return LocalTime.from_parts(
            int(v.get("hour", 0)), int(v.get("minute", 0)),
            int(v.get("second", 0)), int(v.get("millisecond", 0)),
            int(v.get("microsecond", 0)))
    if isinstance(v, LocalTime):
        return v
    if isinstance(v, LocalDateTime):
        return v.local_time()
    raise TypeException("localTime() argument must be a string or map")


@register("localdatetime", 0, 1, propagate_null=False)
def fn_localdatetime(ev, args):
    if not args or args[0] is None:
        return LocalDateTime.now()
    v = args[0]
    if isinstance(v, str):
        return LocalDateTime.parse(v)
    if isinstance(v, dict):
        return LocalDateTime.from_parts(
            int(v.get("year", 1970)), int(v.get("month", 1)),
            int(v.get("day", 1)), int(v.get("hour", 0)),
            int(v.get("minute", 0)), int(v.get("second", 0)),
            int(v.get("millisecond", 0)), int(v.get("microsecond", 0)))
    if isinstance(v, LocalDateTime):
        return v
    raise TypeException("localDateTime() argument must be a string or map")


@register("datetime", 0, 1, propagate_null=False)
def fn_datetime(ev, args):
    if not args or args[0] is None:
        return ZonedDateTime.now()
    v = args[0]
    if isinstance(v, str):
        return ZonedDateTime.parse(v)
    if isinstance(v, ZonedDateTime):
        return v
    raise TypeException("datetime() argument must be a string")


@register("duration", 1, 1)
def fn_duration(ev, args):
    v = args[0]
    if isinstance(v, str):
        return Duration.parse(v)
    if isinstance(v, dict):
        return Duration.from_parts(
            days=v.get("day", v.get("days", 0)),
            hours=v.get("hour", v.get("hours", 0)),
            minutes=v.get("minute", v.get("minutes", 0)),
            seconds=v.get("second", v.get("seconds", 0)),
            milliseconds=v.get("millisecond", v.get("milliseconds", 0)),
            microseconds=v.get("microsecond", v.get("microseconds", 0)))
    if isinstance(v, Duration):
        return v
    raise TypeException("duration() argument must be a string or map")


# --- spatial -----------------------------------------------------------------

@register("point", 1, 1)
def fn_point(ev, args):
    if not isinstance(args[0], dict):
        raise TypeException("point() requires a map")
    return Point.from_map(args[0])


@register("point.distance", 2, 2)
def fn_point_distance(ev, args):
    a, b = args
    if not isinstance(a, Point) or not isinstance(b, Point):
        raise TypeException("point.distance() requires two points")
    return a.distance(b)


@register("distance", 2, 2)
def fn_distance(ev, args):
    return fn_point_distance(ev, args)


@register("point.withinbbox", 3, 3)
def fn_point_withinbbox(ev, args):
    p, lo, hi = args
    if not all(isinstance(x, Point) for x in (p, lo, hi)):
        raise TypeException("point.withinbbox() requires three points")
    ok = lo.x <= p.x <= hi.x and lo.y <= p.y <= hi.y
    if p.crs.dims == 3 and lo.z is not None and hi.z is not None:
        ok = ok and lo.z <= p.z <= hi.z
    return ok


# --- assertion / counters (reference: awesome_memgraph_functions) ------------

@register("assert", 1, 2, propagate_null=False)
def fn_assert(ev, args):
    ok = args[0]
    message = args[1] if len(args) > 1 else "Assertion failed"
    if ok is not True:
        raise TypeException(str(message))
    return True


@register("counter", 2, 3)
def fn_counter(ev, args):
    """counter(name, initial, step=1): named counter scoped to the query
    execution (reference: per-EvaluationContext counters, context.hpp),
    returns the current value then advances."""
    name = _str("counter", args[0])
    initial = int(_num("counter", args[1]))
    step = int(_num("counter", args[2])) if len(args) == 3 else 1
    counters = getattr(ev.ctx, "_query_counters", None)
    if counters is None:
        counters = ev.ctx._query_counters = {}
    current = counters.get(name, initial)
    counters[name] = current + step
    return current


@register("propertysize", 2, 2)
def fn_propertysize(ev, args):
    """Approximate encoded byte size of a stored property."""
    from ..storage.property_store import value_key
    obj, prop = args
    if not isinstance(obj, (VertexAccessor, EdgeAccessor)):
        raise TypeException("propertySize() requires a node or relationship")
    value = ev.get_property(obj, _str("propertySize", prop))
    if value is None:
        return 0
    return len(value_key(value))


@register("tocharlist", 1, 1)
def fn_tocharlist(ev, args):
    return list(_str("toCharList", args[0]))


# --- conversions: *OrNull / *List / container helpers ------------------------

@register("isempty", 1, 1)
def fn_isempty(ev, args):
    v = args[0]
    if isinstance(v, (str, list, tuple, dict)):
        return len(v) == 0
    raise TypeException("isEmpty() requires a string, list or map")


def _toboolean_lenient(ev, args):
    """List/OrNull-variant semantics: integers coerce (nonzero -> true),
    unlike the scalar toBoolean() which raises per the TCK."""
    v = args[0]
    if isinstance(v, int) and not isinstance(v, bool):
        return v != 0
    return fn_toboolean(ev, args)


def _tointeger_lenient(ev, args):
    v = args[0]
    if isinstance(v, bool):
        return 1 if v else 0
    return fn_tointeger(ev, args)


def _or_null(conv):
    def inner(ev, args):
        try:
            return conv(ev, args)
        # mglint: disable=MG003 — Cypher toXOrNull() contract: any
        # conversion failure IS the null result, not an error
        except Exception:
            return None
    return inner


register("tointegerornull", 1, 1)(_or_null(_tointeger_lenient))
register("tofloatornull", 1, 1)(_or_null(fn_tofloat))
register("tobooleanornull", 1, 1)(_or_null(_toboolean_lenient))
register("tostringornull", 1, 1)(_or_null(fn_tostring))


def _list_conv(name, elem_fn):
    @register(name, 1, 1)
    def inner(ev, args, _fn=elem_fn):
        lst = _list(name, args[0])
        out = []
        for item in lst:
            if item is None:
                out.append(None)
                continue
            try:
                out.append(_fn(ev, [item]))
            # mglint: disable=MG003 — per-element toX() null-on-failure
            # is the Cypher list-conversion contract
            except Exception:
                out.append(None)
        return out
    return inner


_list_conv("tointegerlist", _tointeger_lenient)
_list_conv("tofloatlist", fn_tofloat)
_list_conv("tobooleanlist", _toboolean_lenient)
_list_conv("tostringlist", fn_tostring)


@register("toset", 1, 1)
def fn_toset(ev, args):
    lst = _list("toSet", args[0])
    seen = set()
    out = []
    for item in lst:
        key = V.hashable_key(item)
        if key not in seen:
            seen.add(key)
            out.append(item)
    return out


@register("values", 1, 1)
def fn_values(ev, args):
    v = args[0]
    if isinstance(v, dict):
        return list(v.values())
    if isinstance(v, (VertexAccessor, EdgeAccessor)):
        return list(v.properties(ev.ctx.view).values())
    raise TypeException("values() requires a map, node or relationship")


@register("username", 0, 0, propagate_null=False)
def fn_username(ev, args):
    # bound by the session; null on embedded/anonymous use
    return getattr(ev.ctx, "username", None) or None


@register("roles", 0, 1, propagate_null=False)
def fn_roles(ev, args):
    """Role names of the session user (reference:
    awesome_memgraph_functions.cpp Roles); [] when anonymous. The optional
    db_name argument is accepted for parity (roles are global here)."""
    if args and args[0] is not None and not isinstance(args[0], str):
        raise TypeException("roles() db_name must be a string")
    username = getattr(ev.ctx, "username", None)
    if not username:
        return []
    from ..auth.auth import resolve_auth
    exec_ctx = getattr(ev.ctx, "exec_ctx", None)
    auth = resolve_auth(getattr(exec_ctx, "interpreter_context", None))
    return auth.user_roles(username)


@register("elementid", 1, 1)
def fn_elementid(ev, args):
    """id() as a string, for external-integration compatibility (reference:
    awesome_memgraph_functions.cpp ElementId)."""
    v = args[0]
    if isinstance(v, (VertexAccessor, EdgeAccessor)):
        return str(v.gid)
    raise TypeException("elementId() requires a node or relationship")


@register("toenum", 1, 2)
def fn_toenum(ev, args):
    """toEnum("Name::Value") or toEnum("Name", "Value") -> enum value
    (reference: awesome_memgraph_functions.cpp ToEnum)."""
    from ..storage.enums import enum_registry
    if not all(isinstance(a, str) for a in args):
        raise TypeException("toEnum() requires string arguments")
    if len(args) == 1:
        name, sep, value = args[0].partition("::")
        if not sep:
            raise TypeException(
                f"invalid enum literal {args[0]!r} (expected 'Name::Value')")
    else:
        name, value = args
    return enum_registry(ev.ctx.storage).value(name, value)


@register("gethopscounter", 0, 0, propagate_null=False)
def fn_gethopscounter(ev, args):
    """Edge visits consumed so far under USING HOPS LIMIT (reference:
    query/hops_limit.hpp counter surface)."""
    exec_ctx = getattr(ev.ctx, "exec_ctx", None)
    if exec_ctx is not None and exec_ctx.hops_budget is not None:
        return getattr(exec_ctx, "hops_initial", 0) - exec_ctx.hops_budget
    return 0


# --- ids / misc --------------------------------------------------------------

@register("randomuuid", 0, 0, propagate_null=False)
def fn_randomuuid(ev, args):
    return str(_uuid.uuid4())


@register("uuid", 0, 0, propagate_null=False)
def fn_uuid(ev, args):
    return str(_uuid.uuid4())


@register("tobytestring", 1, 1)
def fn_tobytestring(ev, args):
    s = _str("toByteString", args[0])
    if s.startswith("0x") or s.startswith("0X"):
        return bytes.fromhex(s[2:])
    return s.encode("utf-8")


@register("frombytestring", 1, 1)
def fn_frombytestring(ev, args):
    v = args[0]
    if not isinstance(v, bytes):
        raise TypeException("fromByteString() requires bytes")
    return v.decode("utf-8", errors="replace")

# --- convert.* / mgps.* module functions -------------------------------------
# (reference: query_modules/convert.cpp registers these as magic functions;
#  query_modules/mgps.py registers version/validate_predicate)


def _json_path_select(text, path):
    """Parse JSON and walk an optional '$.a.b[0]' path. Returns the selected
    subtree, or None for an unresolved path or a JSON null leaf (reference
    convert.cpp ResolveJsonPath/JsonPathToPointer)."""
    import json
    import re as _re
    try:
        root = json.loads(text)
    except ValueError as exc:
        raise TypeException(f"invalid JSON: {exc}") from None
    if not path:
        return root
    cur = root
    spec = path[1:] if path.startswith("$") else path
    for step in _re.findall(r"\.([^.\[]+)|\[(\d+)\]", spec):
        key, idx = step
        if key:
            if not isinstance(cur, dict) or key not in cur:
                return None
            cur = cur[key]
        else:
            i = int(idx)
            if not isinstance(cur, list) or i >= len(cur):
                return None
            cur = cur[i]
    return cur


def _from_json(args, expected_type, what):
    if not isinstance(args[0], str):
        raise TypeException(f"convert.from_json_{what} expects a JSON "
                            f"string")
    path = args[1] if len(args) > 1 else None
    if path is not None and not isinstance(path, str):
        raise TypeException("the path argument must be a string")
    out = _json_path_select(args[0], path)
    if out is None:
        return None  # unresolved path / JSON null leaf -> null
    if not isinstance(out, expected_type):
        raise TypeException(
            f"convert.from_json_{what} expects a JSON "
            f"{'object' if expected_type is dict else 'array'}")
    return out


@register("convert.from_json_map", 1, 2)
def fn_convert_from_json_map(ev, args):
    return _from_json(args, dict, "map")


@register("convert.from_json_list", 1, 2)
def fn_convert_from_json_list(ev, args):
    return _from_json(args, list, "list")


def _node_json(ev, v):
    mapper = ev.ctx.storage.property_mapper
    obj = {"id": str(v.gid), "type": "node"}
    labels = [ev.ctx.storage.label_mapper.id_to_name(l)
              for l in v.labels(ev.ctx.view)]
    if labels:
        obj["labels"] = labels
    props = {mapper.id_to_name(pid): _jsonable(ev, val)
             for pid, val in v.properties(ev.ctx.view).items()}
    if props:
        obj["properties"] = props
    return obj


def _edge_json(ev, e):
    mapper = ev.ctx.storage.property_mapper
    obj = {"id": str(e.gid), "type": "relationship",
           "label": ev.ctx.storage.edge_type_mapper.id_to_name(e.edge_type),
           "start": _node_json(ev, e.from_vertex()),
           "end": _node_json(ev, e.to_vertex())}
    props = {mapper.id_to_name(pid): _jsonable(ev, val)
             for pid, val in e.properties(ev.ctx.view).items()}
    if props:
        obj["properties"] = props
    return obj


def _jsonable(ev, v):
    """Reference convert.cpp JSON shapes: nodes {id,type,labels,properties},
    relationships with full start/end node objects, paths as interleaved
    arrays; temporal/point/enum values serialize via their string form."""
    from .values import Path as _QPath
    if isinstance(v, VertexAccessor):
        return _node_json(ev, v)
    if isinstance(v, EdgeAccessor):
        return _edge_json(ev, v)
    if isinstance(v, _QPath):
        out = []
        for k, item in enumerate(v.items):
            out.append(_node_json(ev, item) if k % 2 == 0
                       else _edge_json(ev, item))
        return out
    if isinstance(v, (list, tuple)):
        return [_jsonable(ev, x) for x in v]
    if isinstance(v, dict):
        return {k: _jsonable(ev, val) for k, val in v.items()}
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    return str(v)  # temporal/point/enum -> string form


@register("convert.to_json", 1, 1, propagate_null=False)
def fn_convert_to_json(ev, args):
    import json
    return json.dumps(_jsonable(ev, args[0]), separators=(",", ":"))


@register("convert.to_map", 1, 1)
def fn_convert_to_map(ev, args):
    # a map passes through; a node/relationship yields its properties;
    # anything else yields null (reference convert.cpp to_map)
    v = args[0]
    if isinstance(v, dict):
        return v
    if isinstance(v, (VertexAccessor, EdgeAccessor)):
        mapper = ev.ctx.storage.property_mapper
        return {mapper.id_to_name(pid): val
                for pid, val in v.properties(ev.ctx.view).items()}
    return None


@register("mgps.version", 0, 0, propagate_null=False)
def fn_mgps_version(ev, args):
    return "5.9.0"


@register("mgps.validate_predicate", 3, 3)
def fn_mgps_validate_predicate(ev, args):
    predicate, message, params = args
    if not isinstance(predicate, bool):
        raise TypeException(
            "mgps.validate_predicate expects a boolean predicate")
    if predicate:
        try:
            rendered = message % tuple(params or [])
        except (TypeError, ValueError) as exc:
            raise TypeException(
                f"invalid validation message format: {exc}") from None
        raise TypeException(rendered)
    return True
