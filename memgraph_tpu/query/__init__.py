"""openCypher query engine (host side).

Re-design of the reference's query layer (/root/reference/src/query/):
hand-written lexer + recursive-descent parser producing an AST (the
reference uses ANTLR — frontend/opencypher/grammar/), symbol analysis,
a rule-based planner with index rewrites (query/plan/), and a Volcano
pull-based executor (query/plan/operator.hpp) — with the analytics regime
delegated to the TPU ops layer through the procedure registry.
"""

from .interpreter import Interpreter, InterpreterContext

__all__ = ["Interpreter", "InterpreterContext"]
