"""Query interpreter: Prepare/Pull lifecycle over the storage engine.

Counterpart of the reference's Interpreter
(/root/reference/src/query/interpreter.cpp — Prepare at :9802, PullPlan
streaming at :3240): parses (with an AST/plan cache keyed by query text),
dispatches across query classes (Cypher, DDL, transactions, admin), plans,
and streams results batch-by-batch so Bolt's PULL n maps directly onto
`Interpreter.pull`.
"""

from __future__ import annotations

import sys
import threading
import time

# a query plan is a linked chain of operators (one per clause element) and
# execution is a chain of generators — both need Python stack depth
# proportional to query size. 1000-clause CREATE queries (TCK
# LargeCreateQuery) blow the 1000-frame default. Raised when an
# Interpreter is constructed (not at import: embedders using only the
# parser/client keep their own limit). Frames are heap-allocated on
# CPython 3.11+, so this does not risk native stack exhaustion.
_MIN_RECURSION_LIMIT = 20_000


def _ensure_recursion_limit() -> None:
    if sys.getrecursionlimit() < _MIN_RECURSION_LIMIT:
        sys.setrecursionlimit(_MIN_RECURSION_LIMIT)
from dataclasses import dataclass, field
from typing import Iterator, Optional

from ..exceptions import (HintedAbortError, QueryException, SemanticException,
                          TransactionException)
from ..observability import trace as mgtrace
from ..storage.common import IsolationLevel, StorageMode, View
from ..storage.ordering import order_key
from ..storage.storage import InMemoryStorage
from .frontend import ast as A
from .frontend.parser import parse_with_source
from .plan.operators import ExecutionContext, LogicalOperator, Produce
from .plan.planner import Planner
from .plan.profile import attach_profiling, profile_rows
from .plan.pretty_print import plan_to_rows


class InterpreterContext:
    """Shared, process-wide interpreter state (reference:
    InterpreterContext, interpreter.hpp)."""

    def __init__(self, storage: InMemoryStorage, config: Optional[dict] = None):
        from ..utils.locks import tracked_lock
        from ..utils.sanitize import shared_field
        self.storage = storage
        self.config = config or {}
        self._plan_cache_lock = tracked_lock(
            "InterpreterContext._plan_cache_lock")
        self._plan_cache: dict[str, tuple] = {}
        self._ast_cache: dict[str, object] = {}
        self.running_queries: dict[int, dict] = {}
        # SHOW/TERMINATE TRANSACTIONS iterate this dict from other
        # sessions' threads while queries register/unregister — the old
        # unguarded list(items()) could see a mid-resize dict
        self._rq_lock = tracked_lock("InterpreterContext._rq_lock")
        self._next_query_id = 0
        self._query_id_lock = threading.Lock()
        shared_field(self, "_plan_cache", "_ast_cache",
                     "running_queries")
        self.triggers = None       # wired by trigger store
        self.auth = None           # wired by auth subsystem
        self.metrics = None

    def next_query_id(self) -> int:
        with self._query_id_lock:
            self._next_query_id += 1
            return self._next_query_id

    def cached_parse(self, text: str):
        from ..utils.sanitize import shared_read, shared_write
        key = text.strip()
        with self._plan_cache_lock:
            shared_read(self, "_ast_cache")
            hit = self._ast_cache.get(key)
        if hit is not None:
            return hit
        node = parse_with_source(text)
        # only cache cacheable query classes (parameters keep text stable).
        # Parse happens OUTSIDE the lock: duplicated work on a cache miss
        # is benign, serializing parsing is not.
        with self._plan_cache_lock:
            shared_write(self, "_ast_cache")
            if len(self._ast_cache) < 1024:
                self._ast_cache[key] = node
        return node

    def cached_plan(self, text: str, query: A.CypherQuery):
        """Returns (plan, columns, cache_hit) — the hit flag feeds the
        per-fingerprint plan-cache hit-rate in SHOW QUERY STATS."""
        from ..utils.sanitize import shared_read, shared_write
        key = text.strip()
        with self._plan_cache_lock:
            shared_read(self, "_plan_cache")
            hit = self._plan_cache.get(key)
        if hit is not None:
            return hit[0], hit[1], True
        planner = Planner(self.storage, self.config)
        import copy
        plan, columns = planner.plan_query(copy.deepcopy(query))
        with self._plan_cache_lock:
            shared_write(self, "_plan_cache")
            if len(self._plan_cache) < 256:
                self._plan_cache[key] = (plan, columns)
        return plan, columns, False

    def invalidate_plans(self) -> None:
        with self._plan_cache_lock:
            self._plan_cache.clear()
        # schema changes invalidate compiled lanes too: a lane program
        # compiled under dropped DDL / stale statistics must never
        # serve again (query/plan/lane.py; regression: tests/test_lane)
        from .plan.lane import invalidate_lanes
        invalidate_lanes()


@dataclass
class PreparedQuery:
    columns: list[str]
    qid: int
    summary_type: str = "r"   # 'r' read, 'w' write, 'rw', 's' schema
    # Cypher-only precise classification (plan-derived): True when the
    # plan contains any updating operator. Read-only dispatchers (the
    # multiprocess read executor) key on this instead of summary_type,
    # which stays 'rw' for every Cypher query for Bolt compatibility.
    is_write: bool = False


class Interpreter:
    """One per client session (reference: one per Bolt session)."""

    def __init__(self, context: InterpreterContext,
                 system: bool = False) -> None:
        _ensure_recursion_limit()
        # system interpreters (triggers, streams, init-file, replication
        # internals) bypass RBAC — they act on behalf of the server
        self.system = system
        self.ctx = context
        # instance-level anchor: USE DATABASE rebinds self.ctx, but the
        # active-session registry is instance-wide (reference:
        # GetActiveUsersInfo), so it always reads/writes through this
        self.root_ctx = context
        self.session_isolation: Optional[IsolationLevel] = None
        self.next_isolation: Optional[IsolationLevel] = None
        self._explicit_accessor = None
        self._in_explicit_txn = False
        self._stream: Optional[Iterator] = None
        self._stream_accessor = None
        self._stream_owns_txn = False
        self._prepared: Optional[PreparedQuery] = None
        self._exec_ctx: Optional[ExecutionContext] = None
        self._profile_plan = None
        self._profile_start = None
        self._abort_flag = threading.Event()
        self._current_query_info = None
        from ..observability.audit import SessionTrace
        self.session_trace = SessionTrace()
        self.username = ""
        # mgtrace: the query-root trace handle (None unless tracing is
        # armed) + per-phase durations for the slow-query log
        self._trace_root = None
        self._phase_s: dict[str, float] = {}
        self._prepare_finished: tuple[float, float] | None = None
        # mgstat: per-query fingerprint accounting state
        self._query_fingerprint: str | None = None
        self._plan_cache_hit = False
        self._rows_emitted = 0

    # --- public API ---------------------------------------------------------

    def prepare(self, text: str, parameters: Optional[dict] = None
                ) -> PreparedQuery:
        handle = None
        if mgtrace.armed():
            if self._trace_root is not None:
                # the client abandoned the previous prepare (never
                # pulled): close its trace out instead of leaking it
                self._trace_root.finish(status="abandoned")
            # inherits the ambient context (the Bolt session span) as
            # parent when one is active on this thread
            self._trace_root = handle = mgtrace.begin_trace("query")
        try:
            with mgtrace.activate(handle.ctx if handle else None):
                prepared = self._prepare_inner(text, parameters)
            self._prepare_finished = (time.time(), time.monotonic())
            return prepared
        except Exception as e:
            if handle is not None:
                handle.finish(status="error",
                              error=f"{type(e).__name__}: {e}")
                self._trace_root = None
            if self.ctx.config.get("log_failed_queries"):
                import logging
                logging.getLogger(__name__).warning(
                    "query failed: %s", text.strip())
            raise

    def _prepare_inner(self, text: str, parameters: Optional[dict] = None
                       ) -> PreparedQuery:
        parameters = parameters or {}
        audit = getattr(self.ctx, "audit", None)
        if audit is not None:
            audit.record(getattr(self, "username", ""), text, parameters)
        from ..observability.metrics import global_metrics
        global_metrics.increment("query.prepared")
        self._query_started = time.monotonic()
        self._query_text = text
        self._pending_op_counts = None   # drop any abandoned prepare's
        self._query_priv_auth = False    # AUTH queries skip the slow log
        self._phase_s = {}
        self._prepare_finished = None
        self.session_trace.emit("prepare", query=text)
        t0 = time.perf_counter()
        with mgtrace.span("query.parse"):
            node = self.ctx.cached_parse(text)
        self._phase_s["parse"] = time.perf_counter() - t0
        if isinstance(node, A.SessionTraceQuery):
            if node.enabled:
                self.session_trace.enabled = True
                self.session_trace.events = []
                return self._prepare_generator(
                    iter([["session trace enabled"]]), ["status"], "s")
            self.session_trace.enabled = False
            rows = [[e.pop("ts"), e.pop("event"), str(e)]
                    for e in self.session_trace.drain()]
            return self._prepare_generator(
                iter(rows), ["timestamp", "event", "data"], "r")

        priv = self._NODE_PRIVILEGES.get(type(node).__name__)
        # AUTH statements carry plaintext credentials (CREATE USER ...
        # IDENTIFIED BY, SET PASSWORD): never echo them into the slow-query
        # log / monitoring-websocket broadcast (ADVICE r5)
        self._query_priv_auth = priv == "AUTH"
        if priv is not None:
            self._check_privilege(priv)

        if isinstance(node, A.TransactionQuery):
            return self._prepare_transaction(node)
        if isinstance(node, A.CypherQuery):
            return self._prepare_cypher(text, node, parameters)
        if isinstance(node, (A.IndexQuery, A.ConstraintQuery,
                             A.TriggerQuery, A.StorageModeQuery,
                             A.AuthQuery)) and not (
                isinstance(node, A.TriggerQuery) and node.action == "show"):
            self._ensure_writable(type(node).__name__)
        if isinstance(node, A.IndexQuery):
            return self._prepare_generator(self._run_index_query(node),
                                           ["status"], "s")
        if isinstance(node, A.ConstraintQuery):
            return self._prepare_generator(self._run_constraint_query(node),
                                           ["status"], "s")
        if isinstance(node, A.InfoQuery):
            return self._prepare_info(node)
        if isinstance(node, A.ShowTransactionsQuery):
            rows = self._show_transactions()
            return self._prepare_generator(
                iter(rows), ["transaction_id", "query", "username"], "r")
        if isinstance(node, A.TerminateTransactionsQuery):
            return self._prepare_terminate(node, parameters)
        if isinstance(node, A.SnapshotQuery):
            return self._prepare_snapshot(node)
        if isinstance(node, A.DumpQuery):
            from .dump import dump_database
            acc = self.ctx.storage.access()
            def gen():
                try:
                    for line in dump_database(acc):
                        yield [line]
                finally:
                    acc.abort()
            return self._prepare_generator(gen(), ["QUERY"], "r")
        if isinstance(node, A.AnalyzeGraphQuery):
            return self._prepare_analyze_graph(node)
        if isinstance(node, A.IsolationLevelQuery):
            return self._prepare_isolation(node)
        if isinstance(node, A.StorageModeQuery):
            return self._prepare_storage_mode(node)
        if isinstance(node, A.TriggerQuery):
            return self._prepare_trigger(node)
        if isinstance(node, A.AuthQuery):
            return self._prepare_auth(node, parameters)
        if isinstance(node, A.ReplicationQuery):
            return self._prepare_replication(node)
        if isinstance(node, A.StreamQuery):
            return self._prepare_stream(node)
        if isinstance(node, A.CoordinatorQuery):
            return self._prepare_coordinator(node)
        if isinstance(node, A.MultiDatabaseQuery):
            return self._prepare_multidb(node)
        if isinstance(node, A.TenantProfileQuery):
            return self._prepare_tenant_profile(node)
        if isinstance(node, A.UserProfileQuery):
            return self._prepare_user_profile(node)
        if isinstance(node, A.SettingQuery):
            return self._prepare_setting(node)
        if isinstance(node, A.EnumQuery):
            return self._prepare_enum(node)
        if isinstance(node, A.TtlQuery):
            return self._prepare_ttl(node)
        raise SemanticException(
            f"unsupported query type {type(node).__name__}")

    def _prepare_stream(self, node: A.StreamQuery) -> PreparedQuery:
        from .streams import StreamSpec, streams_of
        streams = streams_of(self.ctx)
        if node.action == "create":
            self._ensure_writable("CREATE STREAM")
            cfg = getattr(self.ctx, "config", {}) or {}
            streams.create(StreamSpec(
                name=node.name, kind=node.kind, topics=list(node.topics),
                transform=node.transform, batch_size=node.batch_size,
                batch_interval_sec=node.batch_interval_ms / 1000.0,
                bootstrap_servers=(node.bootstrap_servers
                                   or cfg.get("kafka_bootstrap_servers",
                                              "")),
                service_url=(node.service_url
                             or cfg.get("pulsar_service_url", "")),
                consumer_group=node.consumer_group))
            return self._prepare_generator(iter([]), [], "s")
        if node.action == "drop":
            streams.drop(node.name)
            return self._prepare_generator(iter([]), [], "s")
        if node.action == "start":
            streams.start(node.name)
            return self._prepare_generator(iter([]), [], "s")
        if node.action == "stop":
            streams.stop(node.name)
            return self._prepare_generator(iter([]), [], "s")
        if node.action == "start_all":
            streams.start_all()
            return self._prepare_generator(iter([]), [], "s")
        if node.action == "stop_all":
            streams.stop_all()
            return self._prepare_generator(iter([]), [], "s")
        if node.action == "show":
            return self._prepare_generator(
                iter(streams.show()),
                ["name", "type", "topics", "transform", "batch_size",
                 "status", "processed_messages", "last_error"], "r")
        if node.action == "check":
            rows = [r for r in streams.show() if r[0] == node.name]
            return self._prepare_generator(
                iter(rows),
                ["name", "type", "topics", "transform", "batch_size",
                 "status", "processed_messages", "last_error"], "r")
        raise SemanticException(f"unknown stream action {node.action}")

    def _settings(self):
        from ..storage.kvstore import ensure_settings
        return ensure_settings(self.ctx)

    def _prepare_enum(self, node: A.EnumQuery) -> PreparedQuery:
        from ..storage.enums import enum_registry
        registry = enum_registry(self.ctx.storage)
        if node.action == "create":
            self._ensure_writable("CREATE ENUM")
            registry.create(node.name, node.values)
            self._persist_enums(registry)
            return self._prepare_generator(iter([]), [], "s")
        if node.action == "add_value":
            self._ensure_writable("ALTER ENUM")
            registry.add_value(node.name, node.values[0])
            self._persist_enums(registry)
            return self._prepare_generator(iter([]), [], "s")
        rows = [[name, values] for name, values in registry.to_list()]
        return self._prepare_generator(iter(rows),
                                       ["enum_name", "enum_values"], "r")

    def _persist_enums(self, registry) -> None:
        kv = getattr(self.ctx, "kvstore", None)
        if kv is not None:
            import json as _json
            kv.put("enums", _json.dumps(registry.to_list()))

    def _prepare_analyze_graph(self, node) -> PreparedQuery:
        """ANALYZE GRAPH [ON LABELS ...] [DELETE STATISTICS].

        Computes the same per-index statistics the reference stores for its
        cost model (interpreter.cpp HandleAnalyzeGraphQuery: num estimation
        nodes, num groups, avg group size, chi-squared, avg degree; degrees
        count both directions, and composite indexes get a row per property
        prefix). The planner here reads live approx_count() from the
        indexes, so the rows are a reporting surface; stats live in
        indices.analyze_stats (dropped with their index) and are cleared by
        DELETE STATISTICS."""
        if self._in_explicit_txn:
            raise TransactionException(
                "ANALYZE GRAPH cannot run inside a transaction")
        storage = self.ctx.storage
        indices = storage.indices
        label_filter = None
        if node.labels:
            label_filter = {storage.label_mapper.maybe_name_to_id(name)
                            for name in node.labels}
            label_filter.discard(None)

        def wanted(lid):
            return label_filter is None or lid in label_filter

        if node.action == "delete":
            rows = []
            for (lid, pids) in sorted(indices.analyze_stats):
                if not wanted(lid):
                    continue
                rows.append([
                    storage.label_mapper.id_to_name(lid),
                    [storage.property_mapper.id_to_name(p) for p in pids]
                    if pids else None,
                ])
            indices.analyze_stats = {
                k: v for k, v in indices.analyze_stats.items()
                if not wanted(k[0])}
            # cached plans were chosen under the dropped statistics
            self.ctx.invalidate_plans()
            return self._prepare_generator(
                iter(rows), ["label", "property"], "r")

        acc = storage.access()
        try:
            stats = {}
            rows = []
            for lid in sorted(indices.label.labels()):
                if not wanted(lid):
                    continue
                count = 0
                degree_sum = 0
                for va in acc.vertices_by_label(lid, View.OLD):
                    count += 1
                    degree_sum += (va.out_degree(View.OLD)
                                   + va.in_degree(View.OLD))
                avg_degree = degree_sum / count if count else 0.0
                stats[(lid, ())] = {"count": count,
                                    "avg_degree": avg_degree}
                rows.append([storage.label_mapper.id_to_name(lid), None,
                             count, None, None, None, avg_degree])
            # one scan per indexed label covers the full key and every
            # property prefix (the reference emits a row per prefix so
            # prefix lookups on composite indexes get costed)
            for (lid, pids) in sorted(indices.label_property.keys()):
                if not wanted(lid):
                    continue
                prefixes = [pids[:k] for k in range(1, len(pids) + 1)]
                acc_stats = {pref: {"groups": {}, "count": 0, "deg": 0}
                             for pref in prefixes}
                for va in acc.vertices_by_label(lid, View.OLD):
                    values = tuple(va.get_property(p, View.OLD)
                                   for p in pids)
                    degree = (va.out_degree(View.OLD)
                              + va.in_degree(View.OLD))
                    for pref in prefixes:
                        pvals = values[:len(pref)]
                        if all(v is None for v in pvals):
                            continue
                        st = acc_stats[pref]
                        st["count"] += 1
                        st["deg"] += degree
                        key = order_key(list(pvals))
                        st["groups"][key] = st["groups"].get(key, 0) + 1
                for pref in prefixes:
                    st = acc_stats[pref]
                    count, n_groups = st["count"], len(st["groups"])
                    avg_group = count / n_groups if n_groups else 0.0
                    chi2 = sum((c - avg_group) ** 2 / avg_group
                               for c in st["groups"].values()) \
                        if avg_group else 0.0
                    avg_degree = st["deg"] / count if count else 0.0
                    stats[(lid, pref)] = {
                        "count": count, "num_groups": n_groups,
                        "avg_group_size": avg_group, "chi_squared": chi2,
                        "avg_degree": avg_degree}
                    rows.append([
                        storage.label_mapper.id_to_name(lid),
                        [storage.property_mapper.id_to_name(p)
                         for p in pref],
                        count, n_groups, avg_group, chi2, avg_degree])
        finally:
            acc.abort()
        indices.analyze_stats.update(stats)
        # fresh statistics change index selection: cached plans must
        # re-plan (reference re-plans through its stats-keyed cache)
        self.ctx.invalidate_plans()
        return self._prepare_generator(
            iter(rows),
            ["label", "property", "num estimation nodes", "num groups",
             "avg group size", "chi-squared value", "avg degree"], "r")

    def _prepare_setting(self, node: A.SettingQuery) -> PreparedQuery:
        settings = self._settings()
        if node.action == "set":
            self._ensure_writable("SET DATABASE SETTING")
            settings.set(node.name, node.value)
            return self._prepare_generator(iter([]), [], "s")
        if node.action == "show_one":
            value = settings.get(node.name)
            rows = [[node.name, value]] if value is not None else []
            return self._prepare_generator(iter(rows),
                                           ["setting_name", "setting_value"],
                                           "r")
        rows = sorted([k, v] for k, v in settings.all().items())
        return self._prepare_generator(iter(rows),
                                       ["setting_name", "setting_value"],
                                       "r")

    def _prepare_user_profile(self, node) -> PreparedQuery:
        """Per-user profiles (reference: auth/profiles/user_profiles.cpp,
        grammar MemgraphCypher.g4:974-991)."""
        from ..auth.profiles import ensure_user_profiles
        profiles = ensure_user_profiles(self.ctx)
        if node.action == "create":
            profiles.create(node.name, node.limits or {})
        elif node.action == "update":
            profiles.update(node.name, node.limits or {})
        elif node.action == "drop":
            profiles.drop(node.name)
        elif node.action == "assign":
            profiles.assign(node.user, node.name)
        elif node.action == "clear":
            profiles.clear(node.user)
        elif node.action == "users_for":
            rows = [[u] for u in profiles.users_for(node.name)]
            return self._prepare_generator(iter(rows), ["username"], "r")
        elif node.action == "show_for":
            pname = profiles.profile_for(node.user)
            rows = ([[pname, limits] for _n, limits
                     in profiles.show(pname)] if pname else [])
            return self._prepare_generator(iter(rows),
                                           ["profile", "limits"], "r")
        elif node.action == "show":
            rows = [[n, limits] for n, limits in profiles.show(node.name)]
            return self._prepare_generator(iter(rows),
                                           ["profile", "limits"], "r")
        else:
            raise SemanticException(
                f"unknown profile action {node.action}")
        return self._prepare_generator(iter([]), [], "w")

    def _prepare_tenant_profile(self, node) -> PreparedQuery:
        """Tenant profiles (reference: dbms/tenant_profiles.cpp)."""
        dbms = getattr(self.ctx, "dbms", None)
        if dbms is None:
            raise QueryException(
                "tenant profiles require a DbmsHandler (enabled "
                "automatically by the server entry point)")
        profiles = dbms.tenant_profiles
        if node.action == "create":
            profiles.create(node.name, node.limits or {})
        elif node.action == "alter":
            profiles.alter(node.name, node.limits or {})
        elif node.action == "drop":
            profiles.drop(node.name)
        elif node.action == "assign":
            if node.database not in dbms.names():
                raise QueryException(
                    f"database {node.database!r} does not exist")
            profiles.assign(node.database, node.name)
        elif node.action == "clear":
            profiles.clear(node.database)
        elif node.action == "show":
            import json as _json
            rows = [[name, _json.dumps(limits), dbs]
                    for name, limits, dbs in profiles.show(node.name)]
            return self._prepare_generator(
                iter(rows), ["profile", "limits", "databases"], "r")
        else:
            raise SemanticException(
                f"unknown tenant profile action {node.action}")
        return self._prepare_generator(iter([]), [], "s")

    def _prepare_multidb(self, node: A.MultiDatabaseQuery) -> PreparedQuery:
        dbms = getattr(self.ctx, "dbms", None)
        if dbms is None:
            raise QueryException(
                "multi-database support requires a DbmsHandler (enabled "
                "automatically by the server entry point)")
        if node.action == "create":
            dbms.create(node.name)
            self._publish_system("db_create", {"name": node.name})
            return self._prepare_generator(
                iter([[f"Database {node.name} created."]]), ["status"], "s")
        if node.action == "drop":
            dbms.drop(node.name)
            self._publish_system("db_drop", {"name": node.name})
            return self._prepare_generator(
                iter([[f"Database {node.name} dropped."]]), ["status"], "s")
        if node.action == "use":
            if self._in_explicit_txn:
                raise TransactionException(
                    "cannot switch databases inside a transaction")
            target = dbms.get(node.name)
            # the session keeps this Interpreter object; rebind it
            self.ctx = target
            return self._prepare_generator(
                iter([[f"Using database {node.name}."]]), ["status"], "s")
        if node.action == "suspend":
            dbms.suspend(node.name)
            self._publish_system("db_suspend", {"name": node.name})
            return self._prepare_generator(
                iter([[f"Database {node.name} suspended."]]),
                ["status"], "s")
        if node.action == "resume":
            dbms.resume(node.name)
            self._publish_system("db_resume", {"name": node.name})
            return self._prepare_generator(
                iter([[f"Database {node.name} resumed."]]),
                ["status"], "s")
        if node.action == "show":
            current = getattr(self.ctx, "database_name", "memgraph")
            rows = [[name, name == current] for name in dbms.names()]
            return self._prepare_generator(iter(rows),
                                           ["Name", "Current"], "r")
        raise SemanticException(f"unknown database action {node.action}")

    def _prepare_coordinator(self, node: A.CoordinatorQuery) -> PreparedQuery:
        coordinator = getattr(self.ctx, "coordinator", None)
        if coordinator is None:
            raise QueryException(
                "this instance is not a coordinator (start with "
                "--coordinator-id/--coordinator-port)")
        if node.action == "register":
            ok = coordinator.register_instance(node.name, node.mgmt_address,
                                               node.replication_address,
                                               node.bolt_address)
            if not ok:
                raise QueryException(
                    "could not commit instance registration (no raft "
                    "majority or not the leader)")
            return self._prepare_generator(iter([]), [], "s")
        if node.action == "unregister":
            coordinator.unregister_instance(node.name)
            return self._prepare_generator(iter([]), [], "s")
        if node.action == "set_main":
            if not coordinator.set_instance_to_main(node.name):
                raise QueryException(f"cannot promote {node.name!r}")
            return self._prepare_generator(iter([]), [], "s")
        if node.action == "show":
            return self._prepare_generator(
                iter(coordinator.show_instances()),
                ["name", "address", "role", "health"], "r")
        raise SemanticException(f"unknown coordinator action {node.action}")

    def _prepare_ttl(self, node: A.TtlQuery) -> PreparedQuery:
        from ..storage.ttl import ttl_runner
        runner = ttl_runner(self.ctx)
        if node.action == "enable":
            if node.period:
                runner.period_sec = _parse_period(node.period)
            runner.start()
        else:
            runner.stop()
        return self._prepare_generator(iter([]), [], "s")

    def _fine_grained_view(self):
        """Storage-level fine-grained filter for this session's user, or
        None when unrestricted (reference: glue/auth_checker.cpp building a
        FineGrainedAuthChecker per execution)."""
        from ..auth.auth import resolve_auth
        auth = resolve_auth(self.ctx)
        if not auth.users():
            return None
        checker = auth.fine_grained_checker(self.username or "")
        if not checker.restricted:
            return None
        from ..auth.fine_grained import FgStorageView
        return FgStorageView(checker, self.ctx.storage)

    def _auth_store(self):
        from ..auth.auth import resolve_auth
        return resolve_auth(self.ctx)

    @staticmethod
    def _password_value(expr, parameters):
        """Password expression -> value: literal or $parameter only — a
        silently-ignored expression would null the password and open the
        account (found by review r4)."""
        if expr is None:
            return None
        if isinstance(expr, A.Literal):
            return expr.value
        if isinstance(expr, A.Parameter):
            params = parameters or {}
            if expr.name not in params:
                raise QueryException(
                    f"password parameter ${expr.name} not provided")
            return params[expr.name]
        raise QueryException(
            "passwords must be a string literal or a $parameter")

    def _check_password_policy(self, password) -> None:
        """--auth-password-strength-regex / --auth-password-permit-null
        (reference: flags/general.cpp password policy)."""
        import re as _re
        cfg = getattr(self.ctx, "config", {}) or {}
        if password is None:
            if not cfg.get("auth_password_permit_null", True):
                raise QueryException(
                    "null passwords are forbidden "
                    "(--no-auth-password-permit-null)")
            return
        pattern = cfg.get("auth_password_strength_regex", ".+")
        if not _re.fullmatch(pattern, str(password)):
            raise QueryException(
                "the new password does not satisfy the password "
                "strength policy (--auth-password-strength-regex)")

    def _check_privilege(self, privilege: str) -> None:
        """Enforce RBAC when users are defined (reference: AuthChecker,
        glue/auth_checker.cpp). Sessions without users run open."""
        if self.system:
            return
        auth = self._auth_store()
        if not auth.users():
            return
        from ..exceptions import AuthException
        if not auth.has_privilege(self.username or "", privilege):
            raise AuthException(
                f"user {self.username or '<anonymous>'!r} is not allowed "
                f"to execute this query (missing privilege {privilege})")

    _NODE_PRIVILEGES = {
        "IndexQuery": "INDEX", "ConstraintQuery": "CONSTRAINT",
        "TriggerQuery": "TRIGGER", "StorageModeQuery": "STORAGE_MODE",
        "AuthQuery": "AUTH", "ReplicationQuery": "REPLICATION",
        "StreamQuery": "STREAM", "SnapshotQuery": "DURABILITY",
        "DumpQuery": "DUMP", "MultiDatabaseQuery": "MULTI_DATABASE_EDIT",
        "TenantProfileQuery": "MULTI_DATABASE_EDIT",
        "UserProfileQuery": "AUTH",
        "TtlQuery": "CONFIG", "SettingQuery": "CONFIG",
        "CoordinatorQuery": "COORDINATOR",
        "TerminateTransactionsQuery": "TRANSACTION_MANAGEMENT",
        "ShowTransactionsQuery": "TRANSACTION_MANAGEMENT",
        "AnalyzeGraphQuery": "STATS",
    }

    def _ensure_writable(self, what: str) -> None:
        replication = getattr(self.ctx, "replication", None)
        if replication is not None and replication.role == "replica":
            raise QueryException(
                f"{what} is forbidden on a REPLICA instance")
        if replication is not None and replication.role == "main" \
                and replication.is_fenced():
            # deposed MAIN (a newer fencing epoch exists): refuse loudly
            # at query admission, before the commit path even starts
            from ..exceptions import FencedException
            raise FencedException(
                f"{what} is forbidden: this MAIN was deposed (fenced); "
                "reconnect via the coordinator routing table")

    def _replication_state(self):
        if getattr(self.ctx, "replication", None) is None:
            from ..replication.main_role import ReplicationState
            self.ctx.replication = ReplicationState(self.ctx.storage, ictx=self.ctx)
        return self.ctx.replication

    def _prepare_replication(self, node: A.ReplicationQuery) -> PreparedQuery:
        from ..replication.main_role import ReplicationMode
        state = self._replication_state()
        if node.action == "set_role_main":
            state.set_role_main()
            return self._prepare_generator(iter([]), [], "s")
        if node.action == "set_role_replica":
            state.set_role_replica("0.0.0.0", node.port)
            return self._prepare_generator(iter([]), [], "s")
        if node.action == "register":
            state.register_replica(node.name, node.address,
                                   ReplicationMode[node.mode])
            return self._prepare_generator(iter([]), [], "s")
        if node.action == "drop":
            state.drop_replica(node.name)
            return self._prepare_generator(iter([]), [], "s")
        if node.action == "show_replicas":
            return self._prepare_generator(
                iter(state.show_replicas()),
                ["name", "socket_address", "sync_mode",
                 "last_acked_timestamp", "state"], "r")
        if node.action == "show_role":
            return self._prepare_generator(
                iter([[state.role]]), ["replication role"], "r")
        raise SemanticException(f"unknown replication action {node.action}")

    def pull(self, n: int = -1) -> tuple[list[list], bool, dict]:
        """Pull up to n rows (n<0 = all). Returns (rows, has_more, summary)."""
        if self._stream is None:
            raise QueryException("no query prepared")
        # re-activate the query root on THIS thread (Bolt pulls may run
        # on a different worker thread than the prepare): device/kernel
        # spans opened during execution join the query's trace
        root = self._trace_root
        with mgtrace.activate(root.ctx if root is not None else None):
            return self._pull_inner(n)

    def _pull_inner(self, n: int) -> tuple[list[list], bool, dict]:
        rows: list[list] = []
        has_more = False
        try:
            while n < 0 or len(rows) < n:
                try:
                    rows.append(next(self._stream))
                except StopIteration:
                    break
            else:
                # check if exhausted
                try:
                    rows.append(next(self._stream))
                    has_more = True
                except StopIteration:
                    has_more = False
            if has_more and n >= 0 and len(rows) > n:
                # put back overflow row
                overflow = rows.pop()
                self._stream = _chain_front(overflow, self._stream)
        except Exception:
            self._cleanup_stream(error=True)
            raise
        summary = {}
        if not has_more:
            summary = self._finish_stream()
        return rows, has_more, summary

    def abort(self) -> None:
        """Kill the current query/transaction (TERMINATE/reset)."""
        self._abort_flag.set()
        self._cleanup_stream(error=True)
        if self._explicit_accessor is not None:
            self._explicit_accessor.abort()
            self._explicit_accessor = None
            self._in_explicit_txn = False

    # --- transactions -------------------------------------------------------

    def stage_stream_offset(self, name: str, position) -> None:
        """Stage a stream source position into the OPEN explicit
        transaction: the offset becomes a WAL record in the same commit
        frame as the batch's data (the exactly-once boundary the stream
        consumer relies on)."""
        if not self._in_explicit_txn or self._explicit_accessor is None:
            raise TransactionException(
                "stream offsets can only be staged inside an explicit "
                "transaction")
        self._explicit_accessor.stage_stream_offset(name, position)

    def _prepare_transaction(self, node: A.TransactionQuery) -> PreparedQuery:
        if node.action == "begin":
            if self._in_explicit_txn:
                raise TransactionException(
                    "nested transactions are not supported")
            self._explicit_accessor = self._fg_access(
                self._pick_isolation())
            self._in_explicit_txn = True
            return self._prepare_generator(iter([]), [], "w")
        if node.action == "commit":
            if not self._in_explicit_txn:
                raise TransactionException("no transaction to commit")
            try:
                self._explicit_accessor.commit()
            finally:
                self._explicit_accessor = None
                self._in_explicit_txn = False
            return self._prepare_generator(iter([]), [], "w")
        if node.action == "rollback":
            if not self._in_explicit_txn:
                raise TransactionException("no transaction to rollback")
            self._explicit_accessor.abort()
            self._explicit_accessor = None
            self._in_explicit_txn = False
            return self._prepare_generator(iter([]), [], "w")
        raise SemanticException(f"unknown transaction action {node.action}")

    def _fg_access(self, isolation=None):
        acc = self.ctx.storage.access(isolation)
        acc.fine_grained = self._fine_grained_view()
        return acc

    def _pick_isolation(self) -> IsolationLevel:
        if self.next_isolation is not None:
            level = self.next_isolation
            self.next_isolation = None
            return level
        if self.session_isolation is not None:
            return self.session_isolation
        return self.ctx.storage.config.isolation_level

    # --- cypher -------------------------------------------------------------

    def _prepare_cypher(self, text: str, query: A.CypherQuery,
                        parameters: dict) -> PreparedQuery:
        strip = text.strip()
        if query.explain or query.profile:
            # strip the EXPLAIN/PROFILE keyword for plan-cache keying
            strip = strip.split(None, 1)[1] if " " in strip else strip
        t0 = time.perf_counter()
        with mgtrace.span("query.plan"):
            plan, columns, cache_hit = self.ctx.cached_plan(strip, query)
        self._phase_s["plan"] = time.perf_counter() - t0
        # mgstat: the fingerprint is keyed off the same stripped text as
        # the plan cache, so repeat queries pay one memo-dict lookup
        from ..observability.stats import global_query_stats
        if global_query_stats.enabled():
            self._query_fingerprint = global_query_stats.fingerprint(strip)
        else:
            self._query_fingerprint = None
        if getattr(plan, "_has_lane", False):
            # compiled read lane: the mgstat fingerprint is the lane's
            # compile-cache key and stats bucket (query/plan/lane.py)
            from .plan.lane import bind_fingerprints
            from ..observability.stats import fingerprint_text
            bind_fingerprints(plan, self._query_fingerprint
                              or fingerprint_text(strip))
        self._plan_cache_hit = cache_hit
        self._rows_emitted = 0

        if self.ctx.config.get("debug_query_plans"):
            import logging
            logging.getLogger(__name__).debug(
                "plan for %s:\n%s", strip, "\n".join(plan_to_rows(plan)))
        if self.ctx.config.get("log_query_plan"):
            import logging
            logging.getLogger(__name__).info(
                "plan for %s:\n%s", strip, "\n".join(plan_to_rows(plan)))

        if self._in_explicit_txn and _plan_has_batched_apply(plan):
            raise TransactionException(
                "CALL { } IN TRANSACTIONS is not allowed inside an "
                "explicit transaction")
        needed = _plan_privileges(plan)
        for privilege in sorted(needed):
            self._check_privilege(privilege)
        is_write = bool(needed - _READ_ONLY_PRIVILEGES)

        replication = getattr(self.ctx, "replication", None)
        if replication is not None and replication.role == "replica" \
                and is_write:
            raise QueryException(
                "write queries are forbidden on a REPLICA instance")

        if query.explain:
            rows = [[line] for line in plan_to_rows(plan)]
            return self._prepare_generator(iter(rows), ["QUERY PLAN"], "r")

        # per-operator execution counters (reference:
        # prometheus_metrics.hpp:108-157 via interpreter.cpp:3320):
        # counted at successful COMPLETION (_finish_stream), not prepare,
        # so failed/aborted queries don't inflate them. The counts are
        # derived once per (cached) plan, not walked per query.
        counts = getattr(plan, "_op_counts", None)
        if counts is None:
            counts = _plan_operator_counts(plan)
            try:
                plan._op_counts = counts
            except (AttributeError, TypeError):
                pass  # frozen/slotted root: recompute next time
        self._pending_op_counts = counts

        if self._in_explicit_txn:
            accessor = self._explicit_accessor
            owns = False
        else:
            accessor = self._fg_access(self._pick_isolation())
            owns = True

        self._abort_flag = threading.Event()
        timeout = self.ctx.config.get("execution_timeout_sec", 600.0)
        deadline = time.monotonic() + timeout if timeout else None
        abort_flag = self._abort_flag

        def timeout_checker():
            if abort_flag.is_set():
                raise HintedAbortError("transaction was asked to abort")
            if deadline is not None and time.monotonic() > deadline:
                raise HintedAbortError(
                    f"query exceeded timeout of {timeout}s")

        from ..utils.memory_tracker import QueryMemoryTracker
        mem_limit = query.memory_limit
        if mem_limit is None:
            # defaults layer: the tenant profile caps the database, the
            # USER profile caps the session's user — smaller wins
            # (reference: tenant_profiles.cpp memory_limit +
            # user_profiles.cpp transactions_memory)
            caps = []
            dbms = getattr(self.ctx, "dbms", None)
            if dbms is not None:
                cap = dbms.tenant_profiles.limit_for_database(
                    getattr(self.ctx, "database_name", ""),
                    "memory_limit")
                if cap is not None:
                    caps.append(cap)
            up = getattr(self.ctx, "user_profiles", None)
            if up is not None and self.username:
                cap = up.limit_for_user(self.username,
                                        "transactions_memory")
                if cap is not None:
                    caps.append(cap)
            mem_limit = min(caps) if caps else None
        exec_ctx = ExecutionContext(accessor, parameters,
                                    View.NEW, self.ctx, timeout_checker,
                                    memory=QueryMemoryTracker(mem_limit))
        exec_ctx.eval_ctx.username = self.username
        # flag default, overridable per-instance at runtime via
        # SET DATABASE SETTING 'hops_limit_partial_results'
        exec_ctx.hops_partial = bool(self.ctx.config.get(
            "hops_limit_partial_results", True))
        hp = self._settings().get("hops_limit_partial_results")
        if hp is not None:
            exec_ctx.hops_partial = hp.strip().lower() != "false"
        if owns:
            exec_ctx._txn_owner = _TxnOwner(self, exec_ctx)
        self._exec_ctx = exec_ctx

        if query.profile:
            from .plan.profile import PROFILE_COLUMNS
            plan, collector = attach_profiling(plan)
            self._profile_plan = (plan, collector)
            self._profile_start = time.perf_counter()
            rows_iter = self._profile_rows_iter(plan, exec_ctx, columns)
            self._install_stream(rows_iter, accessor, owns)
            return self._finish_prepare(list(PROFILE_COLUMNS), "r",
                                        is_write)

        qinfo = {"query": text, "start": time.time(),
                 "interpreter": self}
        qid = self.ctx.next_query_id()
        with self.ctx._rq_lock:
            self.ctx.running_queries[qid] = qinfo
        self._current_query_info = qid

        def rows_iter():
            try:
                if not columns:
                    # write-only query (no RETURN / YIELD): drain for the
                    # side effects but emit NO records — the reference
                    # streams zero records for such queries (EmptyResult
                    # operator, query/plan/operator.hpp)
                    for _ in plan.cursor(exec_ctx):
                        pass
                    return
                for frame in plan.cursor(exec_ctx):
                    row = frame.get("__row__", {})
                    self._rows_emitted += 1
                    yield [row.get(c) for c in columns]
            finally:
                with self.ctx._rq_lock:
                    self.ctx.running_queries.pop(qid, None)

        self._install_stream(rows_iter(), accessor, owns)
        return self._finish_prepare(columns, "rw", is_write)

    def _profile_rows_iter(self, plan, exec_ctx, columns):
        # drain fully under an active stage accumulator (device work —
        # in-process mesh kernels OR kernel-server dispatches whose
        # replies ship their stage splits home — attributes to it),
        # then emit the profile tree
        from ..observability import stats as mgstats
        acc = mgstats.StageAccumulator()
        with mgstats.collecting_stages(acc):
            for _ in plan.cursor(exec_ctx):
                self._rows_emitted += 1
        total = time.perf_counter() - self._profile_start
        plan_obj, collector = self._profile_plan
        yield from profile_rows(plan_obj, collector, total,
                                stages=acc.snapshot())

    def _install_stream(self, iterator, accessor, owns_txn):
        self._stream = iterator
        self._stream_accessor = accessor
        self._stream_owns_txn = owns_txn

    def _finish_prepare(self, columns, summary_type,
                        is_write: bool = False) -> PreparedQuery:
        self._prepared = PreparedQuery(columns, 0, summary_type, is_write)
        return self._prepared

    def _finish_stream(self) -> dict:
        summary = {}
        self.session_trace.emit("finish")
        from ..observability.metrics import global_metrics
        pending_ops = getattr(self, "_pending_op_counts", None)
        self._pending_op_counts = None
        started = getattr(self, "_query_started", None)
        self._query_started = None
        if self._exec_ctx is not None:
            summary["stats"] = dict(self._exec_ctx.stats)
            self._exec_ctx.memory.release_all()
        # execute phase = end of prepare -> stream exhaustion (measured
        # BEFORE the commit below so the phases stay disjoint)
        pf = self._prepare_finished
        if pf is not None:
            self._phase_s["execute"] = time.monotonic() - pf[1]
            mgtrace.record_span("query.execute", pf[0],
                                self._phase_s["execute"])
        # the commit can still fail (constraint violations surface here):
        # counters are recorded only after it succeeds
        if self._stream_owns_txn and self._stream_accessor is not None:
            t0 = time.perf_counter()
            with mgtrace.span("query.commit"):
                self._stream_accessor.commit()
            self._phase_s["commit"] = time.perf_counter() - t0
        global_metrics.increment("query.finished")
        if pending_ops:
            for op_name, count in pending_ops.items():
                global_metrics.increment(f"operator.{op_name}", count)
        if started is not None:
            elapsed = time.monotonic() - started
            global_metrics.observe("query.execution_latency_sec", elapsed)
            # mgstat: per-fingerprint accounting (Cypher queries only —
            # admin statements never set a fingerprint). Recorded after
            # the commit so a constraint-violating query lands in the
            # error path below instead.
            fp = getattr(self, "_query_fingerprint", None)
            if fp is not None:
                from ..observability.stats import global_query_stats
                global_query_stats.record(
                    fp, elapsed, rows=getattr(self, "_rows_emitted", 0),
                    error=False,
                    plan_cache_hit=getattr(self, "_plan_cache_hit",
                                           False),
                    trace_id=self._trace_root.trace_id
                    if self._trace_root is not None else None)
                self._query_fingerprint = None
            min_ms = self.ctx.config.get("log_min_duration_ms") or 0
            slow = min_ms and elapsed * 1000.0 >= min_ms and \
                not getattr(self, "_query_priv_auth", False)
            if slow:
                # the logged entry names its trace_id so a slow query
                # links directly to the retained trace in /traces; the
                # per-phase breakdown says WHERE the time went
                import logging
                phases = " ".join(
                    f"{k}={v * 1000.0:.1f}ms"
                    for k, v in sorted(self._phase_s.items()))
                trace_id = self._trace_root.trace_id \
                    if self._trace_root is not None else "-"
                logging.getLogger(__name__).info(
                    "slow query (%.1f ms, trace_id=%s, %s): %s",
                    elapsed * 1000.0, trace_id, phases or "-",
                    _redact_literals(
                        (getattr(self, "_query_text", "") or "").strip()))
            if self._trace_root is not None:
                self._trace_root.finish(
                    status="ok", force_keep=bool(slow),
                    query=_redact_literals(
                        (getattr(self, "_query_text", "") or "").strip()),
                    **{f"{k}_ms": round(v * 1000.0, 3)
                       for k, v in self._phase_s.items()})
                self._trace_root = None
        elif self._trace_root is not None:
            self._trace_root.finish(status="ok")
            self._trace_root = None
        for key, value in summary.get("stats", {}).items():
            if value:
                global_metrics.increment(f"storage.{key}", value)
        self._stream = None
        self._stream_accessor = None
        self._stream_owns_txn = False
        self._exec_ctx = None
        return summary

    def _cleanup_stream(self, error: bool = False) -> None:
        started = getattr(self, "_query_started", None)
        fp = getattr(self, "_query_fingerprint", None)
        if fp is not None and started is not None and error:
            # errored/aborted queries count against their fingerprint
            # too — an error-heavy hot shape is exactly what SHOW QUERY
            # STATS exists to surface
            from ..observability.stats import global_query_stats
            global_query_stats.record(
                fp, time.monotonic() - started,
                rows=getattr(self, "_rows_emitted", 0), error=True,
                plan_cache_hit=getattr(self, "_plan_cache_hit", False),
                trace_id=self._trace_root.trace_id
                if self._trace_root is not None else None)
        self._query_fingerprint = None
        self._query_started = None
        self._pending_op_counts = None
        if self._exec_ctx is not None:
            self._exec_ctx.memory.release_all()
        if self._stream_owns_txn and self._stream_accessor is not None:
            self._stream_accessor.abort()
        if self._trace_root is not None:
            # errored/aborted queries are always retained
            self._trace_root.finish(
                status="error" if error else "aborted",
                error="query aborted or failed mid-stream" if error
                else None, force_keep=error)
            self._trace_root = None
        self._stream = None
        self._stream_accessor = None
        self._stream_owns_txn = False
        self._exec_ctx = None

    # --- convenience (tests, embedded use) ----------------------------------

    def execute(self, text: str, parameters: Optional[dict] = None):
        """Prepare + pull everything. Returns (columns, rows, summary)."""
        prepared = self.prepare(text, parameters)
        rows, _, summary = self.pull(-1)
        return prepared.columns, rows, summary

    # --- DDL ----------------------------------------------------------------

    def _persist_ddl(self, kind: str, key: str, create: bool,
                     value: str = "1") -> None:
        """Record index/constraint DDL in the kvstore — the authoritative
        DDL set at startup (snapshots carry DDL too, but drops after the
        last snapshot must win)."""
        kv = getattr(self.ctx, "kvstore", None)
        if kv is None:
            return
        kv.put("ddl:enabled", "1")  # marker: kvstore is DDL-authoritative
        if create:
            kv.put(f"ddl:{kind}:{key}", value or "1")
        else:
            kv.delete(f"ddl:{kind}:{key}")

    def _run_index_query(self, node: A.IndexQuery):
        storage = self.ctx.storage
        if self._in_explicit_txn:
            raise TransactionException(
                "index operations are not allowed in explicit transactions")
        import json as _json
        if node.kind == "label":
            lid = storage.label_mapper.name_to_id(node.label)
            if node.action == "create":
                storage.create_label_index(lid)
            else:
                storage.indices.label.drop(lid)
                storage.indices.drop_stats(lid)
            self._persist_ddl("index", _json.dumps(["label", node.label]),
                              node.action == "create")
        elif node.kind == "label_property":
            lid = storage.label_mapper.name_to_id(node.label)
            pids = tuple(storage.property_mapper.name_to_id(p)
                         for p in node.properties)
            if node.action == "create":
                storage.create_label_property_index(lid, pids)
            else:
                storage.indices.label_property.drop(lid, pids)
                storage.indices.drop_stats(lid, pids)
            self._persist_ddl(
                "index",
                _json.dumps(["label_property", node.label,
                             list(node.properties)]),
                node.action == "create")
        elif node.kind == "edge_type":
            tid = storage.edge_type_mapper.name_to_id(node.edge_type)
            if node.action == "create":
                storage.create_edge_type_index(tid)
            else:
                storage.indices.edge_type.drop(tid)
            self._persist_ddl("index",
                              _json.dumps(["edge_type", node.edge_type]),
                              node.action == "create")
        self.ctx.invalidate_plans()
        yield [f"Index {node.action}d."]

    def _run_constraint_query(self, node: A.ConstraintQuery):
        storage = self.ctx.storage
        if self._in_explicit_txn:
            raise TransactionException(
                "constraint operations are not allowed in explicit "
                "transactions")
        import json as _json
        lid = storage.label_mapper.name_to_id(node.label)
        pids = [storage.property_mapper.name_to_id(p)
                for p in node.properties]
        if node.kind == "exists":
            if node.action == "create":
                storage.create_existence_constraint(lid, pids[0])
            else:
                storage.constraints.existence.drop(lid, pids[0])
        elif node.kind == "unique":
            if node.action == "create":
                storage.create_unique_constraint(lid, tuple(pids))
            else:
                storage.constraints.unique.drop(lid, tuple(pids))
        elif node.kind == "type":
            if node.action == "create":
                storage.create_type_constraint(lid, pids[0], node.data_type)
            else:
                storage.constraints.type.drop(lid, pids[0])
        # data_type stays OUT of the key (drop matches on (label, props));
        # normalize it into the stored value instead
        self._persist_ddl(
            "constraint",
            _json.dumps([node.kind, node.label, list(node.properties)]),
            node.action == "create",
            value=(node.data_type or "").upper())
        # constraint DDL must drop cached plans AND compiled lanes, same
        # as index DDL: a unique constraint is also an index the planner
        # keys scans on, and a lane compiled before the drop would keep
        # serving a schema that no longer exists (bugfix, r20 mglane)
        self.ctx.invalidate_plans()
        yield [f"Constraint {node.action}d."]

    # --- info / admin -------------------------------------------------------

    def _prepare_info(self, node: A.InfoQuery) -> PreparedQuery:
        storage = self.ctx.storage
        if node.kind == "storage":
            info = storage.info()
            if self.ctx.config.get("storage_enable_edges_metadata"):
                # per-edge-type counts (reference:
                # --storage-enable-edges-metadata)
                counts: dict = {}
                for e in list(storage._edges.values()):
                    if not e.deleted:
                        counts[e.edge_type] = counts.get(e.edge_type, 0) + 1
                for et_id, cnt in sorted(counts.items()):
                    name = storage.edge_type_mapper.id_to_name(et_id)
                    info[f"edge_count[{name}]"] = cnt
            rows = [[k, v] for k, v in sorted(info.items())]
            return self._prepare_generator(iter(rows),
                                           ["storage info", "value"], "r")
        if node.kind == "index":
            # usage columns (r14, mgstat): lookups served, rows returned,
            # last-used timestamp — an index with writes but no lookups
            # is silent write overhead, now visible
            rows = []
            lm, pm = storage.label_mapper, storage.property_mapper

            def usage_cols(usage):
                if usage is None:
                    return [0, 0, None]
                return [usage.lookups, usage.rows,
                        _iso_utc(usage.last_used)]

            for lid in storage.indices.label.labels():
                rows.append(["label", lm.id_to_name(lid), None,
                             storage.indices.label.approx_count(lid)]
                            + usage_cols(storage.indices.label.usage(lid)))
            for (lid, pids) in storage.indices.label_property.keys():
                rows.append(["label+property", lm.id_to_name(lid),
                             [pm.id_to_name(p) for p in pids],
                             storage.indices.label_property.approx_count(
                                 lid, pids)]
                            + usage_cols(
                                storage.indices.label_property.usage(
                                    lid, pids)))
            for tid in storage.indices.edge_type.types():
                rows.append(["edge-type",
                             storage.edge_type_mapper.id_to_name(tid), None,
                             storage.indices.edge_type.approx_count(tid)]
                            + usage_cols(
                                storage.indices.edge_type.usage(tid)))
            return self._prepare_generator(
                iter(rows),
                ["index type", "label", "property", "count", "lookups",
                 "rows_returned", "last_used"], "r")
        if node.kind == "query_stats":
            from ..observability.stats import (QUERY_STATS_COLUMNS,
                                               global_query_stats)
            return self._prepare_generator(
                iter(global_query_stats.rows()),
                list(QUERY_STATS_COLUMNS), "r")
        if node.kind == "constraint":
            rows = []
            lm, pm = storage.label_mapper, storage.property_mapper
            for (lid, pid) in storage.constraints.existence.all():
                rows.append(["exists", lm.id_to_name(lid),
                             pm.id_to_name(pid)])
            for (lid, pids) in storage.constraints.unique.all():
                rows.append(["unique", lm.id_to_name(lid),
                             [pm.id_to_name(p) for p in pids]])
            for (lid, pid, tname) in storage.constraints.type.all():
                rows.append([f"data_type({tname})", lm.id_to_name(lid),
                             pm.id_to_name(pid)])
            return self._prepare_generator(
                iter(rows), ["constraint type", "label", "properties"], "r")
        if node.kind == "version":
            from .. import __version__
            return self._prepare_generator(iter([[__version__]]),
                                           ["version"], "r")
        if node.kind == "build":
            from .. import __version__
            rows = [["version", __version__], ["build_type", "Release"],
                    ["backend", "jax/XLA (TPU)"]]
            return self._prepare_generator(iter(rows),
                                           ["build info", "value"], "r")
        if node.kind == "license":
            from ..utils.license import LicenseChecker
            info = LicenseChecker(self._settings()).info()
            rows = [[k, v] for k, v in info.items()]
            return self._prepare_generator(iter(rows),
                                           ["license info", "value"], "r")
        if node.kind == "active_users":
            sessions = getattr(self.root_ctx, "active_sessions", {})
            # snapshot: the event-loop thread mutates this dict while
            # queries run on the worker pool
            rows = [[username, sid, login_ts]
                    for sid, (username, login_ts)
                    in sorted(list(sessions.items()),
                              key=lambda kv: kv[1][1])]
            return self._prepare_generator(
                iter(rows), ["username", "session uuid",
                             "login timestamp"], "r")
        if node.kind == "metrics":
            from ..observability.metrics import global_metrics
            rows = [[name, str(kind), value]
                    for name, kind, value in global_metrics.snapshot()]
            return self._prepare_generator(iter(rows),
                                           ["name", "type", "value"], "r")
        if node.kind == "schema":
            # full live-schema JSON document (reference:
            # storage/v2/schema_info.cpp, returned as one `schema` row;
            # gated by --schema-info-enabled as the reference gates it
            # behind --storage-enable-schema-metadata)
            if self.ctx.config.get("schema_info_enabled", True) is False:
                raise QueryException(
                    "SHOW SCHEMA INFO is disabled "
                    "(--schema-info-enabled=false)")
            from ..storage.schema_info import schema_info_json
            acc = storage.access()
            try:
                doc = schema_info_json(acc, View.OLD)
            finally:
                acc.abort()
            return self._prepare_generator(iter([[doc]]), ["schema"], "r")
        if node.kind == "database":
            name = getattr(self.ctx, "database_name", "memgraph")
            return self._prepare_generator(iter([[name]]), ["Name"], "r")
        if node.kind == "free_memory":
            # reference requires FREE_MEMORY for FREE MEMORY (declared in
            # auth.PRIVILEGES; enforce it here, not just declare it).
            self._check_privilege("FREE_MEMORY")
            import gc
            stats = storage.collect_garbage()
            gc.collect()
            from ..ops.csr import GLOBAL_GRAPH_CACHE
            GLOBAL_GRAPH_CACHE.clear()
            rows = [[k, v] for k, v in sorted(stats.items())]
            return self._prepare_generator(iter(rows),
                                           ["freed", "count"], "s")
        raise SemanticException(f"unknown info query {node.kind}")

    def _show_transactions(self):
        rows = []
        with self.ctx._rq_lock:
            snapshot = list(self.ctx.running_queries.items())
        for qid, info in snapshot:
            rows.append([str(qid), info.get("query", ""),
                         info.get("username", "")])
        return rows

    def _prepare_terminate(self, node: A.TerminateTransactionsQuery,
                           parameters) -> PreparedQuery:
        from .plan.operators import ExecutionContext
        acc = self.ctx.storage.access()
        ctx = ExecutionContext(acc, parameters)
        results = []
        try:
            for expr in node.ids:
                tid = ctx.evaluator.eval(expr, {})
                killed = False
                with self.ctx._rq_lock:
                    info = self.ctx.running_queries.get(
                        int(tid) if str(tid).isdigit() else -1)
                if info is not None:
                    interp = info.get("interpreter")
                    if interp is not None and interp is not self:
                        interp._abort_flag.set()
                        killed = True
                results.append([str(tid), killed])
        finally:
            acc.abort()
        return self._prepare_generator(iter(results),
                                       ["transaction_id", "killed"], "w")

    def _prepare_snapshot(self, node: A.SnapshotQuery) -> PreparedQuery:
        from ..storage.durability.snapshot import (create_snapshot,
                                                   list_snapshots)
        storage = self.ctx.storage
        if node.action == "create":
            path = create_snapshot(storage)
            return self._prepare_generator(iter([[str(path)]]),
                                           ["snapshot"], "s")
        if node.action == "show":
            rows = [[str(p), ts] for p, ts in list_snapshots(storage)]
            return self._prepare_generator(iter(rows),
                                           ["path", "timestamp"], "r")
        if node.action == "recover":
            from ..storage.durability.recovery import (
                recover_latest_snapshot, recover_snapshot_from)
            if node.source is not None:
                if not node.source.strip():
                    raise QueryException(
                        "RECOVER SNAPSHOT FROM requires a non-empty "
                        "source")
                recover_snapshot_from(storage, node.source)
            else:
                recover_latest_snapshot(storage)
            self.ctx.invalidate_plans()
            return self._prepare_generator(iter([["Snapshot recovered."]]),
                                           ["status"], "s")
        raise SemanticException(f"unknown snapshot action {node.action}")

    def _prepare_isolation(self, node: A.IsolationLevelQuery) -> PreparedQuery:
        level = IsolationLevel(node.level)
        if node.scope == "global":
            self.ctx.storage.config.isolation_level = level
        elif node.scope == "session":
            self.session_isolation = level
        else:
            self.next_isolation = level
        return self._prepare_generator(iter([]), [], "s")

    def _prepare_storage_mode(self, node: A.StorageModeQuery) -> PreparedQuery:
        target = StorageMode(node.mode)
        current = self.ctx.storage.config.storage_mode
        disk = StorageMode.ON_DISK_TRANSACTIONAL
        if target is disk or current is disk:
            if target is not current:
                # same rule as the reference: memory<->disk switching only
                # while the database holds no data
                acc = self.ctx.storage.access()
                try:
                    empty = next(acc.vertices(), None) is None
                finally:
                    acc.abort()
                if not empty:
                    raise QueryException(
                        "Cannot switch between in-memory and on-disk "
                        "storage modes on a non-empty database")
                self._swap_storage(target)
                return self._prepare_generator(iter([]), [], "s")
        self.ctx.storage.config.storage_mode = target
        return self._prepare_generator(iter([]), [], "s")

    def _swap_storage(self, target) -> None:
        """Replace ctx.storage with a fresh engine of the target mode (only
        reachable on an empty database)."""
        import dataclasses
        import os as _os
        from ..storage import InMemoryStorage
        from ..storage.common import StorageMode as SM
        from ..storage.disk_storage import DiskStorage
        old = self.ctx.storage
        cfg = dataclasses.replace(old.config, storage_mode=target)
        if not cfg.durability_dir:
            raise QueryException(
                "switching to/from ON_DISK_TRANSACTIONAL requires the "
                "server to run with a data directory")
        if target is SM.ON_DISK_TRANSACTIONAL:
            new = DiskStorage(cfg)
            if len(new._vertices) or len(new._edges):
                new.close()
                raise QueryException(
                    "on-disk data directory already contains a graph; "
                    "cannot switch a different database onto it")
        else:
            new = InMemoryStorage(cfg)
        if not len(new.label_mapper) and not len(new.property_mapper):
            # fresh target: carry interned names so ids stay stable for
            # cached plans; a restored disk store keeps its own mappers
            new.label_mapper = old.label_mapper
            new.property_mapper = old.property_mapper
            new.edge_type_mapper = old.edge_type_mapper
        if isinstance(old, DiskStorage):
            old.close()
        # persist the choice so restarts come back in the same mode
        marker = _os.path.join(cfg.durability_dir, "STORAGE_MODE")
        with open(marker, "w", encoding="utf-8") as f:
            f.write(target.value)
        self.ctx.storage = new
        if getattr(self.ctx, "dbms", None) is not None:
            self.ctx.dbms._databases[self.ctx.database_name] = self.ctx

    def _prepare_trigger(self, node: A.TriggerQuery) -> PreparedQuery:
        from .triggers import global_trigger_store
        store = global_trigger_store(self.ctx)
        if node.action == "create":
            store.create(node.name, node.event, node.phase, node.statement)
            return self._prepare_generator(iter([]), [], "s")
        if node.action == "drop":
            store.drop(node.name)
            return self._prepare_generator(iter([]), [], "s")
        rows = [[t.name, t.event or "ANY", t.phase, t.statement]
                for t in store.all()]
        return self._prepare_generator(
            iter(rows), ["trigger name", "event", "phase", "statement"], "r")

    def _prepare_auth(self, node: A.AuthQuery,
                  parameters=None) -> PreparedQuery:
        auth = self._auth_store()
        if node.action == "create_user":
            pw = self._password_value(node.password, parameters)
            self._check_password_policy(pw)
            auth.create_user(node.user, pw)
        elif node.action == "drop_user":
            auth.drop_user(node.user)
        elif node.action == "create_role":
            auth.create_role(node.role)
        elif node.action == "drop_role":
            auth.drop_role(node.role)
        elif node.action == "set_role":
            auth.set_role(node.user, node.role)
        elif node.action == "grant":
            auth.grant(node.user, node.privileges)
        elif node.action == "deny":
            auth.deny(node.user, node.privileges)
        elif node.action == "revoke":
            auth.revoke(node.user, node.privileges)
        elif node.action == "grant_fine_grained":
            auth.grant_fine_grained(node.user, node.fg_kind, node.fg_items,
                                    node.fg_level)
        elif node.action == "revoke_fine_grained":
            auth.revoke_fine_grained(node.user, node.fg_kind, node.fg_items)
        elif node.action == "show_users":
            return self._prepare_generator(
                iter([[u] for u in auth.users()]), ["user"], "r")
        elif node.action == "show_current_user":
            return self._prepare_generator(
                iter([[self.username or None]]), ["user"], "r")
        elif node.action == "show_roles":
            return self._prepare_generator(
                iter([[r] for r in auth.roles()]), ["role"], "r")
        elif node.action == "set_password":
            pw = self._password_value(node.password, parameters)
            self._check_password_policy(pw)
            if not self.username:
                raise QueryException(
                    "SET PASSWORD requires an authenticated user")
            auth.set_password(self.username, pw)
        elif node.action == "show_privileges":
            rows = [[p, eff] for p, eff
                    in auth.effective_privileges(node.user)]
            checker = auth.fine_grained_checker(node.user, allow_role=True)
            if checker.restricted:
                from ..auth.auth import FG_LEVELS
                inv = {v: k for k, v in FG_LEVELS.items()}
                for lbl, lv in sorted(checker._labels.items()):
                    rows.append([f"LABEL :{lbl}" if lbl != "*"
                                 else "LABEL *", inv[lv]])
                for et, lv in sorted(checker._edge_types.items()):
                    rows.append([f"EDGE_TYPE :{et}" if et != "*"
                                 else "EDGE_TYPE *", inv[lv]])
            return self._prepare_generator(
                iter(rows), ["privilege", "effective"], "r")
        else:
            raise SemanticException(f"unknown auth action {node.action}")
        # mutations replicate as ordered system transactions (reference:
        # src/system/transaction.cpp — auth + multi-DB DDL must survive
        # failover); full-state dumps keep replays idempotent
        self._publish_system("auth", auth.to_dict())
        return self._prepare_generator(iter([]), [], "s")

    def _publish_system(self, kind: str, data: dict) -> None:
        replication = getattr(self.ctx, "replication", None)
        if replication is not None and replication.role == "main":
            replication.publish_system(kind, data)

    # --- helpers ------------------------------------------------------------

    def _prepare_generator(self, iterator, columns, summary_type
                           ) -> PreparedQuery:
        self._install_stream(iterator, None, False)
        self._prepared = PreparedQuery(columns, 0, summary_type)
        return self._prepared


class _TxnOwner:
    """Lets CALL { } IN TRANSACTIONS batch-commit an autocommit query:
    commits the current accessor and swaps in a fresh one mid-stream."""

    def __init__(self, interp: "Interpreter", exec_ctx) -> None:
        self._interp = interp
        self._exec_ctx = exec_ctx

    def renew(self) -> None:
        # in-place: the SAME accessor object re-begins, so graph handles
        # held in frames and in-flight scan iterators keep working and
        # post-boundary writes land in the fresh transaction (a swapped-in
        # accessor would leave them bound to the finished one)
        self._exec_ctx.accessor.periodic_commit()


def _redact_literals(text: str) -> str:
    """Mask quoted string literals before a query reaches logs or the
    monitoring broadcast — secrets may hide in any literal, not only in
    AUTH statements (which are skipped entirely)."""
    import re
    return re.sub(r"'(?:[^'\\]|\\.)*'|\"(?:[^\"\\]|\\.)*\"", "'***'", text)


def _iso_utc(ts: float | None) -> str | None:
    """Unix seconds -> ISO-8601 UTC string (SHOW INDEX INFO last_used)."""
    if not ts:
        return None
    import datetime
    return datetime.datetime.fromtimestamp(
        ts, datetime.timezone.utc).isoformat()


def _parse_period(text: str) -> float:
    """'500ms' / '2s' / '5m' / '1h' → seconds."""
    import re
    m = re.fullmatch(r"\s*(\d+(?:\.\d+)?)\s*(ms|s|m|h)?\s*", text)
    if not m:
        raise SemanticException(f"invalid period {text!r}")
    value = float(m.group(1))
    unit = m.group(2) or "s"
    return value * {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0}[unit]


def _chain_front(first_row, rest):
    yield first_row
    yield from rest


def _plan_operator_counts(plan) -> dict:
    """{operator class name: occurrences} over a plan tree."""
    counts: dict = {}

    def walk(op):
        if op is None:
            return
        counts[type(op).__name__] = counts.get(type(op).__name__, 0) + 1
        for child in op.children():
            walk(child)

    walk(plan)
    return counts


def _plan_has_batched_apply(plan) -> bool:
    from .plan import operators as Op
    found = False

    def walk(op):
        nonlocal found
        if op is None or found:
            return
        if isinstance(op, Op.Apply) and op.batch_rows:
            found = True
            return
        for child in op.children():
            walk(child)

    walk(plan)
    return found


def _plan_privileges(plan) -> set:
    """Privileges a plan requires (reference: per-clause privilege map)."""
    from .plan import operators as Op
    needed: set = set()

    def walk(op):
        if op is None:
            return
        if isinstance(op, (Op.ScanAll, Op.ScanAllByLabel,
                           Op.ScanAllByLabelPropertyValue,
                           Op.ScanAllByLabelPropertyRange, Op.ScanAllById,
                           Op.Expand, Op.ExpandVariable, Op.ExpandShortest,
                           Op.ExpandKShortest)):
            needed.add("MATCH")
        elif isinstance(op, (Op.CreateNode, Op.CreateExpand,
                             Op.BatchCreateGraph)):
            needed.add("CREATE")
        elif isinstance(op, Op.Merge):
            needed.update(("MERGE", "MATCH", "CREATE"))
        elif isinstance(op, Op.Delete):
            needed.add("DELETE")
        elif isinstance(op, (Op.SetProperty, Op.SetProperties,
                             Op.SetLabels)):
            needed.add("SET")
        elif isinstance(op, (Op.RemoveProperty, Op.RemoveLabels)):
            needed.add("REMOVE")
        elif isinstance(op, (Op.LoadCsvOp, Op.LoadJsonlOp,
                             Op.LoadParquetOp)):
            # reference: required_privileges.cpp:283-293 (READ_FILE for
            # LOAD CSV); file-reading operators must not run unprivileged.
            needed.add("READ_FILE")
        elif isinstance(op, Op.CallProcedureOp):
            from .procedures.registry import global_registry
            proc = global_registry.find(op.proc_name)
            needed.add("MODULE_WRITE" if proc is not None and proc.is_write
                       else "MODULE_READ")
        for child in op.children():
            walk(child)

    walk(plan)
    return needed


# privileges whose presence does NOT make a plan a write
_READ_ONLY_PRIVILEGES = frozenset({"MATCH", "MODULE_READ", "READ_FILE"})
