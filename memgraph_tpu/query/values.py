"""Cypher value semantics: ternary logic, equality, comparison, arithmetic.

The runtime value model is native Python (None/bool/int/float/str/list/dict,
temporal types, Point, VertexAccessor/EdgeAccessor/Path) — the counterpart of
the reference's TypedValue (/root/reference/src/query/typed_value.cpp) with
openCypher null-propagation rules.
"""

from __future__ import annotations

import math

from ..exceptions import ArithmeticException, TypeException
from ..storage.ordering import order_key
from ..storage.storage import EdgeAccessor, VertexAccessor
from ..utils.point import Point
from ..utils.temporal import (Date, Duration, LocalDateTime, LocalTime,
                              ZonedDateTime)

_TEMPORAL = (Date, Duration, LocalDateTime, LocalTime, ZonedDateTime)


class Path:
    """Alternating vertex/edge sequence produced by path patterns."""

    __slots__ = ("items",)

    def __init__(self, items: list) -> None:
        self.items = items  # [VertexAccessor, EdgeAccessor, Vertex..., ...]

    def vertices(self) -> list:
        return self.items[0::2]

    def edges(self) -> list:
        return self.items[1::2]

    def __len__(self) -> int:
        return len(self.items) // 2  # path length = edge count

    def __eq__(self, other):
        return isinstance(other, Path) and self.items == other.items

    def __hash__(self):
        return hash(tuple(self.items))

    def __repr__(self) -> str:  # pragma: no cover
        return f"Path({self.items})"


def is_numeric(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def cypher_eq(a, b):
    """Ternary equality: None if either side is null (or null inside lists)."""
    if a is None or b is None:
        return None
    if isinstance(a, bool) or isinstance(b, bool):
        if isinstance(a, bool) and isinstance(b, bool):
            return a == b
        return False
    if is_numeric(a) and is_numeric(b):
        return float(a) == float(b) if (isinstance(a, float)
                                        or isinstance(b, float)) else a == b
    if isinstance(a, str) and isinstance(b, str):
        return a == b
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        if len(a) != len(b):
            return False
        saw_null = False
        for x, y in zip(a, b):
            r = cypher_eq(x, y)
            if r is None:
                saw_null = True
            elif not r:
                return False
        return None if saw_null else True
    if isinstance(a, dict) and isinstance(b, dict):
        if set(a) != set(b):
            return False
        saw_null = False
        for k in a:
            r = cypher_eq(a[k], b[k])
            if r is None:
                saw_null = True
            elif not r:
                return False
        return None if saw_null else True
    if type(a) is type(b):
        return a == b
    if isinstance(a, _TEMPORAL) or isinstance(b, _TEMPORAL):
        return False
    if isinstance(a, (VertexAccessor, EdgeAccessor, Path)) or \
            isinstance(b, (VertexAccessor, EdgeAccessor, Path)):
        return False
    return False


def cypher_lt(a, b):
    """Ternary '<'. None on null or incomparable type mix."""
    if a is None or b is None:
        return None
    if is_numeric(a) and is_numeric(b):
        if isinstance(a, float) and math.isnan(a):
            return None
        if isinstance(b, float) and math.isnan(b):
            return None
        return a < b
    if isinstance(a, str) and isinstance(b, str) and not isinstance(a, bool):
        return a < b
    if isinstance(a, bool) and isinstance(b, bool):
        return a < b
    for cls in _TEMPORAL:
        if isinstance(a, cls) and isinstance(b, cls):
            return a < b
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return order_key(list(a)) < order_key(list(b))
    from ..storage.enums import EnumValue
    if (isinstance(a, EnumValue) and isinstance(b, EnumValue)
            and a.enum_name == b.enum_name):
        return a.position < b.position
    return None  # incomparable mix → null (openCypher comparability)


def cypher_add(a, b):
    if a is None or b is None:
        return None
    if isinstance(a, str) and isinstance(b, str):
        return a + b
    if isinstance(a, (list, tuple)):
        if isinstance(b, (list, tuple)):
            return list(a) + list(b)
        return list(a) + [b]
    if isinstance(b, (list, tuple)):
        return [a] + list(b)
    if is_numeric(a) and is_numeric(b):
        return a + b
    # temporal arithmetic
    try:
        result = a + b
        if result is not NotImplemented:
            return result
    except TypeError:
        pass
    raise TypeException(f"invalid '+' operands: {_tn(a)} and {_tn(b)}")


def cypher_sub(a, b):
    if a is None or b is None:
        return None
    if is_numeric(a) and is_numeric(b):
        return a - b
    try:
        result = a - b
        if result is not NotImplemented:
            return result
    except TypeError:
        pass
    raise TypeException(f"invalid '-' operands: {_tn(a)} and {_tn(b)}")


def cypher_mul(a, b):
    if a is None or b is None:
        return None
    if is_numeric(a) and is_numeric(b):
        return a * b
    raise TypeException(f"invalid '*' operands: {_tn(a)} and {_tn(b)}")


def cypher_div(a, b):
    if a is None or b is None:
        return None
    if is_numeric(a) and is_numeric(b):
        if isinstance(a, int) and isinstance(b, int):
            if b == 0:
                raise ArithmeticException("division by zero")
            q = abs(a) // abs(b)
            return q if (a >= 0) == (b >= 0) else -q  # truncate toward zero
        if b == 0:
            if a == 0:
                return math.nan
            return math.inf if a > 0 else -math.inf
        return a / b
    raise TypeException(f"invalid '/' operands: {_tn(a)} and {_tn(b)}")


def cypher_mod(a, b):
    if a is None or b is None:
        return None
    if is_numeric(a) and is_numeric(b):
        if b == 0:
            if isinstance(a, int) and isinstance(b, int):
                raise ArithmeticException("modulo by zero")
            return math.nan
        r = math.fmod(a, b)
        if isinstance(a, int) and isinstance(b, int):
            return int(r)
        return r
    raise TypeException(f"invalid '%' operands: {_tn(a)} and {_tn(b)}")


def cypher_pow(a, b):
    if a is None or b is None:
        return None
    if is_numeric(a) and is_numeric(b):
        return float(a) ** float(b)
    raise TypeException(f"invalid '^' operands: {_tn(a)} and {_tn(b)}")


def ternary_and(a, b):
    if a is False or b is False:
        return False
    if a is None or b is None:
        return None
    _require_bool(a), _require_bool(b)
    return True


def ternary_or(a, b):
    if a is True or b is True:
        return True
    if a is None or b is None:
        return None
    _require_bool(a), _require_bool(b)
    return False


def ternary_xor(a, b):
    if a is None or b is None:
        return None
    _require_bool(a), _require_bool(b)
    return a != b


def ternary_not(a):
    if a is None:
        return None
    _require_bool(a)
    return not a


def _require_bool(v):
    if not isinstance(v, bool):
        raise TypeException(f"expected boolean, got {_tn(v)}")


def _tn(v) -> str:
    if v is None:
        return "Null"
    return type(v).__name__


def type_name(v) -> str:
    """Cypher type name (for type() / valueType() style functions)."""
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "BOOLEAN"
    if isinstance(v, int):
        return "INTEGER"
    if isinstance(v, float):
        return "FLOAT"
    if isinstance(v, str):
        return "STRING"
    if isinstance(v, (list, tuple)):
        return "LIST"
    if isinstance(v, dict):
        return "MAP"
    if isinstance(v, VertexAccessor):
        return "NODE"
    if isinstance(v, EdgeAccessor):
        return "RELATIONSHIP"
    if isinstance(v, Path):
        return "PATH"
    if isinstance(v, Date):
        return "DATE"
    if isinstance(v, LocalTime):
        return "LOCAL_TIME"
    if isinstance(v, LocalDateTime):
        return "LOCAL_DATE_TIME"
    if isinstance(v, ZonedDateTime):
        return "ZONED_DATE_TIME"
    if isinstance(v, Duration):
        return "DURATION"
    if isinstance(v, Point):
        return "POINT"
    return type(v).__name__.upper()


def hashable_key(v):
    """Key usable for DISTINCT / grouping (lists→tuples, maps→sorted tuples)."""
    if isinstance(v, list):
        return ("__list__", tuple(hashable_key(x) for x in v))
    if isinstance(v, dict):
        return ("__map__", tuple(sorted((k, hashable_key(x))
                                        for k, x in v.items())))
    if isinstance(v, float) and not isinstance(v, bool) and v.is_integer() \
            and abs(v) < 2 ** 63:
        return int(v)  # 1.0 groups with 1
    return v
