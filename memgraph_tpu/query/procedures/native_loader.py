"""Loader for native (C ABI) query modules.

Counterpart of the reference's dlopen module loading
(/root/reference/src/query/procedure/module.cpp:861): shared libraries
implementing `mgtpu_init_module` (native/mg_procedure.h) are loaded via
ctypes, handed a vtable of host callbacks, and register procedures that
compute over the zero-copy CSR snapshot view.
"""

from __future__ import annotations

import ctypes
import logging
from typing import Optional

import numpy as np

from .registry import Procedure, global_registry

log = logging.getLogger(__name__)


class _CsrView(ctypes.Structure):
    _fields_ = [
        ("n_nodes", ctypes.c_int64),
        ("n_edges", ctypes.c_int64),
        ("n_pad", ctypes.c_int64),
        ("e_pad", ctypes.c_int64),
        ("row_ptr", ctypes.POINTER(ctypes.c_int32)),
        ("col_idx", ctypes.POINTER(ctypes.c_int32)),
        ("csr_src", ctypes.POINTER(ctypes.c_int32)),
        ("weights", ctypes.POINTER(ctypes.c_float)),
        ("csc_src", ctypes.POINTER(ctypes.c_int32)),
        ("csc_dst", ctypes.POINTER(ctypes.c_int32)),
        ("node_gids", ctypes.POINTER(ctypes.c_int64)),
    ]


PROC_CB = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.POINTER(_CsrView),
                           ctypes.c_void_p, ctypes.c_void_p)

_REGISTER = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p,
                             ctypes.c_char_p, PROC_CB, ctypes.c_char_p)
_NEW_RECORD = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p)
_SET_INT = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p,
                            ctypes.c_char_p, ctypes.c_int64)
_SET_DOUBLE = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p,
                               ctypes.c_char_p, ctypes.c_double)
_SET_STRING = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p,
                               ctypes.c_char_p, ctypes.c_char_p)
_SET_NODE = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p,
                             ctypes.c_char_p, ctypes.c_int64)
_SET_ERROR = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p,
                              ctypes.c_char_p)


class _HostApi(ctypes.Structure):
    _fields_ = [
        ("register_procedure", _REGISTER),
        ("result_new_record", _NEW_RECORD),
        ("result_set_int", _SET_INT),
        ("result_set_double", _SET_DOUBLE),
        ("result_set_string", _SET_STRING),
        ("result_set_node", _SET_NODE),
        ("result_set_error", _SET_ERROR),
    ]


class _ResultCollector:
    """Backs the opaque mgtpu_result handle during one procedure call."""

    def __init__(self) -> None:
        self.rows: list[dict] = []
        self.error: Optional[str] = None

    def new_record(self) -> None:
        self.rows.append({})

    def set(self, field: str, value) -> None:
        if not self.rows:
            self.rows.append({})
        self.rows[-1][field] = value


# live result collectors keyed by handle id (the void* we pass to C)
_ACTIVE_RESULTS: dict[int, _ResultCollector] = {}
_NEXT_HANDLE = [1]

# keep callback objects and loaded libs alive for the process lifetime
_KEEPALIVE: list = []


def _collector(handle) -> Optional[_ResultCollector]:
    return _ACTIVE_RESULTS.get(int(handle or 0))


def _make_host_api() -> _HostApi:
    def new_record(handle):
        c = _collector(handle)
        if c is None:
            return 1
        c.new_record()
        return 0

    def set_int(handle, field, value):
        c = _collector(handle)
        if c is None:
            return 1
        c.set(field.decode(), int(value))
        return 0

    def set_double(handle, field, value):
        c = _collector(handle)
        if c is None:
            return 1
        c.set(field.decode(), float(value))
        return 0

    def set_string(handle, field, value):
        c = _collector(handle)
        if c is None:
            return 1
        c.set(field.decode(), value.decode() if value else "")
        return 0

    def set_node(handle, field, idx):
        c = _collector(handle)
        if c is None:
            return 1
        c.set(field.decode(), ("__node_index__", int(idx)))
        return 0

    def set_error(handle, message):
        c = _collector(handle)
        if c is None:
            return 1
        c.error = message.decode() if message else "native module error"
        return 0

    def register(registry_handle, name, cb, results_sig):
        try:
            name_s = name.decode()
            results = []
            for part in (results_sig.decode() if results_sig else "").split(","):
                part = part.strip()
                if not part:
                    continue
                fname, _, ftype = part.partition(":")
                results.append((fname.strip(), ftype.strip() or "ANY"))
            _KEEPALIVE.append(cb)
            global_registry.register(Procedure(
                name=name_s, func=_make_proc_func(cb, results),
                args=[], opt_args=[], results=results, is_write=False))
            return 0
        except Exception:
            log.exception("native procedure registration failed")
            return 1

    api = _HostApi(
        register_procedure=_REGISTER(register),
        result_new_record=_NEW_RECORD(new_record),
        result_set_int=_SET_INT(set_int),
        result_set_double=_SET_DOUBLE(set_double),
        result_set_string=_SET_STRING(set_string),
        result_set_node=_SET_NODE(set_node),
        result_set_error=_SET_ERROR(set_error),
    )
    _KEEPALIVE.append(api)
    return api


def _p32(a):
    return np.ascontiguousarray(a, dtype=np.int32).ctypes.data_as(
        ctypes.POINTER(ctypes.c_int32))


def _make_proc_func(cb, results):
    node_fields = {f for f, t in results if t.upper() == "NODE"}

    def proc(pctx, *args):
        from ...exceptions import ProcedureException
        graph = pctx.device_graph()
        # host-resident contiguous copies (zero-copy for the C side)
        row_ptr = np.ascontiguousarray(np.asarray(graph.row_ptr),
                                       dtype=np.int32)
        col_idx = np.ascontiguousarray(np.asarray(graph.col_idx),
                                       dtype=np.int32)
        csr_src = np.ascontiguousarray(np.asarray(graph.src_idx),
                                       dtype=np.int32)
        weights = np.ascontiguousarray(np.asarray(graph.weights),
                                       dtype=np.float32)
        csc_src = np.ascontiguousarray(np.asarray(graph.csc_src),
                                       dtype=np.int32)
        csc_dst = np.ascontiguousarray(np.asarray(graph.csc_dst),
                                       dtype=np.int32)
        node_gids = np.ascontiguousarray(graph.node_gids, dtype=np.int64)
        view = _CsrView(
            n_nodes=graph.n_nodes, n_edges=graph.n_edges,
            n_pad=graph.n_pad, e_pad=graph.e_pad,
            row_ptr=row_ptr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            col_idx=col_idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            csr_src=csr_src.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            weights=weights.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            csc_src=csc_src.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            csc_dst=csc_dst.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            node_gids=node_gids.ctypes.data_as(
                ctypes.POINTER(ctypes.c_int64)),
        )
        collector = _ResultCollector()
        handle = _NEXT_HANDLE[0]
        _NEXT_HANDLE[0] += 1
        _ACTIVE_RESULTS[handle] = collector
        try:
            rc = cb(ctypes.byref(view), ctypes.c_void_p(handle), None)
        finally:
            _ACTIVE_RESULTS.pop(handle, None)
        if rc != 0 or collector.error:
            raise ProcedureException(
                collector.error or f"native procedure failed (rc={rc})")
        for row in collector.rows:
            out = {}
            for key, value in row.items():
                if (key in node_fields and isinstance(value, tuple)
                        and value and value[0] == "__node_index__"):
                    out[key] = pctx.vertex_by_index(graph, value[1])
                else:
                    out[key] = value
            yield out

    return proc


def load_native_module(path: str) -> bool:
    """dlopen a native module and run its registration. Returns success."""
    try:
        lib = ctypes.CDLL(path)
    except OSError as e:
        log.error("cannot load native module %s: %s", path, e)
        return False
    try:
        init = lib.mgtpu_init_module
    except AttributeError:
        log.error("%s does not export mgtpu_init_module", path)
        return False
    init.restype = ctypes.c_int
    init.argtypes = [ctypes.POINTER(_HostApi), ctypes.c_void_p]
    api = _make_host_api()
    rc = init(ctypes.byref(api), None)
    if rc != 0:
        log.error("native module %s init returned %d", path, rc)
        return False
    _KEEPALIVE.append(lib)
    return True
