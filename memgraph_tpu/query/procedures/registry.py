"""Query-module procedure registry (the mgp-equivalent boundary).

Counterpart of the reference's ModuleRegistry + mgp API
(/root/reference/src/query/procedure/module.cpp:61,811 and include/mgp.py):
procedures are registered under dotted names ("pagerank.get"), declare
result fields, and stream result records. Python modules register with the
@read_proc / @write_proc decorators (memgraph_tpu.procedures.mgp); the
builtin TPU analytics modules live in memgraph_tpu.procedures.*.

The ProcedureContext handed to implementations exposes the storage accessor
AND the device graph cache — the mgp_graph → CSR DeviceArray seam
(SURVEY.md §3.4).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional


@dataclass
class Procedure:
    name: str                              # full dotted name
    func: Callable                         # (ProcedureContext, *args) -> iter
    args: list[tuple[str, str]]            # (name, type hint)
    opt_args: list[tuple[str, str, object]]
    results: list[tuple[str, str]]         # (field, type hint)
    is_write: bool = False
    # VOID procs run for their side effects and pass the input row through;
    # a proc declared ':: ()' instead yields an empty record stream
    # (openCypher TCK distinction, ProcedureCallAcceptance)
    void: bool = False

    def call(self, exec_ctx, args: list) -> Iterable[dict]:
        pctx = ProcedureContext(exec_ctx)
        return self.func(pctx, *args)


class ProcedureContext:
    """What a procedure sees: graph access + device snapshot export."""

    def __init__(self, exec_ctx) -> None:
        self.exec_ctx = exec_ctx
        self.accessor = exec_ctx.accessor
        self.storage = exec_ctx.accessor.storage
        self.view = exec_ctx.view

    def device_graph(self, weight_property: Optional[str] = None,
                     label: Optional[str] = None,
                     edge_types: Optional[list[str]] = None):
        """Export (or fetch cached) CSR DeviceGraph for the current graph."""
        from ...ops.csr import GLOBAL_GRAPH_CACHE
        wp = None
        if weight_property is not None:
            wp = self.storage.property_mapper.maybe_name_to_id(weight_property)
        lf = None
        if label is not None:
            lf = self.storage.label_mapper.maybe_name_to_id(label)
        etf = None
        if edge_types:
            etf = {self.storage.edge_type_mapper.maybe_name_to_id(t)
                   for t in edge_types}
            etf.discard(None)
        return GLOBAL_GRAPH_CACHE.get(self.accessor, weight_property=wp,
                                      label_filter=lf, edge_type_filter=etf)

    def vertex_by_index(self, graph, idx: int):
        """Dense device index -> VertexAccessor."""
        gid = int(graph.node_gids[idx])
        return self.accessor.find_vertex(gid, self.view)

    def vertices_by_indices(self, graph, indices):
        return [self.vertex_by_index(graph, int(i)) for i in indices]


class ProcedureRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._procedures: dict[str, Procedure] = {}
        self._loaded_builtin = False

    def register(self, proc: Procedure) -> None:
        with self._lock:
            self._procedures[proc.name.lower()] = proc

    def unregister(self, name: str) -> None:
        with self._lock:
            self._procedures.pop(name.lower(), None)

    def find(self, name: str) -> Optional[Procedure]:
        self._ensure_builtin()
        proc = self._procedures.get(name.lower())
        if proc is None:
            target = getattr(self, "_aliases", {}).get(name.lower())
            if target:
                proc = self._procedures.get(target.lower())
        return proc

    def load_callable_mappings(self, path: str) -> int:
        """JSON {alias: canonical-procedure-name} — lets Neo4j-style
        CALL names resolve to local implementations (reference:
        --query-callable-mappings-path)."""
        import json
        with open(path, encoding="utf-8") as f:
            mappings = json.load(f)
        if not isinstance(mappings, dict):
            raise ValueError("callable mappings must be a JSON object")
        with self._lock:
            aliases = getattr(self, "_aliases", None)
            if aliases is None:
                aliases = self._aliases = {}
            for alias, target in mappings.items():
                aliases[str(alias).lower()] = str(target)
        return len(mappings)

    def all_procedures(self) -> list[Procedure]:
        self._ensure_builtin()
        return sorted(self._procedures.values(), key=lambda p: p.name)

    def _ensure_builtin(self) -> None:
        if self._loaded_builtin:
            return
        with self._lock:
            if self._loaded_builtin:
                return
            self._loaded_builtin = True
        # import for side effect: modules register their procedures
        from ...procedures import load_builtin_modules
        load_builtin_modules()

    def load_directory(self, path: str) -> list[str]:
        """Load user query modules (*.py and native *.so) from a directory
        (the reference's module dir scan, module.cpp:811)."""
        import importlib.util
        import os
        loaded = []
        if not os.path.isdir(path):
            return loaded
        for fname in sorted(os.listdir(path)):
            full = os.path.join(path, fname)
            if fname.endswith(".py") and not fname.startswith("_"):
                mod_name = fname[:-3]
                spec = importlib.util.spec_from_file_location(
                    f"mg_user_module_{mod_name}", full)
                module = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(module)
                loaded.append(mod_name)
            elif fname.endswith(".so"):
                from .native_loader import load_native_module
                if load_native_module(full):
                    loaded.append(fname)
        return loaded


global_registry = ProcedureRegistry()
