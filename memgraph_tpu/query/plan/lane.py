"""mglane: compile hot Cypher read pipelines onto the semiring core.

Plan-lowering pass that runs AFTER the columnar rewrite
(query/plan/parallel.py). Eligible read-pipeline tails —

    label/property filter -> [1-2 hop expand] -> count/sum/min/max
    label/property filter -> ORDER BY <int key> LIMIT k

— are lowered onto the compiled-lane operators below, whose cursors
dispatch ONE jitted XLA program (ops/pipeline.py) per recognized shape:
predicate masks become columnar int32 compares, expansion becomes a
masked ``plus_first`` SpMV chain over the semiring core (GraphBLAST),
and the aggregate/top-k epilogue fuses into the same program.

Layering (each stage is the exact degeneracy of the one above):

    compiled device program          (this module + ops/pipeline.py)
      -> host columnar kernels       (ParallelScanAggregate et al.)
        -> row-at-a-time Volcano     (the original subplan)

Every step down is LOUD: a typed reason is counted per plan-cache
fingerprint (``lane.fallback_total.<reason>``; per-fingerprint table in
``GET /stats`` -> ``lane``) — and CORRECT: the host paths own the exact
semantics, so a refused shape never changes results.

Fallback taxonomy (docs/architecture.md §Compiled read lane):
  shape-level   group_by, agg_avg/agg_<kind>, remember, multi_key,
                edge_prop, dynamic_predicate, direction, edge_type_mix
  data-level    float_column, float_rhs, big_int, column_kind,
                str_order, vocab_miss, null_rhs, type_mismatch,
                topk_precision, precision_overflow
  state-level   mvcc_private, small_input, small_frontier,
                columnar_unsupported, remote_error

Compilation is keyed by the mgstat plan-cache fingerprint (PR 9):
``InterpreterContext.cached_plan`` stamps it onto every lane operator
(``bind_fingerprints``), each distinct shape compiles ONCE (the witness
is the per-fingerprint compile counter plus ``jit.compile_total``), and
schema changes (index/constraint DDL, ANALYZE) drop every compiled lane
through the same ``invalidate_plans`` hook that drops cached plans.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass, fields
from typing import Optional

import numpy as np

from ...ops.columnar import COLUMNAR_CACHE
from ..frontend import ast as A
from . import operators as Op
from .parallel import (ParallelExpandAggregate, ParallelOrderedScan,
                       ParallelScanAggregate, _as_predicate, _gid_rows,
                       _pred_mask, _split_and, _Unsupported)

log = logging.getLogger(__name__)

DISABLE_ENV = "MEMGRAPH_TPU_DISABLE_LANE"
REMOTE_ENV = "MEMGRAPH_TPU_LANE_REMOTE"

_DEVICE_AGGS = ("count", "sum", "min", "max")


def _lane_min_rows() -> int:
    """Read per-call so tests/benches can tune without re-imports."""
    from ...ops import pipeline
    try:
        return int(os.environ.get("MEMGRAPH_TPU_LANE_MIN_ROWS",
                                  pipeline.LANE_MIN_ROWS))
    except ValueError:
        return pipeline.LANE_MIN_ROWS


def _registry():
    from ...ops import pipeline
    return pipeline.LANE_REGISTRY


def _note_fallback(fingerprint, reason: str, detail: str = "") -> None:
    """LOUD, typed: counted per fingerprint + debug-logged."""
    _registry().note_fallback(fingerprint, reason)
    log.debug("lane fallback (%s) fp=%s %s", reason, fingerprint, detail)


# --------------------------------------------------------------------------
# predicate admission (host semantics -> device spec)
# --------------------------------------------------------------------------


def _device_pred(col, op: str, rhs):
    """Mirror of parallel._pred_mask admission: returns the int32 rhs
    for a device compare, or raises LaneRefused with the typed reason
    routing this query to the host path (which owns the exact
    semantics for every refused case)."""
    from ...ops import pipeline as pl
    if rhs is None:
        raise pl.LaneRefused("null_rhs")
    if col.kind == "other":
        if not col.present.any():
            # vacuous column (property absent everywhere): the fused
            # presence mask alone excludes every row, any rhs works
            return 0
        raise pl.LaneRefused("column_kind")
    if isinstance(rhs, bool):
        if col.kind != "bool":
            raise pl.LaneRefused("type_mismatch")
        return 1 if rhs else 0
    if isinstance(rhs, int):
        if col.kind != "int":
            raise pl.LaneRefused("type_mismatch" if col.kind != "float"
                                 else "float_column")
        if not -(2**31) < rhs < 2**31 or col.big \
                or pl.i32_column(col) is None:
            raise pl.LaneRefused("big_int")
        return rhs
    if isinstance(rhs, float):
        raise pl.LaneRefused("float_rhs")
    if isinstance(rhs, str):
        if col.kind != "str":
            raise pl.LaneRefused("type_mismatch")
        if op not in ("=", "<>"):
            raise pl.LaneRefused("str_order")
        code = col.vocab.get(rhs)
        if code is None:
            raise pl.LaneRefused("vocab_miss")
        return int(code)
    raise pl.LaneRefused("rhs_kind")


def _stack_columns(snap, needed: list):
    """Stack the needed columns as (C, n) int32 values + bool presence;
    ``needed`` maps prop name -> column. Count-only columns ("other"
    kinds) contribute presence with zero values."""
    from ...ops import pipeline as pl
    n = snap.n
    vals = np.zeros((len(needed), n), dtype=np.int32)
    present = np.zeros((len(needed), n), dtype=bool)
    index: dict[str, int] = {}
    for i, (prop, need_values) in enumerate(needed):
        col = snap.columns[prop]
        index[prop] = i
        present[i] = col.present
        if need_values:
            v = pl.i32_column(col)
            if v is None:
                raise pl.LaneRefused(
                    "float_column" if col.kind == "float" else
                    ("big_int" if col.kind == "int" else "column_kind"))
            vals[i] = v
        elif col.values is not None:
            v = pl.i32_column(col)
            if v is not None:
                vals[i] = v
    return vals, present, index


# --------------------------------------------------------------------------
# compiled scan / expand aggregate
# --------------------------------------------------------------------------


class _LaneAggMixin:
    """Device-first cursor shared by the scan and expand aggregates."""

    def cursor(self, ctx):
        from ...ops import pipeline as pl
        row = None
        ok = False
        try:
            row = self._device_row(ctx)
            ok = True
        except pl.LaneRefused as e:
            _note_fallback(self.fingerprint, e.reason, str(e))
        except _Unsupported:
            _note_fallback(self.fingerprint, "columnar_unsupported")
        if ok:
            _registry().note_hit(self.fingerprint)
            yield row
            return
        yield from super().cursor(ctx)

    def _device_row(self, ctx) -> dict:
        from ...ops import pipeline as pl
        if self.group_by:
            raise pl.LaneRefused("group_by")
        for kind, _prop, _name in self.aggregations:
            if kind not in _DEVICE_AGGS:
                raise pl.LaneRefused(f"agg_{kind}")
        if not COLUMNAR_CACHE._cacheable(ctx.accessor):
            raise pl.LaneRefused("mvcc_private")
        snap, base = self._snapshot_base(ctx)
        if snap.n < _lane_min_rows() and not self.hinted:
            raise pl.LaneRefused("small_input")

        # admission first (host semantics decide the typed reason),
        # then one fused device program over the stacked columns
        rhs_values = []
        for prop, op, rhs_expr in self.predicates:
            rhs = ctx.evaluator.eval(rhs_expr, {})
            rhs_values.append(_device_pred(snap.columns[prop], op, rhs))

        needed: list = []
        order: dict[str, int] = {}

        def need(prop, values_needed):
            if prop in order:
                if values_needed and not needed[order[prop]][1]:
                    needed[order[prop]] = (prop, True)
                return
            order[prop] = len(needed)
            needed.append((prop, values_needed))

        for prop, _op, _rhs in self.predicates:
            need(prop, snap.columns[prop].kind != "other")
        for kind, prop, _name in self.aggregations:
            if prop is None:
                continue
            if kind != "count":
                # sum/min/max: the row path aggregates NUMERICS only
                # (min over strings etc. is the row fallback's job) —
                # and the device lane's exactness discipline admits
                # int32 of those; floats go to the host columnar path
                ckind = snap.columns[prop].kind
                if ckind != "int":
                    raise pl.LaneRefused(
                        "float_column" if ckind == "float"
                        else "column_kind")
            need(prop, kind != "count")
        vals, present, index = _stack_columns(snap, needed)

        preds = tuple((index[prop], op)
                      for prop, op, _ in self.predicates)
        aggs = tuple((kind, index[prop] if prop is not None else None)
                     for kind, prop, _ in self.aggregations)
        if base is None:
            base = np.ones(snap.n, dtype=bool)
        out = pl.masked_aggregate(preds, aggs, vals, present, base,
                                  rhs_values,
                                  fingerprint=self.fingerprint)
        row = {}
        for (kind, _prop, name), value in zip(self.aggregations, out):
            if kind == "sum" and value is None:
                value = 0
            row[name] = value
        return row


@dataclass
class ParallelScanAggregateLane(_LaneAggMixin, ParallelScanAggregate):
    """Device-first ParallelScanAggregate (class name extends the base
    so EXPLAIN/operator counters keep their established vocabulary)."""
    fingerprint: Optional[str] = None


@dataclass
class ParallelExpandAggregateLane(_LaneAggMixin, ParallelExpandAggregate):
    fingerprint: Optional[str] = None


# --------------------------------------------------------------------------
# compiled 1-2 hop counts (masked plus_first SpMV chain)
# --------------------------------------------------------------------------


@dataclass
class LaneHopCount(Op.LogicalOperator):
    """Aggregate <- [Filter] <- 1-2 hop expand <- [Filter] <- Scan,
    where every aggregation is a path/row count — lowered to a masked
    frontier SpMV chain with the self-loop edge-uniqueness correction
    (count(DISTINCT target) is the reachability popcount epilogue)."""
    input: Op.LogicalOperator            # Once
    fallback: Op.LogicalOperator         # the original Aggregate subplan
    source: tuple                        # ("label", l) | ("all",) |
    #                                      ("label_prop_eq", l, p, expr)
    src_label: Optional[str]
    src_preds: list
    mid_label: Optional[str]
    mid_preds: list
    dst_label: Optional[str]
    dst_preds: list
    direction: str                       # out | in
    edge_types: Optional[list]
    hops: int
    include_lower: bool
    edge_unique: bool
    row_aggs: list                       # output names: plain counts
    distinct_aggs: list                  # output names: count(DISTINCT m)
    hinted: bool = False
    fingerprint: Optional[str] = None

    def cursor(self, ctx):
        from ...ops import pipeline as pl
        row = None
        ok = False
        try:
            row = self._device_row(ctx)
            ok = True
        except pl.LaneRefused as e:
            _note_fallback(self.fingerprint, e.reason, str(e))
        except _Unsupported:
            _note_fallback(self.fingerprint, "columnar_unsupported")
        if ok:
            _registry().note_hit(self.fingerprint)
            yield row
            return
        yield from self.fallback.cursor(ctx)

    # -- device path -------------------------------------------------------

    def _role_mask(self, ctx, full, f_sorted, f_order, label, preds,
                   as_float: bool):
        """Predicate/label mask for one pattern role, lifted into the
        full-vertex index space (host _pred_mask semantics: exact)."""
        n = full.n
        if label is None and not preds:
            return (np.ones(n, dtype=np.float32) if as_float
                    else np.ones(n, dtype=bool))
        props = tuple(sorted({p for p, _, _ in preds}))
        snap = COLUMNAR_CACHE.get(ctx.accessor, label, props, ctx.view,
                                  abort_check=ctx.check_abort)
        mask = np.ones(snap.n, dtype=bool)
        for prop, op, rhs_expr in preds:
            mask &= _pred_mask(ctx, snap, prop, op, rhs_expr)
        rows = _gid_rows(f_sorted, f_order, snap.gids)
        sel = mask & (rows >= 0)
        out = np.zeros(n, dtype=np.float32 if as_float else bool)
        out[rows[sel]] = 1.0 if as_float else True
        return out

    def _device_row(self, ctx) -> dict:
        from ...ops import pipeline as pl
        if not COLUMNAR_CACHE._cacheable(ctx.accessor):
            raise pl.LaneRefused("mvcc_private")
        if self.source[0] == "label_prop_eq" and not self.hinted:
            # a point source expands O(degree^2) rows; the device sweep
            # is O(E) — the row path IS the fast path here
            raise pl.LaneRefused("small_frontier")
        acc = ctx.accessor
        edges = COLUMNAR_CACHE.get_edges(acc, (), ctx.view,
                                         abort_check=ctx.check_abort)
        ctx.check_abort()
        if edges.n < _lane_min_rows() and not self.hinted:
            raise pl.LaneRefused("small_input")
        full = COLUMNAR_CACHE.get(acc, None, (), ctx.view,
                                  abort_check=ctx.check_abort)
        ctx.check_abort()

        # per-version staging, cached on the snapshots themselves
        f_order = getattr(full, "_lane_order", None)
        if f_order is None:
            f_order = np.argsort(full.gids, kind="stable")
            full._lane_order = f_order
            full._lane_sorted = full.gids[f_order]
        f_sorted = full._lane_sorted
        endpoints = getattr(edges, "_lane_endpoints", None)
        if endpoints is None:
            s_idx = _gid_rows(f_sorted, f_order, edges.src)
            d_idx = _gid_rows(f_sorted, f_order, edges.dst)
            endpoints = (s_idx.astype(np.int32), d_idx.astype(np.int32),
                         (s_idx >= 0) & (d_idx >= 0))
            edges._lane_endpoints = endpoints
        s_idx, d_idx, ep_ok = endpoints

        emask = ep_ok
        tkey = tuple(sorted(self.edge_types or ()))
        if self.edge_types:
            cache = getattr(edges, "_lane_typemask", None)
            if cache is None:
                cache = edges._lane_typemask = {}
            tmask_e = cache.get(tkey)
            if tmask_e is None:
                ids = [tid for tid in
                       (ctx.storage.edge_type_mapper.maybe_name_to_id(t)
                        for t in self.edge_types) if tid is not None]
                tmask_e = np.isin(edges.type_ids,
                                  np.asarray(ids, dtype=np.int32))
                cache[tkey] = tmask_e
            emask = emask & tmask_e

        src_preds = list(self.src_preds)
        if self.source[0] == "label_prop_eq":
            src_preds.append((self.source[2], "=", self.source[3]))
        smask = self._role_mask(ctx, full, f_sorted, f_order,
                                self.src_label, src_preds, False)
        midmask = self._role_mask(ctx, full, f_sorted, f_order,
                                  self.mid_label, self.mid_preds, True)
        tmask = self._role_mask(ctx, full, f_sorted, f_order,
                                self.dst_label, self.dst_preds, True)
        if self.direction == "in":
            s_idx, d_idx = d_idx, s_idx

        kwargs = dict(hops=self.hops, include_lower=self.include_lower,
                      edge_unique=self.edge_unique,
                      need_rows=bool(self.row_aggs),
                      need_distinct=bool(self.distinct_aggs))
        if os.environ.get(REMOTE_ENV):
            totals = self._remote(s_idx, d_idx, emask, smask, midmask,
                                  tmask, full.n, kwargs)
        else:
            # edge arrays stay device-resident per (version, types,
            # direction): repeat queries move only the O(n) masks
            staged_cache = getattr(edges, "_lane_staged", None)
            if staged_cache is None:
                staged_cache = edges._lane_staged = {}
            skey = (tkey, self.direction)
            staged = staged_cache.get(skey)
            if staged is None:
                staged = pl.stage_edges(s_idx, d_idx, emask)
                staged_cache[skey] = staged
            totals = pl.hop_counts(staged[0], staged[1], staged[2],
                                   smask, midmask, tmask, full.n,
                                   fingerprint=self.fingerprint,
                                   **kwargs)
        row = {}
        for name in self.row_aggs:
            row[name] = totals["rows"]
        for name in self.distinct_aggs:
            row[name] = totals["distinct"]
        return row

    def _remote(self, s_idx, d_idx, emask, smask, midmask, tmask,
                n_nodes, kwargs) -> dict:
        """Dispatch the hop-count program through the kernel server
        (the same resident device plane every analytics op rides)."""
        from ...ops import pipeline as pl
        from ...server import kernel_server as ks
        try:
            client = ks.shared_client(spawn=True)
            return client.lane_hops(
                s_idx, d_idx, emask, smask, midmask, tmask,
                n_nodes=n_nodes, **kwargs)
        except pl.LaneRefused:
            raise
        except Exception as e:  # noqa: BLE001 — typed, loud fallback
            raise pl.LaneRefused("remote_error",
                                 f"{type(e).__name__}: {e}")


# --------------------------------------------------------------------------
# compiled top-k ORDER BY
# --------------------------------------------------------------------------


@dataclass
class ParallelOrderedScanLane(ParallelOrderedScan):
    """ParallelOrderedScan whose order is computed by one fused
    mask+stable-argsort device program (only instantiated under LIMIT,
    where lazy pulling makes the sort a top-k)."""
    fingerprint: Optional[str] = None

    def _columnar_order(self, ctx):
        from ...ops import pipeline as pl
        try:
            return self._device_order(ctx)
        except pl.LaneRefused as e:
            _note_fallback(self.fingerprint, e.reason, str(e))
            return super()._columnar_order(ctx)

    def _device_order(self, ctx):
        from ...ops import pipeline as pl
        if len(self.keys) != 1:
            raise pl.LaneRefused("multi_key")
        if not COLUMNAR_CACHE._cacheable(ctx.accessor):
            raise pl.LaneRefused("mvcc_private")
        props = tuple(sorted({p for p, _, _ in self.predicates}
                             | {p for p, _ in self.keys}))
        snap = COLUMNAR_CACHE.get(ctx.accessor, self.label, props,
                                  ctx.view, abort_check=ctx.check_abort)
        ctx.check_abort()
        if snap.n < _lane_min_rows() and not self.hinted:
            raise pl.LaneRefused("small_input")
        key_prop, asc = self.keys[0]
        kcol = snap.columns.get(key_prop)
        if kcol is None or kcol.kind != "int":
            raise pl.LaneRefused("topk_precision"
                                 if kcol is not None and
                                 kcol.kind == "float" else "column_kind")
        kv = pl.i32_column(kcol)
        if kv is None:
            raise pl.LaneRefused("big_int")
        f24ok = getattr(kcol, "_lane_f24ok", None)
        if f24ok is None:
            sel = kv[kcol.present]
            f24ok = bool(sel.size == 0
                         or int(np.abs(sel).max()) < (1 << 24))
            kcol._lane_f24ok = f24ok
        if not f24ok:
            raise pl.LaneRefused("topk_precision")

        rhs_values = []
        for prop, op, rhs_expr in self.predicates:
            rhs = ctx.evaluator.eval(rhs_expr, {})
            rhs_values.append(_device_pred(snap.columns[prop], op, rhs))
        needed = []
        order_map: dict = {}
        for prop, _op, _rhs in self.predicates:
            if prop not in order_map:
                order_map[prop] = len(needed)
                needed.append((prop, snap.columns[prop].kind != "other"))
        vals, present, index = _stack_columns(snap, needed)
        preds = tuple((index[prop], op)
                      for prop, op, _ in self.predicates)
        order, count = pl.masked_topk(
            preds, asc, vals, present, kv, kcol.present, rhs_values,
            fingerprint=self.fingerprint)
        _registry().note_hit(self.fingerprint)
        order = order[order < snap.n][:count]
        return order, snap.gids


# --------------------------------------------------------------------------
# plan rewrite
# --------------------------------------------------------------------------


def _clone_as(cls, op, fingerprint=None):
    kw = {f.name: getattr(op, f.name) for f in fields(op)}
    kw["fingerprint"] = fingerprint
    return cls(**kw)


def _scan_source(node):
    """Scan leaf -> (source descriptor, label) or None."""
    if isinstance(node, Op.ScanAllByLabel):
        return ("label", node.label), node.label
    if isinstance(node, Op.ScanAll):
        return ("all",), None
    if isinstance(node, Op.ScanAllByLabelPropertyValue) \
            and len(node.properties) == 1:
        return (("label_prop_eq", node.label, node.properties[0],
                 node.value_exprs[0]), node.label)
    return None


def _match_hops(agg: Op.Aggregate, hinted: bool):
    """Match the 1-2 hop count tails the columnar expand collapse does
    not claim. Returns a LaneHopCount or None; near-misses (shape
    matched, feature refused) are counted as plan-time fallbacks."""
    if agg.remember or agg.group_by:
        return None

    def filters_of(node):
        out = []
        while isinstance(node, Op.Filter):
            out.append(node.expr)
            node = node.input
        return out, node

    upper, node = filters_of(agg.input)
    expands = []
    mid_filters: list = []
    if isinstance(node, Op.ExpandVariable):
        ev = node
        if ev.filter_lambda is not None or ev.prev_edge_symbols:
            return None
        if ev.direction not in ("out", "in"):
            return None
        if ev.from_symbol == ev.to_symbol:
            return None       # (a)-[*..]->(a): dst-bound constraint
        span = (ev.min_hops, ev.max_hops)
        if span not in ((1, 1), (2, 2), (1, 2)):
            return None
        hops = span[1]
        include_lower = span == (1, 2)
        edge_unique = True
        syms = {"src": ev.from_symbol, "mid": None, "dst": ev.to_symbol,
                "edges": {ev.edge_symbol}}
        direction = ev.direction
        edge_types = list(ev.edge_types or [])
        node = ev.input
    elif isinstance(node, Op.Expand) and type(node) is Op.Expand:
        e2 = node
        inner, node = filters_of(e2.input)
        if isinstance(node, Op.Expand) and type(node) is Op.Expand:
            e1 = node
            if e1.direction != e2.direction \
                    or e1.direction not in ("out", "in"):
                return None
            if e2.from_symbol != e1.to_symbol:
                return None
            named = {e1.from_symbol, e1.to_symbol, e2.to_symbol}
            if len(named) != 3 or e1.edge_symbol == e2.edge_symbol:
                return None
            if sorted(e1.edge_types or []) != sorted(e2.edge_types
                                                     or []):
                _registry().note_fallback(None, "edge_type_mix")
                return None
            hops, include_lower = 2, False
            edge_unique = e1.edge_symbol in (e2.prev_edge_symbols or [])
            syms = {"src": e1.from_symbol, "mid": e1.to_symbol,
                    "dst": e2.to_symbol,
                    "edges": {e1.edge_symbol, e2.edge_symbol}}
            direction = e1.direction
            edge_types = list(e1.edge_types or [])
            mid_filters = inner
            node = e1.input
        else:
            # single-hop counts normally ride the columnar expand
            # collapse; claim the leftovers here
            if e2.direction not in ("out", "in"):
                return None
            if e2.prev_edge_symbols or e2.from_symbol == e2.to_symbol:
                return None
            hops, include_lower, edge_unique = 1, False, True
            syms = {"src": e2.from_symbol, "mid": None,
                    "dst": e2.to_symbol, "edges": {e2.edge_symbol}}
            direction = e2.direction
            edge_types = list(e2.edge_types or [])
            upper = upper + inner
    else:
        return None

    lower, node = filters_of(node)
    src = _scan_source(node)
    if src is None or not isinstance(node.input, Op.Once) \
            or node.symbol != syms["src"]:
        return None
    source, src_label = src

    chain_syms = {syms["src"], syms["dst"]} | syms["edges"]
    if syms["mid"]:
        chain_syms.add(syms["mid"])
    row_aggs, distinct_aggs = [], []
    for spec in agg.aggregations:
        kind, expr, distinct, name = spec[0], spec[1], spec[2], spec[3]
        if len(spec) > 4 and spec[4] is not None:
            return None
        if kind != "count":
            _registry().note_fallback(None, f"agg_{kind}")
            return None
        if distinct:
            if isinstance(expr, A.Identifier) \
                    and expr.name == syms["dst"]:
                distinct_aggs.append(name)
                continue
            _registry().note_fallback(None, "agg_distinct")
            return None
        if expr is None:
            row_aggs.append(name)
            continue
        if isinstance(expr, A.Identifier) and expr.name in chain_syms:
            # count over a chain symbol: never null in an expand row
            row_aggs.append(name)
            continue
        _registry().note_fallback(None, "agg_unsupported")
        return None

    role_preds = {"src": [], "mid": [], "dst": []}
    role_labels = {"src": src_label, "mid": None, "dst": None}
    sym_role = {syms["src"]: "src", syms["dst"]: "dst"}
    if syms["mid"]:
        sym_role[syms["mid"]] = "mid"
    for cond_src in (upper, mid_filters, lower):
        for f in cond_src:
            for cond in _split_and(f):
                if isinstance(cond, A.LabelsTest) and \
                        isinstance(cond.expr, A.Identifier) and \
                        cond.expr.name in sym_role and \
                        len(cond.labels) == 1:
                    role = sym_role[cond.expr.name]
                    if role == "src" and src_label == cond.labels[0]:
                        continue
                    if role_labels[role] is None:
                        role_labels[role] = cond.labels[0]
                        continue
                    return None
                matched = False
                for sym, role in sym_role.items():
                    pred = _as_predicate(cond, sym, None)
                    if pred is not None and pred != ():
                        role_preds[role].append(pred)
                        matched = True
                        break
                if not matched:
                    for esym in syms["edges"]:
                        if _as_predicate(cond, esym, None):
                            _registry().note_fallback(None, "edge_prop")
                            return None
                    _registry().note_fallback(None, "dynamic_predicate")
                    return None

    return LaneHopCount(
        input=Op.Once(), fallback=agg, source=source,
        src_label=role_labels["src"], src_preds=role_preds["src"],
        mid_label=role_labels["mid"], mid_preds=role_preds["mid"],
        dst_label=role_labels["dst"], dst_preds=role_preds["dst"],
        direction=direction, edge_types=edge_types, hops=hops,
        include_lower=include_lower, edge_unique=edge_unique,
        row_aggs=row_aggs, distinct_aggs=distinct_aggs, hinted=hinted)


def lane_rewrite(plan, hinted: bool = False):
    """Lower lane-eligible operators in place (runs after
    parallel_rewrite; disabled alongside it — the lane is the device
    extension of the columnar rewrite, not an independent strategy)."""
    if os.environ.get(DISABLE_ENV) \
            or os.environ.get("MEMGRAPH_TPU_DISABLE_PARALLEL"):
        return plan

    changed = [False]

    def walk(op):
        if isinstance(op, ParallelExpandAggregate) \
                and not isinstance(op, ParallelExpandAggregateLane):
            changed[0] = True
            op = _clone_as(ParallelExpandAggregateLane, op)
        elif isinstance(op, ParallelScanAggregate) \
                and not isinstance(op, (ParallelExpandAggregate,
                                        ParallelScanAggregateLane)):
            changed[0] = True
            op = _clone_as(ParallelScanAggregateLane, op)
        elif isinstance(op, Op.Aggregate):
            repl = _match_hops(op, hinted)
            if repl is not None:
                changed[0] = True
                return repl             # fallback subplan stays pristine
        elif isinstance(op, (Op.Limit, Op.Skip)):
            inner = op.input
            produce = inner if isinstance(inner, Op.Produce) else None
            if produce is not None and isinstance(
                    produce.input, ParallelOrderedScan) and not \
                    isinstance(produce.input, ParallelOrderedScanLane):
                changed[0] = True
                produce.input = _clone_as(ParallelOrderedScanLane,
                                          produce.input)
        if not hasattr(op, "__dataclass_fields__"):
            return op
        for f in fields(op):
            if f.name == "fallback":
                continue            # row-path subplans stay pristine
            v = getattr(op, f.name)
            if isinstance(v, Op.LogicalOperator):
                setattr(op, f.name, walk(v))
        return op

    plan = walk(plan)
    if changed[0]:
        try:
            plan._has_lane = True
        except (AttributeError, TypeError):
            pass
    return plan


def bind_fingerprints(plan, fingerprint: str) -> None:
    """Stamp the mgstat plan-cache fingerprint onto every lane operator
    (the compile-cache key + the per-fingerprint stats bucket)."""
    if not getattr(plan, "_has_lane", False):
        return

    def walk(op):
        if hasattr(op, "fingerprint"):
            op.fingerprint = fingerprint
        if not hasattr(op, "__dataclass_fields__"):
            return
        for f in fields(op):
            v = getattr(op, f.name)
            if isinstance(v, Op.LogicalOperator):
                walk(v)

    walk(plan)


def invalidate_lanes() -> None:
    """Drop every compiled lane program. Wired into
    InterpreterContext.invalidate_plans, so every schema change that
    drops cached plans (index/constraint DDL, ANALYZE GRAPH,
    statistics) also drops the lanes compiled under them."""
    from ...ops import pipeline
    pipeline.drop_programs()
