"""Bulk-write fast lane: plan rewrite routing batchable write shapes
through storage.batch_insert().

Detects a chain of CreateNode/CreateExpand operators at the ROOT of a
write-only plan — the shapes `UNWIND … CREATE`, multi-row `CREATE`,
`LOAD CSV/JSONL/PARQUET … CREATE`, and `MATCH … CREATE` edge loads — and
replaces it with one BatchCreateGraph operator (operators.py) that turns
N per-row operator pulls into one amortized storage batch.

Safety rules (each falls back to the unmodified per-row plan):
  * only at the plan root of a write-only query (no downstream consumer
    observes the created accessors, no RETURN/WITH columns exist);
  * the source subtree is read-only, and if it reads the graph (scans /
    expands) it must sit behind the Eager barrier the planner inserts on
    read→write clause transitions — so deferring all creates to the end
    of the input stream is unobservable;
  * no property expression references an entity created by the same
    chain (`CREATE (a {x:1}) CREATE (b {y:a.x})` keeps the row path).

Reference analog: the reference batches commits at the storage layer
(storage/v2/inmemory/storage.cpp) and dedicates an operator to LOAD CSV;
GraphBLAST (arxiv 1908.01407) and PCPM (arxiv 1709.07122) make the same
argument for amortizing per-element overhead into batch operations.
"""

from __future__ import annotations

import os

from ..frontend import ast as A
from . import operators as Op

# ops that may appear anywhere in a fast-lane source subtree
_PLAIN_SOURCES = (Op.Once, Op.Unwind, Op.Filter, Op.Eager, Op.LoadCsvOp,
                  Op.LoadJsonlOp, Op.LoadParquetOp)
# graph-reading ops additionally allowed when the source root is an Eager
# barrier (the planner's read→write fence)
_GRAPH_READERS = (Op.ScanAll, Op.ScanAllByLabel,
                  Op.ScanAllByLabelPropertyValue,
                  Op.ScanAllByLabelPropertyRange, Op.ScanAllById,
                  Op.Expand, Op.ExpandVariable)


def bulk_rewrite(plan, storage, config=None):
    """Replace a root CreateNode/CreateExpand chain with BatchCreateGraph.

    Called from Planner.plan_query for write-only, union-free,
    non-periodic-commit plans only.
    """
    if config is not None and not config.get("bulk_fast_lane", True):
        return plan
    if os.environ.get("MEMGRAPH_TPU_DISABLE_BULK"):
        return plan
    if not getattr(storage, "supports_batch_insert", False):
        return plan

    chain = []
    node = plan
    while isinstance(node, (Op.CreateNode, Op.CreateExpand)):
        chain.append(node)
        node = node.input
    if not chain:
        return plan
    source = node
    if not _source_ok(source):
        return plan

    chain.reverse()  # bottom-up = per-row execution order
    steps: list = []
    created: set[str] = set()
    for op in chain:
        if isinstance(op, Op.CreateNode):
            if _props_reference(op.properties, created):
                return plan
            steps.append(Op.BatchNodeStep(op.symbol, op.labels,
                                          op.properties))
            created.add(op.symbol)
        else:
            if op.create_to_node:
                if _props_reference(op.to_properties, created):
                    return plan
                steps.append(Op.BatchNodeStep(op.to_symbol, op.to_labels,
                                              op.to_properties))
                created.add(op.to_symbol)
            if _props_reference(op.edge_properties, created):
                return plan
            steps.append(Op.BatchEdgeStep(op.from_symbol, op.edge_symbol,
                                          op.to_symbol, op.direction,
                                          op.edge_type, op.edge_properties))
            created.add(op.edge_symbol)
    pipeline_base = pipeline = None
    inner = source.input if isinstance(source, Op.Eager) else source
    folded = _fold_pipeline(inner)
    if folded is not None:
        pipeline_base, pipeline = folded
    return Op.BatchCreateGraph(source, steps, pipeline_base, pipeline)


def _fold_pipeline(op):
    """Fold an UNWIND / equality-index-scan pipeline over a simple base
    into inline stage descriptors, or None when the shape doesn't match.
    Returns (base_operator, stages bottom-up)."""
    stages: list = []
    node = op
    while True:
        if isinstance(node, Op.Unwind):
            stages.append(("unwind", node.expr, node.symbol))
        elif isinstance(node, Op.ScanAllByLabelPropertyValue):
            stages.append(("scan", node.symbol, node.label,
                           list(node.properties), list(node.value_exprs)))
        elif isinstance(node, (Op.Once, Op.LoadCsvOp, Op.LoadJsonlOp,
                               Op.LoadParquetOp)):
            stages.reverse()
            return node, stages
        else:
            return None
        node = node.input


def _source_ok(source) -> bool:
    reads_graph = False

    def walk(op) -> bool:
        nonlocal reads_graph
        if op is None:
            return True
        if isinstance(op, _GRAPH_READERS):
            reads_graph = True
        elif not isinstance(op, _PLAIN_SOURCES):
            return False
        return all(walk(child) for child in op.children())

    if not walk(source):
        return False
    return not reads_graph or isinstance(source, Op.Eager)


def _props_reference(properties, names: set) -> bool:
    """True when a property map's expressions reference any of `names`
    (symbols bound by earlier creates of the same chain — the batch path
    evaluates property maps before any object exists)."""
    if not names or properties is None:
        return False
    if isinstance(properties, A.Parameter):
        return False
    exprs = properties.values() if isinstance(properties, dict) \
        else [properties]
    from .operators import _expr_references
    return any(_expr_references(e, names) for e in exprs)
