"""Volcano-style pull operators (generator cursors).

Counterpart of the reference's ~80 pull operators
(/root/reference/src/query/plan/operator.hpp:331-3189). Each logical
operator exposes `cursor(ctx)` returning an iterator of frames (dicts);
the chain streams row-by-row so LIMIT short-circuits and Bolt can pull
incrementally — the same contract as the reference's Cursor::Pull
(operator.hpp:79). PROFILE wraps cursors with counters (profile.py).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Optional

from ...exceptions import (HintedAbortError, QueryException, SemanticException,
                           TypeException)
from ...storage.common import View
from ...storage.objects import Vertex
from ...storage.ordering import order_key
from ...storage.storage import EdgeAccessor, VertexAccessor
from ..eval import EvalContext, Evaluator
from ..frontend import ast as A
from .. import values as V
from ..values import Path


class ExecutionContext:
    """Per-execution state shared by all cursors."""

    def __init__(self, accessor, parameters=None, view=View.NEW,
                 interpreter_context=None, timeout_checker=None,
                 memory=None):
        from ...utils.memory_tracker import QueryMemoryTracker
        self.accessor = accessor
        self.parameters = parameters or {}
        self.view = view
        self.eval_ctx = EvalContext(accessor, self.parameters, view)
        self.eval_ctx.exec_ctx = self  # functions needing execution state
        self.evaluator = Evaluator(self.eval_ctx)
        self.interpreter_context = interpreter_context
        self.timeout_checker = timeout_checker
        # per-query materialized-state accounting (QUERY MEMORY LIMIT);
        # reference: memory/query_memory_control.cpp
        self.memory = memory if memory is not None else QueryMemoryTracker()
        self.stats = {"nodes_created": 0, "nodes_deleted": 0,
                      "relationships_created": 0, "relationships_deleted": 0,
                      "properties_set": 0, "labels_added": 0,
                      "labels_removed": 0}
        self.hops_budget = None  # USING HOPS LIMIT (query/hops_limit.hpp)
        # when the budget runs out: True -> stop expanding (partial
        # results), False -> raise. Reference default true
        # (run_time_configurable.cpp:77 hops_limit_partial_results)
        self.hops_partial = True

    def check_abort(self):
        if self.timeout_checker is not None:
            self.timeout_checker()

    def consume_hop(self) -> bool:
        """False = budget exhausted in partial-results mode (caller stops
        expanding); raises when partial results are disabled."""
        if self.hops_budget is not None:
            self.hops_budget -= 1
            if self.hops_budget < 0:
                if self.hops_partial:
                    return False
                raise QueryException(
                    "hops limit exceeded (USING HOPS LIMIT)")
        return True

    @property
    def storage(self):
        return self.accessor.storage


class LogicalOperator:
    """Base: single-input operators hold `input` (no default here — a base
    class attribute would leak a dataclass default into every subclass)."""

    def cursor(self, ctx: ExecutionContext):
        raise NotImplementedError

    def name(self) -> str:
        return type(self).__name__

    def children(self) -> list:
        child = getattr(self, "input", None)
        return [child] if child is not None else []


class Once(LogicalOperator):
    input = None

    def cursor(self, ctx):
        yield {}


@dataclass
class Eager(LogicalOperator):
    """Pipeline barrier: drain the input fully before yielding anything.

    Gives Cypher its clause-at-a-time visibility semantics — a reading
    clause must observe the graph state AFTER a preceding updating clause
    processed every row, and an updating clause must not mutate the graph
    while an upstream scan is still iterating. The planner inserts this on
    read->write and write->read clause transitions (reference: Accumulate
    with advance_command, query/plan/operator.hpp; neo4j's Eager)."""
    input: LogicalOperator

    def cursor(self, ctx):
        rows = []
        for frame in self.input.cursor(ctx):
            ctx.memory.add_value(frame)
            rows.append(frame)
        for frame in rows:
            ctx.check_abort()
            yield frame


@dataclass
class ScanAll(LogicalOperator):
    input: LogicalOperator
    symbol: str

    def cursor(self, ctx):
        for frame in self.input.cursor(ctx):
            ctx.check_abort()
            for va in ctx.accessor.vertices(ctx.view):
                new = dict(frame)
                new[self.symbol] = va
                yield new


@dataclass
class ScanAllByLabel(LogicalOperator):
    input: LogicalOperator
    symbol: str
    label: str

    def cursor(self, ctx):
        lid = ctx.storage.label_mapper.maybe_name_to_id(self.label)
        for frame in self.input.cursor(ctx):
            ctx.check_abort()
            if lid is None:
                continue
            for va in ctx.accessor.vertices_by_label(lid, ctx.view):
                new = dict(frame)
                new[self.symbol] = va
                yield new


@dataclass
class ScanAllByLabelPropertyValue(LogicalOperator):
    input: LogicalOperator
    symbol: str
    label: str
    properties: list[str]
    value_exprs: list[A.Expr]

    def cursor(self, ctx):
        storage = ctx.storage
        lid = storage.label_mapper.maybe_name_to_id(self.label)
        pids = [storage.property_mapper.maybe_name_to_id(p)
                for p in self.properties]
        for frame in self.input.cursor(ctx):
            ctx.check_abort()
            if lid is None or any(p is None for p in pids):
                continue
            values = [ctx.evaluator.eval(e, frame) for e in self.value_exprs]
            if any(v is None for v in values):
                continue  # = null never matches
            for va in ctx.accessor.vertices_by_label_property_value(
                    lid, tuple(pids), values, ctx.view):
                new = dict(frame)
                new[self.symbol] = va
                yield new


@dataclass
class ScanAllByLabelPropertyRange(LogicalOperator):
    input: LogicalOperator
    symbol: str
    label: str
    prop: str
    lower: Optional[A.Expr]
    upper: Optional[A.Expr]
    lower_inclusive: bool = True
    upper_inclusive: bool = True

    def cursor(self, ctx):
        storage = ctx.storage
        lid = storage.label_mapper.maybe_name_to_id(self.label)
        pid = storage.property_mapper.maybe_name_to_id(self.prop)
        for frame in self.input.cursor(ctx):
            ctx.check_abort()
            if lid is None or pid is None:
                continue
            lo = ctx.evaluator.eval(self.lower, frame) \
                if self.lower is not None else None
            hi = ctx.evaluator.eval(self.upper, frame) \
                if self.upper is not None else None
            if (self.lower is not None and lo is None) or \
                    (self.upper is not None and hi is None):
                continue
            for va in ctx.accessor.vertices_by_label_property_range(
                    lid, (pid,), lo, hi, self.lower_inclusive,
                    self.upper_inclusive, ctx.view):
                new = dict(frame)
                new[self.symbol] = va
                yield new


@dataclass
class ScanAllById(LogicalOperator):
    input: LogicalOperator
    symbol: str
    id_expr: A.Expr

    def cursor(self, ctx):
        for frame in self.input.cursor(ctx):
            gid = ctx.evaluator.eval(self.id_expr, frame)
            if not isinstance(gid, int) or isinstance(gid, bool):
                continue
            va = ctx.accessor.find_vertex(gid, ctx.view)
            if va is not None:
                new = dict(frame)
                new[self.symbol] = va
                yield new


def _used_edge_gids(frame, prev_edge_symbols) -> set:
    """Edge gids already consumed by earlier pattern elements of the same
    MATCH — single edges AND var-length edge lists (relationship
    isomorphism; reference: EdgeUniquenessFilter, plan/operator.hpp)."""
    used = set()
    for s in prev_edge_symbols:
        v = frame.get(s)
        if isinstance(v, EdgeAccessor):
            used.add(v.gid)
        elif isinstance(v, (list, tuple)):
            for e in v:
                if isinstance(e, EdgeAccessor):
                    used.add(e.gid)
    return used


@dataclass
class Expand(LogicalOperator):
    """Expand one hop from `from_symbol`; binds edge_symbol/to_symbol.

    direction: 'out' | 'in' | 'both'. If to_symbol is already bound, acts
    as an edge test between the two bound nodes. `prev_edge_symbols` holds
    edge symbols of the same MATCH for relationship-uniqueness filtering
    (reference: EdgeUniquenessFilter, plan/operator.hpp).
    """
    input: LogicalOperator
    from_symbol: str
    edge_symbol: str
    to_symbol: str
    direction: str
    edge_types: list[str]
    prev_edge_symbols: list[str] = field(default_factory=list)

    def _type_ids(self, ctx):
        if not self.edge_types:
            return None
        ids = set()
        for t in self.edge_types:
            tid = ctx.storage.edge_type_mapper.maybe_name_to_id(t)
            if tid is not None:
                ids.add(tid)
        return ids

    def cursor(self, ctx):
        type_ids = self._type_ids(ctx)
        for frame in self.input.cursor(ctx):
            ctx.check_abort()
            if self.edge_types and not type_ids:
                continue
            from_v = frame.get(self.from_symbol)
            if from_v is None:
                continue
            to_bound = self.to_symbol in frame
            # an edge variable bound by an earlier clause constrains the
            # match to that exact edge (TCK MatchAcceptance2 "Matching
            # using a relationship that is already bound"; reference:
            # existing-symbol handling in rule_based_planner). A PRESENT
            # key bound to null (OPTIONAL MATCH miss) matches nothing.
            if self.edge_symbol in frame:
                prebound = frame[self.edge_symbol]
                if not isinstance(prebound, EdgeAccessor):
                    continue
            else:
                prebound = None
            used = _used_edge_gids(frame, self.prev_edge_symbols)
            bound_other = None
            if to_bound:
                bound_other = frame[self.to_symbol]
                if not isinstance(bound_other, VertexAccessor):
                    continue
            for ea, other in self._edges(ctx, from_v, type_ids,
                                         bound_other):
                if not ctx.consume_hop():
                    break
                if ea.gid in used:
                    continue
                if prebound is not None and ea.gid != prebound.gid:
                    continue
                if to_bound:
                    if bound_other.gid != other.gid:
                        continue
                    new = dict(frame)
                    new[self.edge_symbol] = ea
                    yield new
                else:
                    new = dict(frame)
                    new[self.edge_symbol] = ea
                    new[self.to_symbol] = other
                    yield new

    def _edges(self, ctx, from_v, type_ids, bound_other=None):
        # a bound destination is pushed down into the adjacency read: on
        # supernode hubs the accessor serves it from the per-vertex
        # adjacency map instead of scanning all O(degree) entries — this is
        # what takes hub MERGE's existence probe from O(degree) to O(1)
        view = ctx.view
        if self.direction in ("out", "both"):
            for ea in from_v.out_edges(view, type_ids,
                                       to_vertex=bound_other):
                yield ea, ea.to_vertex()
        if self.direction in ("in", "both"):
            for ea in from_v.in_edges(view, type_ids,
                                      from_vertex=bound_other):
                if self.direction == "both" and \
                        ea.from_vertex().gid == from_v.gid and \
                        ea.to_vertex().gid == from_v.gid:
                    continue  # self-loop already produced by the out pass
                yield ea, ea.from_vertex()


@dataclass
class ExpandVariable(LogicalOperator):
    """Variable-length expansion (DFS enumeration with hop bounds).

    Binds edge_symbol to the list of edges. Counterpart of the reference's
    ExpandVariable (plan/operator.hpp:1140).
    """
    input: LogicalOperator
    from_symbol: str
    edge_symbol: str
    to_symbol: str
    direction: str
    edge_types: list[str]
    min_hops: int = 1
    max_hops: int = -1          # -1 = unbounded
    prev_edge_symbols: list[str] = field(default_factory=list)
    filter_lambda: object = None    # A.Lambda — per-step (e, n | pred)

    def _step_ok(self, ctx, frame, edge, node) -> bool:
        lam = self.filter_lambda
        if lam is None:
            return True
        inner = dict(frame)
        inner[lam.edge_var] = edge
        inner[lam.node_var] = node
        return ctx.evaluator.eval(lam.expr, inner) is True

    def cursor(self, ctx):
        type_ids = Expand._type_ids(self, ctx)
        max_hops = self.max_hops if self.max_hops >= 0 else 1 << 30
        for frame in self.input.cursor(ctx):
            ctx.check_abort()
            if self.edge_types and not type_ids:
                continue
            from_v = frame.get(self.from_symbol)
            if from_v is None:
                continue
            to_bound = self.to_symbol in frame
            used = _used_edge_gids(frame, self.prev_edge_symbols)

            def dfs(node, path_edges, used_gids):
                depth = len(path_edges)
                if depth >= self.min_hops:
                    if to_bound:
                        bound = frame[self.to_symbol]
                        if isinstance(bound, VertexAccessor) and \
                                bound.gid == node.gid:
                            yield path_edges, node
                    else:
                        yield path_edges, node
                if depth >= max_hops:
                    return
                for ea, other in Expand._edges(self, ctx, node, type_ids):
                    if not ctx.consume_hop():
                        break
                    if ea.gid in used_gids:
                        continue
                    if prebound is not None and (
                            depth >= len(prebound)
                            or ea.gid != prebound[depth].gid):
                        continue
                    if not self._step_ok(ctx, frame, ea, other):
                        continue
                    yield from dfs(other, path_edges + [ea],
                                   used_gids | {ea.gid})

            # a pre-bound edge-list variable constrains the path to exactly
            # that relationship sequence (TCK MatchAcceptance2 "Matching
            # relationships into a list and matching variable length using
            # the list"); a null binding (OPTIONAL MATCH miss) matches
            # nothing. The dfs prefix check below keeps this O(len(list))
            # instead of enumerating every path and filtering after.
            if self.edge_symbol in frame:
                prebound = frame[self.edge_symbol]
                if not isinstance(prebound, (list, tuple)) or not all(
                        isinstance(p, EdgeAccessor) for p in prebound):
                    continue
            else:
                prebound = None

            def seq_ok(path_edges):
                return prebound is None or len(path_edges) == len(prebound)

            if self.min_hops == 0:
                # zero-length: from == to
                if seq_ok([]):
                    if to_bound:
                        bound = frame[self.to_symbol]
                        if isinstance(bound, VertexAccessor) and \
                                bound.gid == from_v.gid:
                            new = dict(frame)
                            new[self.edge_symbol] = []
                            yield new
                    else:
                        new = dict(frame)
                        new[self.edge_symbol] = []
                        new[self.to_symbol] = from_v
                        yield new
            start = max(self.min_hops, 1)
            for path_edges, end in dfs(from_v, [], set(used)):
                if len(path_edges) < start:
                    continue
                if not seq_ok(path_edges):
                    continue
                new = dict(frame)
                new[self.edge_symbol] = list(path_edges)
                if not to_bound:
                    new[self.to_symbol] = end
                yield new


@dataclass
class ExpandShortest(LogicalOperator):
    """BFS / weighted-shortest / all-shortest expansion.

    Counterpart of the traversal modes the reference embeds in
    ExpandVariable (plan/operator.hpp:1140 — *BFS, *WSHORTEST,
    *ALLSHORTEST with filter/weight lambdas). Host-side graph walk (the
    point-query regime); whole-graph distances run on device via
    ops/traversal.py.
    """
    input: LogicalOperator
    from_symbol: str
    edge_symbol: str
    to_symbol: str
    direction: str
    edge_types: list[str]
    algo: str                          # 'bfs' | 'wshortest' | 'allshortest'
    max_hops: int = -1
    weight_lambda: object = None       # A.Lambda
    filter_lambda: object = None       # A.Lambda
    total_weight_symbol: Optional[str] = None

    def cursor(self, ctx):
        type_ids = Expand._type_ids(self, ctx)
        max_hops = self.max_hops if self.max_hops >= 0 else 1 << 30
        for frame in self.input.cursor(ctx):
            ctx.check_abort()
            if self.edge_types and not type_ids:
                continue
            source = frame.get(self.from_symbol)
            if not isinstance(source, VertexAccessor):
                continue
            to_bound = self.to_symbol in frame
            target_gid = None
            if to_bound:
                bound = frame[self.to_symbol]
                if not isinstance(bound, VertexAccessor):
                    continue
                target_gid = bound.gid
            if self.algo == "bfs":
                results = self._bfs(ctx, frame, source, target_gid, max_hops,
                                    type_ids)
            else:
                results = self._dijkstra(
                    ctx, frame, source, target_gid, max_hops, type_ids,
                    all_shortest=(self.algo == "allshortest"))
            for (end_vertex, edges, weight) in results:
                new = dict(frame)
                new[self.edge_symbol] = edges
                if not to_bound:
                    new[self.to_symbol] = end_vertex
                if self.total_weight_symbol:
                    new[self.total_weight_symbol] = weight
                yield new

    def _neighbors(self, ctx, va, type_ids):
        yield from Expand._edges(self, ctx, va, type_ids)

    def _passes_filter(self, ctx, frame, edge, node) -> bool:
        lam = self.filter_lambda
        if lam is None:
            return True
        inner = dict(frame)
        inner[lam.edge_var] = edge
        inner[lam.node_var] = node
        return ctx.evaluator.eval(lam.expr, inner) is True

    def _edge_weight(self, ctx, frame, edge, node) -> float:
        lam = self.weight_lambda
        if lam is None:
            return 1.0
        inner = dict(frame)
        inner[lam.edge_var] = edge
        inner[lam.node_var] = node
        w = ctx.evaluator.eval(lam.expr, inner)
        if not V.is_numeric(w):
            raise TypeException("weight lambda must return a number")
        if w < 0:
            raise TypeException("weight lambda must be non-negative")
        return w

    def _bfs(self, ctx, frame, source, target_gid, max_hops, type_ids):
        from collections import deque
        parent = {source.gid: None}   # gid -> (prev_gid, edge)
        node_of = {source.gid: source}
        queue = deque([(source, 0)])
        while queue:
            ctx.check_abort()
            va, depth = queue.popleft()
            if depth >= max_hops:
                continue
            for ea, other in self._neighbors(ctx, va, type_ids):
                if other.gid in parent:
                    continue
                if not self._passes_filter(ctx, frame, ea, other):
                    continue
                parent[other.gid] = (va.gid, ea)
                node_of[other.gid] = other
                if target_gid is not None and other.gid == target_gid:
                    yield (other, self._path(parent, other.gid),
                           float(depth + 1))
                    return
                if target_gid is None:
                    yield (other, self._path(parent, other.gid),
                           float(depth + 1))
                queue.append((other, depth + 1))

    @staticmethod
    def _path(parent, gid):
        edges = []
        while parent[gid] is not None:
            prev_gid, edge = parent[gid]
            edges.append(edge)
            gid = prev_gid
        edges.reverse()
        return edges

    def _dijkstra(self, ctx, frame, source, target_gid, max_hops, type_ids,
                  all_shortest, banned_edges=frozenset(),
                  banned_nodes=frozenset()):
        import heapq
        import itertools as it
        dist = {source.gid: 0.0}
        hops = {source.gid: 0}
        parents: dict = {source.gid: []}  # gid -> [(prev_gid, edge)]
        node_of = {source.gid: source}
        tie = it.count()
        heap = [(0.0, next(tie), source)]
        settled = set()
        while heap:
            ctx.check_abort()
            d, _, va = heapq.heappop(heap)
            if va.gid in settled:
                continue
            settled.add(va.gid)
            if target_gid is not None and va.gid == target_gid:
                break
            if hops[va.gid] >= max_hops:
                continue
            for ea, other in self._neighbors(ctx, va, type_ids):
                if ea.gid in banned_edges or other.gid in banned_nodes:
                    continue
                if not self._passes_filter(ctx, frame, ea, other):
                    continue
                w = self._edge_weight(ctx, frame, ea, other)
                nd = d + w
                old = dist.get(other.gid)
                if old is None or nd < old - 1e-12:
                    dist[other.gid] = nd
                    hops[other.gid] = hops[va.gid] + 1
                    parents[other.gid] = [(va.gid, ea)]
                    node_of[other.gid] = other
                    heapq.heappush(heap, (nd, next(tie), other))
                elif all_shortest and abs(nd - old) <= 1e-12:
                    parents[other.gid].append((va.gid, ea))

        def all_paths(gid):
            if not parents[gid]:
                yield []
                return
            for (prev_gid, edge) in parents[gid]:
                for prefix in all_paths(prev_gid):
                    yield prefix + [edge]

        targets = ([target_gid] if target_gid is not None
                   else [g for g in dist if g != source.gid])
        for gid in targets:
            if gid not in dist:
                continue
            if all_shortest:
                for path in all_paths(gid):
                    yield (node_of[gid], path, dist[gid])
            else:
                yield (node_of[gid], all_paths(gid).__next__(), dist[gid])


@dataclass
class ExpandKShortest(LogicalOperator):
    """*KSHORTEST: Yen's algorithm over the Dijkstra base (reference:
    the KSHORTEST mode of ExpandVariable). Requires a bound target."""
    input: LogicalOperator
    from_symbol: str
    edge_symbol: str
    to_symbol: str
    direction: str
    edge_types: list[str]
    k: int
    weight_lambda: object = None
    filter_lambda: object = None
    total_weight_symbol: Optional[str] = None

    def cursor(self, ctx):
        type_ids = Expand._type_ids(self, ctx)
        helper = ExpandShortest(
            self.input, self.from_symbol, self.edge_symbol, self.to_symbol,
            self.direction, self.edge_types, "wshortest", -1,
            self.weight_lambda, self.filter_lambda, None)
        for frame in self.input.cursor(ctx):
            ctx.check_abort()
            source = frame.get(self.from_symbol)
            target = frame.get(self.to_symbol)
            if not isinstance(source, VertexAccessor) or \
                    not isinstance(target, VertexAccessor):
                continue
            for (edges, weight) in self._yen(ctx, frame, helper, source,
                                             target, type_ids):
                new = dict(frame)
                new[self.edge_symbol] = edges
                if self.total_weight_symbol:
                    new[self.total_weight_symbol] = weight
                yield new

    def _shortest(self, ctx, frame, helper, source, target, banned_edges,
                  banned_nodes, type_ids):
        """One Dijkstra run honoring Yen's removals."""
        results = list(helper._dijkstra(
            ctx, frame, source, target.gid, 1 << 30, type_ids,
            all_shortest=False, banned_edges=frozenset(banned_edges),
            banned_nodes=frozenset(banned_nodes)))
        return results[0] if results else None

    def _yen(self, ctx, frame, helper, source, target, type_ids):
        first = self._shortest(ctx, frame, helper, source, target,
                               set(), set(), type_ids)
        if first is None:
            return
        paths = [(first[1], first[2])]   # (edges, weight)
        yield paths[0]
        candidates: list = []
        import heapq
        while len(paths) < self.k:
            prev_edges, _ = paths[-1]
            prev_nodes = self._node_seq(source, prev_edges)
            for i in range(len(prev_edges)):
                spur_node = prev_nodes[i]
                root_edges = prev_edges[:i]
                root_weight = sum(
                    helper._edge_weight(ctx, frame, e,
                                        self._other(e, prev_nodes[j]))
                    for j, e in enumerate(root_edges))
                banned_edges = set()
                for (p_edges, _w) in paths:
                    if [e.gid for e in p_edges[:i]] == \
                            [e.gid for e in root_edges] and len(p_edges) > i:
                        banned_edges.add(p_edges[i].gid)
                banned_nodes = {n.gid for n in prev_nodes[:i]}
                spur = self._shortest(ctx, frame, helper, spur_node, target,
                                      banned_edges, banned_nodes, type_ids)
                if spur is None:
                    continue
                total = root_edges + spur[1]
                weight = root_weight + spur[2]
                key = tuple(e.gid for e in total)
                if not any(tuple(e.gid for e in c[2]) == key
                           for c in candidates) and \
                        not any(tuple(e.gid for e in p[0]) == key
                                for p in paths):
                    heapq.heappush(candidates,
                                   (weight, id(total), total))
            if not candidates:
                return
            weight, _, best = heapq.heappop(candidates)
            paths.append((best, weight))
            yield paths[-1]

    def _node_seq(self, source, edges):
        nodes = [source]
        for e in edges:
            cur = nodes[-1]
            nxt = e.to_vertex() if e.from_vertex().gid == cur.gid \
                else e.from_vertex()
            nodes.append(nxt)
        return nodes

    @staticmethod
    def _other(edge, from_node):
        return edge.to_vertex() if edge.from_vertex().gid == from_node.gid \
            else edge.from_vertex()


def _chain_edges(edge_list, start_node):
    """Walk edge_list in the GIVEN order from start_node; returns the
    interleaved [edge, node, edge, node, ...] tail, or None if some edge
    is not incident to the walk front (wrong orientation)."""
    out = []
    last = start_node
    for ea in edge_list:
        if ea.from_vertex().gid == last.gid:
            nxt = ea.to_vertex()
        elif ea.to_vertex().gid == last.gid:
            nxt = ea.from_vertex()
        else:
            return None
        out.append(ea)
        out.append(nxt)
        last = nxt
    return out


@dataclass
class ConstructNamedPath(LogicalOperator):
    """Bind a path variable from matched pattern symbols."""
    input: LogicalOperator
    path_symbol: str
    element_symbols: list[str]   # node, edge, node, edge, ...

    def cursor(self, ctx):
        for frame in self.input.cursor(ctx):
            items = []
            ok = True
            for i, sym in enumerate(self.element_symbols):
                v = frame.get(sym)
                if v is None:
                    ok = False
                    break
                if isinstance(v, list):      # variable-length edge list
                    if items:
                        # the matcher stores the list in TRAVERSAL order,
                        # which is REVERSED when the planner expanded from
                        # the far end — chain whichever orientation walks
                        # from the declared start, so relationships(p)
                        # comes out in pattern order (TCK MatchAcceptance
                        # "starting from the end"). Trying both exact
                        # orders (not greedy incidence picking) stays
                        # correct on cycles and parallel edges.
                        chained = _chain_edges(v, items[-1]) or \
                            _chain_edges(list(reversed(v)), items[-1])
                        if chained is None:
                            ok = False
                            break
                        items.extend(chained)
                    continue
                if items and isinstance(v, VertexAccessor) and \
                        isinstance(items[-1], VertexAccessor):
                    if items[-1].gid == v.gid:
                        continue  # var-length already appended the end node
                items.append(v)
            new = dict(frame)
            new[self.path_symbol] = Path(items) if ok else None
            yield new


@dataclass
class Filter(LogicalOperator):
    input: LogicalOperator
    expr: A.Expr

    def cursor(self, ctx):
        for frame in self.input.cursor(ctx):
            ctx.check_abort()
            if ctx.evaluator.eval(self.expr, frame) is True:
                yield frame


@dataclass
class Produce(LogicalOperator):
    input: LogicalOperator
    items: list[tuple[A.Expr, str]]   # (expr, output name)

    def cursor(self, ctx):
        for frame in self.input.cursor(ctx):
            ctx.check_abort()
            out = dict(frame)
            row = {}
            for expr, name in self.items:
                value = ctx.evaluator.eval(expr, frame)
                row[name] = value
                out[name] = value
            out["__row__"] = row
            yield out


@dataclass
class CreateNode(LogicalOperator):
    input: LogicalOperator
    symbol: str
    labels: list[str]
    properties: object           # dict[str, Expr] | A.Parameter | None

    def cursor(self, ctx):
        storage = ctx.storage
        for frame in self.input.cursor(ctx):
            ctx.check_abort()
            va = ctx.accessor.create_vertex()
            ctx.stats["nodes_created"] += 1
            for label in self.labels:
                va.add_label(storage.label_mapper.name_to_id(label))
                ctx.stats["labels_added"] += 1
            props = _eval_prop_map(ctx, self.properties, frame)
            for key, value in props.items():
                if value is not None:
                    va.set_property(
                        storage.property_mapper.name_to_id(key), value)
                    ctx.stats["properties_set"] += 1
            new = dict(frame)
            new[self.symbol] = va
            yield new


@dataclass
class CreateExpand(LogicalOperator):
    """Create an edge (and possibly the other endpoint node)."""
    input: LogicalOperator
    from_symbol: str
    edge_symbol: str
    to_symbol: str
    direction: str               # 'out' | 'in' (creation needs a direction)
    edge_type: str
    edge_properties: object
    create_to_node: bool
    to_labels: list[str] = field(default_factory=list)
    to_properties: object = None

    def cursor(self, ctx):
        storage = ctx.storage
        for frame in self.input.cursor(ctx):
            ctx.check_abort()
            from_v = frame[self.from_symbol]
            if not isinstance(from_v, VertexAccessor):
                raise QueryException("CREATE edge endpoint is not a node")
            new = dict(frame)
            if self.create_to_node:
                to_v = ctx.accessor.create_vertex()
                ctx.stats["nodes_created"] += 1
                for label in self.to_labels:
                    to_v.add_label(storage.label_mapper.name_to_id(label))
                    ctx.stats["labels_added"] += 1
                props = _eval_prop_map(ctx, self.to_properties, frame)
                for key, value in props.items():
                    if value is not None:
                        to_v.set_property(
                            storage.property_mapper.name_to_id(key), value)
                        ctx.stats["properties_set"] += 1
                new[self.to_symbol] = to_v
            else:
                to_v = frame[self.to_symbol]
                if not isinstance(to_v, VertexAccessor):
                    raise QueryException("CREATE edge endpoint is not a node")
            tid = storage.edge_type_mapper.name_to_id(self.edge_type)
            if self.direction == "in":
                ea = ctx.accessor.create_edge(to_v, from_v, tid)
            else:
                ea = ctx.accessor.create_edge(from_v, to_v, tid)
            ctx.stats["relationships_created"] += 1
            props = _eval_prop_map(ctx, self.edge_properties, frame)
            for key, value in props.items():
                if value is not None:
                    ea.set_property(storage.property_mapper.name_to_id(key),
                                    value)
                    ctx.stats["properties_set"] += 1
            new[self.edge_symbol] = ea
            yield new


def _eval_prop_map(ctx, properties, frame) -> dict:
    if properties is None:
        return {}
    if isinstance(properties, A.Parameter):
        value = ctx.evaluator.eval(properties, frame)
        if not isinstance(value, dict):
            raise TypeException("property parameter must be a map")
        return value
    return {k: ctx.evaluator.eval(e, frame) for k, e in properties.items()}


@dataclass
class SetProperty(LogicalOperator):
    input: LogicalOperator
    target: A.PropertyLookup
    value: A.Expr

    def cursor(self, ctx):
        for frame in self.input.cursor(ctx):
            obj = ctx.evaluator.eval(self.target.expr, frame)
            value = ctx.evaluator.eval(self.value, frame)
            if obj is None:
                yield frame
                continue
            if not isinstance(obj, (VertexAccessor, EdgeAccessor)):
                raise TypeException("SET property on a non-graph value")
            pid = ctx.storage.property_mapper.name_to_id(self.target.prop)
            obj.set_property(pid, value)
            ctx.stats["properties_set"] += 1
            yield frame


@dataclass
class SetProperties(LogicalOperator):
    """n = {..} (replace) or n += {..} (update)."""
    input: LogicalOperator
    symbol: str
    value: A.Expr
    update: bool

    def cursor(self, ctx):
        storage = ctx.storage
        for frame in self.input.cursor(ctx):
            obj = frame.get(self.symbol)
            if obj is None:
                yield frame
                continue
            if not isinstance(obj, (VertexAccessor, EdgeAccessor)):
                raise TypeException("SET properties on a non-graph value")
            value = ctx.evaluator.eval(self.value, frame)
            if isinstance(value, (VertexAccessor, EdgeAccessor)):
                value = {storage.property_mapper.id_to_name(k): v
                         for k, v in value.properties(ctx.view).items()}
            if not isinstance(value, dict):
                raise TypeException("SET expects a map")
            if not self.update:
                for pid in list(obj.properties(ctx.view)):
                    obj.set_property(pid, None)
            for key, v in value.items():
                obj.set_property(storage.property_mapper.name_to_id(key), v)
                ctx.stats["properties_set"] += 1
            yield frame


@dataclass
class SetLabels(LogicalOperator):
    input: LogicalOperator
    symbol: str
    labels: list[str]

    def cursor(self, ctx):
        for frame in self.input.cursor(ctx):
            obj = frame.get(self.symbol)
            if obj is None:
                yield frame
                continue
            if not isinstance(obj, VertexAccessor):
                raise TypeException("SET label on a non-node value")
            for label in self.labels:
                if obj.add_label(ctx.storage.label_mapper.name_to_id(label)):
                    ctx.stats["labels_added"] += 1
            yield frame


@dataclass
class RemoveProperty(LogicalOperator):
    input: LogicalOperator
    target: A.PropertyLookup

    def cursor(self, ctx):
        for frame in self.input.cursor(ctx):
            obj = ctx.evaluator.eval(self.target.expr, frame)
            if obj is None:
                yield frame
                continue
            if not isinstance(obj, (VertexAccessor, EdgeAccessor)):
                raise TypeException("REMOVE property on a non-graph value")
            pid = ctx.storage.property_mapper.maybe_name_to_id(self.target.prop)
            if pid is not None:
                obj.set_property(pid, None)
                ctx.stats["properties_set"] += 1
            yield frame


@dataclass
class RemoveLabels(LogicalOperator):
    input: LogicalOperator
    symbol: str
    labels: list[str]

    def cursor(self, ctx):
        for frame in self.input.cursor(ctx):
            obj = frame.get(self.symbol)
            if obj is None:
                yield frame
                continue
            if not isinstance(obj, VertexAccessor):
                raise TypeException("REMOVE label on a non-node value")
            for label in self.labels:
                lid = ctx.storage.label_mapper.maybe_name_to_id(label)
                if lid is not None and obj.remove_label(lid):
                    ctx.stats["labels_removed"] += 1
            yield frame


@dataclass
class Delete(LogicalOperator):
    input: LogicalOperator
    exprs: list[A.Expr]
    detach: bool

    def cursor(self, ctx):
        for frame in self.input.cursor(ctx):
            # two-phase per input row: collect every entity from every
            # clause expression, delete relationships FIRST, then nodes —
            # so DELETE p1, p2 over paths sharing endpoints never trips
            # the has-edges check on a node whose edge dies in the same
            # clause (TCK DeleteAcceptance "Delete paths from nested
            # map/list")
            edges: list = []
            vertices: list = []
            for expr in self.exprs:
                value = ctx.evaluator.eval(expr, frame)
                self._collect(value, edges, vertices)
            for ea in edges:
                if ea.is_visible(View.NEW):
                    ctx.accessor.delete_edge(ea)
                    ctx.stats["relationships_deleted"] += 1
            for va in vertices:
                if va.is_visible(View.NEW):
                    _, deleted_edges = ctx.accessor.delete_vertex(
                        va, detach=self.detach)
                    ctx.stats["nodes_deleted"] += 1
                    ctx.stats["relationships_deleted"] += len(deleted_edges)
            yield frame

    def _collect(self, value, edges, vertices):
        if value is None:
            return
        if isinstance(value, VertexAccessor):
            vertices.append(value)
        elif isinstance(value, EdgeAccessor):
            edges.append(value)
        elif isinstance(value, Path):
            edges.extend(value.edges())
            vertices.extend(value.vertices())
        elif isinstance(value, (list, tuple)):
            for item in value:
                self._collect(item, edges, vertices)
        else:
            raise TypeException(
                f"DELETE on {V.type_name(value)} is not supported")


@dataclass
class SetHopsLimit(LogicalOperator):
    input: LogicalOperator
    limit: int

    def cursor(self, ctx):
        ctx.hops_budget = self.limit
        ctx.hops_initial = self.limit
        yield from self.input.cursor(ctx)


class Argument(LogicalOperator):
    """Subplan leaf: yields the frame installed by _run_subplan (the cached
    plan itself stays immutable, so concurrent executions can share it —
    same role as the reference/Neo4j 'Argument' operator)."""

    input = None

    def cursor(self, ctx):
        yield dict(ctx._argument_frame)


def _run_subplan(subplan: LogicalOperator, ctx, frame) -> list:
    """Execute a subplan (leaf: Argument) against one input frame.

    Materializes the result list so ctx._argument_frame is never observed
    by a suspended generator after it changes.
    """
    prev = getattr(ctx, "_argument_frame", None)
    ctx._argument_frame = frame
    try:
        return list(subplan.cursor(ctx))
    finally:
        ctx._argument_frame = prev


@dataclass
class Optional_(LogicalOperator):
    """OPTIONAL MATCH: run subplan per input row; null-fill on no match."""
    input: LogicalOperator
    subplan: LogicalOperator
    optional_symbols: list[str]

    def cursor(self, ctx):
        for frame in self.input.cursor(ctx):
            subs = _run_subplan(self.subplan, ctx, frame)
            if subs:
                yield from subs
            else:
                new = dict(frame)
                for sym in self.optional_symbols:
                    new[sym] = None
                yield new

    def children(self):
        return [self.input, self.subplan]


@dataclass
class Merge(LogicalOperator):
    """MERGE: try match subplan; else run create subplan. ON CREATE/ON MATCH
    handled by Set* operators appended to the respective subplans."""
    input: LogicalOperator
    match_plan: LogicalOperator
    create_plan: LogicalOperator

    def cursor(self, ctx):
        for frame in self.input.cursor(ctx):
            ctx.check_abort()
            subs = _run_subplan(self.match_plan, ctx, frame)
            if subs:
                yield from subs
            else:
                yield from _run_subplan(self.create_plan, ctx, frame)

    def children(self):
        return [self.input, self.match_plan, self.create_plan]


AGGREGATE_FUNCTIONS = {"count", "sum", "avg", "min", "max", "collect",
                       "stdev", "stdevp", "project",
                       "percentiledisc", "percentilecont"}


@dataclass
class Aggregate(LogicalOperator):
    """Hash aggregation. group_by: (expr, name); aggregations:
    (kind, expr|None, distinct, output name)."""
    input: LogicalOperator
    group_by: list[tuple[A.Expr, str]]
    aggregations: list[tuple[str, Optional[A.Expr], bool, str]]
    remember: list[str] = field(default_factory=list)

    def cursor(self, ctx):
        groups: dict = {}
        order: list = []
        for frame in self.input.cursor(ctx):
            ctx.check_abort()
            key_vals = [ctx.evaluator.eval(e, frame) for e, _ in self.group_by]
            key = tuple(V.hashable_key(v) for v in key_vals)
            if key not in groups:
                state = {
                    "key_vals": key_vals,
                    "frame": {s: frame.get(s) for s in self.remember},
                    "aggs": [_AggState(spec[0], spec[2])
                             for spec in self.aggregations],
                }
                ctx.memory.add_value(key_vals)
                ctx.memory.add(256)   # group bookkeeping overhead
                groups[key] = state
                order.append(key)
            state = groups[key]
            for spec, agg in zip(self.aggregations, state["aggs"]):
                kind, expr = spec[0], spec[1]
                if len(spec) > 4 and spec[4] is not None:
                    # extra constant argument (percentileDisc/Cont's p)
                    agg.param = ctx.evaluator.eval(spec[4], frame)
                value = (ctx.evaluator.eval(expr, frame)
                         if expr is not None else "__row__")
                if agg.seen is not None or kind in (
                        "collect", "project", "percentiledisc",
                        "percentilecont"):
                    # collecting/DISTINCT aggregates retain every value
                    ctx.memory.add_value(value)
                agg.update(value)
        if not groups and not self.group_by:
            # aggregation over empty input yields one row of neutral values
            state = {"key_vals": [], "frame": {},
                     "aggs": [_AggState(spec[0], spec[2])
                              for spec in self.aggregations]}
            groups[()] = state
            order.append(())
        for key in order:
            state = groups[key]
            new = dict(state["frame"])
            for (_, name), val in zip(self.group_by, state["key_vals"]):
                new[name] = val
            for spec, agg in zip(self.aggregations, state["aggs"]):
                new[spec[3]] = agg.result()
            yield new


class _AggState:
    __slots__ = ("kind", "distinct", "seen", "count", "total", "minv",
                 "maxv", "items", "m2", "mean", "param")

    def __init__(self, kind, distinct):
        self.kind = kind
        self.distinct = distinct
        self.seen = set() if distinct else None
        self.count = 0
        self.total = 0
        self.minv = None
        self.maxv = None
        self.items = []
        self.mean = 0.0
        self.m2 = 0.0
        self.param = None    # percentileDisc/Cont's p argument

    def update(self, value):
        kind = self.kind
        if kind == "count" and value == "__row__":
            self.count += 1
            return
        if value is None:
            return
        if self.distinct:
            key = V.hashable_key(value)
            if key in self.seen:
                return
            self.seen.add(key)
        self.count += 1
        if kind == "count":
            return
        if kind == "collect":
            self.items.append(value)
            return
        if kind in ("percentiledisc", "percentilecont"):
            if not V.is_numeric(value):
                raise TypeException(f"{kind}() requires numeric input")
            self.items.append(value)
            return
        if kind == "project":
            self.items.append(value)
            return
        if kind in ("sum", "avg"):
            from ...utils.temporal import Duration
            if not (V.is_numeric(value) or isinstance(value, Duration)):
                raise TypeException(f"{kind}() requires numeric input")
            self.total = value if self.count == 1 else self.total + value
            return
        if kind in ("stdev", "stdevp"):
            if not V.is_numeric(value):
                raise TypeException(f"{kind}() requires numeric input")
            delta = value - self.mean
            self.mean += delta / self.count
            self.m2 += delta * (value - self.mean)
            return
        if kind == "min":
            # full orderability, not comparability: over mixed types the
            # TCK expects e.g. lists < strings < numbers (order_key ranks)
            if self.minv is None or order_key(value) < order_key(self.minv):
                self.minv = value
            return
        if kind == "max":
            if self.maxv is None or order_key(self.maxv) < order_key(value):
                self.maxv = value
            return
        raise SemanticException(f"unknown aggregate {kind}")

    def result(self):
        kind = self.kind
        if kind == "count":
            return self.count
        if kind == "collect":
            return self.items
        if kind == "project":
            # graph projection: collect of paths/nodes into a map
            return {"nodes": [x for x in self.items
                              if isinstance(x, VertexAccessor)],
                    "edges": [x for x in self.items
                              if isinstance(x, EdgeAccessor)]}
        if kind == "sum":
            return self.total if self.count else 0
        if kind == "avg":
            return (self.total / self.count) if self.count else None
        if kind == "min":
            return self.minv
        if kind == "max":
            return self.maxv
        if kind == "stdev":
            if self.count < 2:
                return 0.0 if self.count else None
            return (self.m2 / (self.count - 1)) ** 0.5
        if kind == "stdevp":
            if not self.count:
                return None
            return (self.m2 / self.count) ** 0.5
        if kind in ("percentiledisc", "percentilecont"):
            if not self.items:
                return None  # aggregation over zero rows yields null
            p = self.param
            if not V.is_numeric(p) or not (0.0 <= p <= 1.0):
                raise QueryException(
                    f"NumberOutOfRange: {kind}() percentile must be in "
                    f"[0, 1], got {p!r}")
            xs = sorted(self.items)
            if kind == "percentiledisc":
                # smallest value with cumulative frequency >= p
                import math
                idx = max(0, math.ceil(p * len(xs)) - 1)
                return xs[idx]
            if len(xs) == 1:
                return float(xs[0])
            pos = p * (len(xs) - 1)
            lo = int(pos)
            frac = pos - lo
            if lo + 1 >= len(xs):
                return float(xs[-1])
            return xs[lo] + (xs[lo + 1] - xs[lo]) * frac
        raise SemanticException(f"unknown aggregate {kind}")


@dataclass
class OrderBy(LogicalOperator):
    input: LogicalOperator
    items: list[tuple[A.Expr, bool]]   # (expr, ascending)

    def cursor(self, ctx):
        rows = []
        for frame in self.input.cursor(ctx):
            ctx.check_abort()
            keys = []
            for expr, asc in self.items:
                k = order_key(ctx.evaluator.eval(expr, frame))
                keys.append((k, asc))
            ctx.memory.add_value(frame)
            rows.append((keys, frame))

        import functools

        def compare(a, b):
            for (ka, asc), (kb, _) in zip(a[0], b[0]):
                if ka < kb:
                    return -1 if asc else 1
                if ka > kb:
                    return 1 if asc else -1
            return 0

        rows.sort(key=functools.cmp_to_key(compare))
        for _, frame in rows:
            yield frame


@dataclass
class Skip(LogicalOperator):
    input: LogicalOperator
    expr: A.Expr

    def cursor(self, ctx):
        n = ctx.evaluator.eval(self.expr, {})
        if not isinstance(n, int) or isinstance(n, bool) or n < 0:
            raise TypeException("SKIP must be a non-negative integer")
        yield from itertools.islice(self.input.cursor(ctx), n, None)


@dataclass
class Limit(LogicalOperator):
    input: LogicalOperator
    expr: A.Expr

    def cursor(self, ctx):
        n = ctx.evaluator.eval(self.expr, {})
        if not isinstance(n, int) or isinstance(n, bool):
            raise TypeException("LIMIT must be a non-negative integer")
        # negative literals fail at compile time; a negative PARAMETER
        # "should not generate errors" (TCK OrderByAcceptance) — clamp
        yield from itertools.islice(self.input.cursor(ctx), max(n, 0))


@dataclass
class ScopeBarrier(LogicalOperator):
    """WITH scope close: prune frames to the projected columns so stale
    pre-WITH bindings never leak into later clauses (reference: symbol
    table scoping in semantic/symbol_generator.cpp)."""
    input: LogicalOperator
    columns: list[str]

    def cursor(self, ctx):
        cols = self.columns
        for frame in self.input.cursor(ctx):
            yield {k: frame[k] for k in cols if k in frame}


@dataclass
class Distinct(LogicalOperator):
    input: LogicalOperator
    symbols: list[str]

    def cursor(self, ctx):
        seen = set()
        for frame in self.input.cursor(ctx):
            key = tuple(V.hashable_key(frame.get(s)) for s in self.symbols)
            if key in seen:
                continue
            ctx.memory.add_value(key)
            seen.add(key)
            yield frame


@dataclass
class Unwind(LogicalOperator):
    input: LogicalOperator
    expr: A.Expr
    symbol: str

    def cursor(self, ctx):
        for frame in self.input.cursor(ctx):
            value = ctx.evaluator.eval(self.expr, frame)
            if value is None:
                continue
            if not isinstance(value, (list, tuple)):
                raise TypeException("UNWIND requires a list")
            for item in value:
                new = dict(frame)
                new[self.symbol] = item
                yield new


@dataclass
class CallProcedureOp(LogicalOperator):
    input: LogicalOperator
    proc_name: str
    args: list[A.Expr]
    result_fields: list[str]
    output_symbols: list[str]
    memory_limit: "Optional[int]" = None   # PROCEDURE MEMORY LIMIT, bytes

    def cursor(self, ctx):
        from ..procedures.registry import global_registry
        from ...utils.memory_tracker import (MemoryLimitException,
                                             approx_size)
        proc = global_registry.find(self.proc_name)
        if proc is None:
            raise SemanticException(f"unknown procedure: {self.proc_name}")
        from .planner import _literal_matches_type
        proc_bytes = 0   # yielded-record accounting vs PROCEDURE limit
        for frame in self.input.cursor(ctx):
            ctx.check_abort()
            args = [ctx.evaluator.eval(e, frame) for e in self.args]
            for value, (aname, atype) in zip(args, proc.args):
                if not _literal_matches_type(value, atype):
                    raise TypeException(
                        f"procedure {self.proc_name} argument {aname!r} "
                        f"expects {atype}, got {value!r}")
            if not proc.results:
                # VOID procedure: run for its effects, pass the row through
                # (TCK: "In-query call to VOID procedure does not consume
                # rows"); a ':: ()' procedure instead yields nothing
                for _ in proc.call(ctx, args):
                    pass
                if getattr(proc, "void", False):
                    yield dict(frame)
                continue
            for record in proc.call(ctx, args):
                if self.memory_limit is not None:
                    proc_bytes += approx_size(record)
                    if proc_bytes > self.memory_limit:
                        raise MemoryLimitException(
                            f"procedure {self.proc_name} exceeded its "
                            f"PROCEDURE MEMORY LIMIT of "
                            f"{self.memory_limit} bytes")
                new = dict(frame)
                for fieldname, sym in zip(self.result_fields,
                                          self.output_symbols):
                    if fieldname not in record:
                        raise SemanticException(
                            f"procedure {self.proc_name} did not yield "
                            f"{fieldname!r}")
                    new[sym] = record[fieldname]
                yield new


@dataclass
class PeriodicCommit(LogicalOperator):
    """USING PERIODIC COMMIT n: commit the enclosing autocommit
    transaction and open a fresh one after every n pulled rows, plus once
    more for the remainder when the stream ends (reference:
    plan/operator.cpp PeriodicCommitCursor). Batches already committed
    survive a later failure — the point of the directive for huge loads.

    Graph values in frames stay readable across the boundary: reads
    through a committed accessor see its committed state (round-3
    post-commit visibility semantics), matching the reference where
    accessors outlive PeriodicCommit's internal commits.
    """
    input: LogicalOperator
    frequency: object   # int literal or frontend Parameter

    def cursor(self, ctx):
        freq = self.frequency
        if not isinstance(freq, int):   # $param, resolved at runtime
            freq = ctx.evaluator.eval(freq, {})
            if not isinstance(freq, int) or isinstance(freq, bool) \
                    or freq < 1:
                raise QueryException(
                    "periodic commit frequency must be a positive "
                    f"integer, got {freq!r}")
        owner = getattr(ctx, "_txn_owner", None)
        if owner is None:
            raise QueryException(
                "USING PERIODIC COMMIT requires an implicit (autocommit) "
                "transaction")
        pulled = 0
        for frame in self.input.cursor(ctx):
            ctx.check_abort()
            pulled += 1
            if pulled >= freq:
                owner.renew()
                pulled = 0
            yield frame
        if pulled:
            owner.renew()   # remainder batch, mirroring the reference

    def children(self):
        return [self.input]


@dataclass
class Apply(LogicalOperator):
    """CALL { subquery }: run the subplan per input row; merge returned
    columns (or pass rows through for unit subqueries).

    batch_rows (CALL { } IN TRANSACTIONS OF n ROWS): commit the enclosing
    autocommit transaction and open a fresh one every n input rows —
    periodic-commit batching for huge loads (reference: PeriodicCommit,
    plan/operator.hpp). Restriction: frames crossing the batch boundary
    must not carry graph values (their accessors die with the committed
    transaction); the operator enforces this with a clear error.
    """
    input: LogicalOperator
    subplan: LogicalOperator
    columns: list[str]
    batch_rows: Optional[int] = None

    def cursor(self, ctx):
        since_commit = 0
        for frame in self.input.cursor(ctx):
            ctx.check_abort()
            if self.batch_rows:
                self._guard_frame(frame, "input row")
                if since_commit >= self.batch_rows:
                    self._renew_transaction(ctx)
                    since_commit = 0
            sub_rows = _run_subplan(self.subplan, ctx, frame)
            since_commit += 1
            if not self.columns:
                yield frame  # unit subquery: cardinality preserved
                continue
            for sub in sub_rows:
                row = sub.get("__row__", {})
                merged = dict(frame)
                for col in self.columns:
                    merged[col] = row.get(col, sub.get(col))
                if self.batch_rows:
                    # subquery outputs may outlive this batch's transaction
                    # downstream — graph values would silently go stale
                    self._guard_frame({c: merged[c] for c in self.columns},
                                      "subquery result")
                yield merged

    @staticmethod
    def _contains_graph_value(value) -> bool:
        if isinstance(value, (VertexAccessor, EdgeAccessor, Path)):
            return True
        if isinstance(value, (list, tuple)):
            return any(Apply._contains_graph_value(v) for v in value)
        if isinstance(value, dict):
            return any(Apply._contains_graph_value(v)
                       for v in value.values())
        return False

    @staticmethod
    def _guard_frame(frame: dict, where: str) -> None:
        for key, value in frame.items():
            if key.startswith("__"):
                continue
            if Apply._contains_graph_value(value):
                raise QueryException(
                    "CALL { } IN TRANSACTIONS cannot carry graph values "
                    f"({key}, in the {where}) across batch boundaries — "
                    "their transaction commits mid-query; project scalar "
                    "values (ids, properties) instead")

    @staticmethod
    def _renew_transaction(ctx) -> None:
        if getattr(ctx, "_txn_owner", None) is None:
            raise QueryException(
                "CALL { } IN TRANSACTIONS requires an implicit "
                "(autocommit) transaction")
        ctx._txn_owner.renew()

    def children(self):
        return [self.input, self.subplan]


@dataclass
class Union(LogicalOperator):
    left: LogicalOperator
    right: LogicalOperator
    symbols: list[str]
    distinct: bool

    input: None = None

    def cursor(self, ctx):
        seen = set()
        for plan in (self.left, self.right):
            for frame in plan.cursor(ctx):
                row = frame.get("__row__", {})
                out = {s: row.get(s) for s in self.symbols}
                if self.distinct:
                    key = tuple(V.hashable_key(out[s]) for s in self.symbols)
                    if key in seen:
                        continue
                    seen.add(key)
                yield {**out, "__row__": out}

    def children(self):
        return [self.left, self.right]


@dataclass
class Foreach(LogicalOperator):
    input: LogicalOperator
    symbol: str
    list_expr: A.Expr
    update_plan: LogicalOperator

    def cursor(self, ctx):
        for frame in self.input.cursor(ctx):
            lst = ctx.evaluator.eval(self.list_expr, frame)
            if lst is not None:
                if not isinstance(lst, (list, tuple)):
                    raise TypeException("FOREACH requires a list")
                for item in lst:
                    inner = dict(frame)
                    inner[self.symbol] = item
                    for _ in _run_subplan(self.update_plan, ctx, inner):
                        pass
            yield frame

    def children(self):
        return [self.input, self.update_plan]


@dataclass
class LoadCsvOp(LogicalOperator):
    """Stream rows from a CSV file (reference: operator.hpp:2883 LoadCsv).
    With header → map rows; without → list rows. Values stay strings
    (explicit casts in the query, matching the reference's LOAD CSV)."""
    input: LogicalOperator
    file: A.Expr
    symbol: str
    with_header: bool
    ignore_bad: bool
    delimiter: Optional[A.Expr]
    quote: Optional[A.Expr]

    def cursor(self, ctx):
        cfg = getattr(ctx.interpreter_context, "config", None) or {}
        if not cfg.get("allow_load_csv", True):
            raise QueryException(
                "LOAD CSV is disabled (--no-allow-load-csv)")
        import csv as csvlib
        for frame in self.input.cursor(ctx):
            path = ctx.evaluator.eval(self.file, frame)
            if not isinstance(path, str):
                raise TypeException("LOAD CSV FROM requires a string path")
            delim = (ctx.evaluator.eval(self.delimiter, frame)
                     if self.delimiter is not None else ",")
            quote = (ctx.evaluator.eval(self.quote, frame)
                     if self.quote is not None else '"')
            try:
                f = open(path, newline="", encoding="utf-8")
            except OSError as e:
                raise QueryException(f"cannot open CSV file: {e}") from e
            with f:
                reader = csvlib.reader(f, delimiter=delim, quotechar=quote)
                header = None
                for lineno, row in enumerate(reader):
                    ctx.check_abort()
                    if self.with_header and header is None:
                        header = row
                        continue
                    if self.with_header:
                        if len(row) != len(header):
                            if self.ignore_bad:
                                continue
                            raise QueryException(
                                f"CSV row {lineno + 1} has {len(row)} "
                                f"fields, header has {len(header)}")
                        value = dict(zip(header, row))
                    else:
                        value = list(row)
                    new = dict(frame)
                    new[self.symbol] = value
                    yield new


@dataclass
class LoadJsonlOp(LogicalOperator):
    """Stream objects from a JSON-lines file (reference: LoadJsonl,
    query/jsonl/reader.cppm)."""
    input: LogicalOperator
    file: A.Expr
    symbol: str

    def cursor(self, ctx):
        import json as jsonlib
        for frame in self.input.cursor(ctx):
            path = ctx.evaluator.eval(self.file, frame)
            if not isinstance(path, str):
                raise TypeException("LOAD JSONL FROM requires a string path")
            try:
                f = open(path, encoding="utf-8")
            except OSError as e:
                raise QueryException(f"cannot open JSONL file: {e}") from e
            with f:
                for line in f:
                    ctx.check_abort()
                    line = line.strip()
                    if not line:
                        continue
                    new = dict(frame)
                    new[self.symbol] = jsonlib.loads(line)
                    yield new


@dataclass
class LoadParquetOp(LogicalOperator):
    """Stream rows from a Parquet file via pyarrow (reference: LoadParquet,
    query/arrow_parquet/reader.cppm)."""
    input: LogicalOperator
    file: A.Expr
    symbol: str

    def cursor(self, ctx):
        try:
            import pyarrow.parquet as pq
        except ImportError as e:  # pragma: no cover
            raise QueryException("pyarrow is not available") from e
        for frame in self.input.cursor(ctx):
            path = ctx.evaluator.eval(self.file, frame)
            if not isinstance(path, str):
                raise TypeException("LOAD PARQUET FROM requires a string path")
            table = pq.read_table(path)
            for batch in table.to_batches():
                rows = batch.to_pylist()
                for row in rows:
                    ctx.check_abort()
                    new = dict(frame)
                    new[self.symbol] = row
                    yield new


def _expr_references(expr, names) -> bool:
    """Does an expression tree mention any Identifier in `names`?"""
    import dataclasses
    if isinstance(expr, A.Identifier):
        return expr.name in names
    if dataclasses.is_dataclass(expr) and not isinstance(expr, type):
        return any(_expr_references(getattr(expr, f.name), names)
                   for f in dataclasses.fields(expr))
    if isinstance(expr, (list, tuple)):
        return any(_expr_references(e, names) for e in expr)
    if isinstance(expr, dict):
        return any(_expr_references(e, names) for e in expr.values())
    return False


def _compile_value_fn(expr, parameters):
    """Closure for trivially-evaluable expressions (literal / identifier /
    parameter / constant list subscript) on the bulk lane's per-row hot
    path — mirrors the evaluator's semantics for exactly these shapes.
    None = not compilable, caller keeps the generic evaluator."""
    if isinstance(expr, A.Literal):
        value = expr.value
        return lambda frame: value
    if isinstance(expr, A.Identifier):
        name = expr.name
        return lambda frame: frame.get(name)
    if isinstance(expr, A.Parameter):
        if expr.name not in parameters:
            return None     # let the evaluator raise its own error
        value = parameters[expr.name]
        return lambda frame: value
    if isinstance(expr, A.Subscript) and isinstance(expr.expr, A.Identifier) \
            and isinstance(expr.index, A.Literal):
        name = expr.expr.name
        idx = expr.index.value
        if isinstance(idx, int) and not isinstance(idx, bool):
            def list_item(frame):
                obj = frame.get(name)
                if obj is None:
                    return None
                if isinstance(obj, (list, tuple)):
                    if idx < -len(obj) or idx >= len(obj):
                        return None
                    return obj[idx]
                if isinstance(obj, dict):
                    raise TypeException("map key must be a string")
                raise TypeException("subscript on a non-list value")
            return list_item
        # string subscripts can hit maps OR graph entities at runtime —
        # those keep the generic evaluator
    if isinstance(expr, A.Binary):
        op_fn = _COMPILED_BINOPS.get(expr.op)
        if op_fn is not None:
            lf = _compile_value_fn(expr.left, parameters)
            rf = _compile_value_fn(expr.right, parameters)
            if lf is not None and rf is not None:
                # delegates to the evaluator's own arithmetic functions,
                # so null propagation / type rules stay identical
                return lambda frame: op_fn(lf(frame), rf(frame))
    return None


_COMPILED_BINOPS = {
    "+": V.cypher_add, "-": V.cypher_sub, "*": V.cypher_mul,
    "/": V.cypher_div, "%": V.cypher_mod, "^": V.cypher_pow,
}


@dataclass
class BatchNodeStep:
    """One per-row vertex creation inside the bulk-write fast lane."""
    symbol: str
    labels: list[str]
    properties: object           # dict[str, Expr] | A.Parameter | None


@dataclass
class BatchEdgeStep:
    """One per-row edge creation inside the bulk-write fast lane. Endpoints
    resolve to a same-row BatchNodeStep symbol or a frame-bound vertex."""
    from_symbol: str
    edge_symbol: str
    to_symbol: str
    direction: str               # 'out' | 'in'
    edge_type: str
    edge_properties: object


@dataclass
class BatchCreateGraph(LogicalOperator):
    """Bulk-write fast lane: executes a root chain of CreateNode /
    CreateExpand steps over ALL input rows with one storage
    ``batch_insert()`` call instead of per-row operator pulls — one gid
    reservation, one undo delta per object, bulk-merged index maintenance,
    one WAL record, one change-log bump per batch.

    Installed by query/plan/bulk.py only at the root of write-only plans
    (no downstream consumer exists), so it yields no frames. Engines that
    don't support batch_insert fall back to equivalent per-row creates.

    When the row source is a pure point-lookup pipeline (UNWIND /
    equality-index scans over a simple base), bulk.py additionally folds
    it into `pipeline` and the cursor runs the lookups inline against the
    label+property index — skipping per-row generator frames, dict copies,
    and the Eager barrier's bookkeeping (safe: the batch path defers every
    write until the input is fully consumed anyway).
    """
    input: LogicalOperator
    steps: list                  # BatchNodeStep | BatchEdgeStep, row order
    pipeline_base: object = None   # base operator of the folded pipeline
    pipeline: list = None          # [("unwind", expr, sym) |
    #                                 ("scan", sym, label, props, exprs)]

    def cursor(self, ctx):
        storage = ctx.storage
        acc = ctx.accessor
        if not getattr(storage, "supports_batch_insert", False) \
                or not hasattr(acc, "batch_insert"):
            yield from self._row_fallback(ctx)
            return

        # resolve name->id mappings and compile property maps once per
        # batch, not once per row
        name_to_pid = storage.property_mapper.name_to_id

        def compile_props(properties):
            """[(pid, fn_or_None, expr)] for a static map; None when the
            map itself is dynamic (a $parameter)."""
            if properties is None:
                return ()
            if isinstance(properties, A.Parameter):
                return None
            return [(name_to_pid(k), _compile_value_fn(e, ctx.parameters), e)
                    for k, e in properties.items()]

        resolved = []
        for step in self.steps:
            if isinstance(step, BatchNodeStep):
                resolved.append((step, tuple(
                    storage.label_mapper.name_to_id(l)
                    for l in step.labels),
                    compile_props(step.properties)))
            else:
                resolved.append((step, storage.edge_type_mapper.name_to_id(
                    step.edge_type),
                    compile_props(step.edge_properties)))
        pid_cache: dict[str, int] = {}
        evaluator = ctx.evaluator

        def prop_ids(compiled, properties, frame) -> dict:
            out = {}
            if compiled is None:    # $parameter map: dynamic keys
                for key, value in _eval_prop_map(ctx, properties,
                                                 frame).items():
                    if value is None:
                        continue
                    pid = pid_cache.get(key)
                    if pid is None:
                        pid = name_to_pid(key)
                        pid_cache[key] = pid
                    out[pid] = value
                return out
            for pid, fn, expr in compiled:
                value = fn(frame) if fn is not None \
                    else evaluator.eval(expr, frame)
                if value is not None:
                    out[pid] = value
            return out

        vertices: list = []
        edges: list = []
        counters = [0, 0, 0]     # rows, labels_added, props_set
        single = len(resolved) == 1
        first_step, first_ids, first_compiled = resolved[0]
        single_node = single and isinstance(first_step, BatchNodeStep)
        single_edge = single and isinstance(first_step, BatchEdgeStep)

        def process_row(frame):
            counters[0] += 1
            if not counters[0] % 1024:
                ctx.check_abort()
            if single_node:
                # the dominant UNWIND…CREATE-one-node shape, un-dispatched
                props = prop_ids(first_compiled, first_step.properties,
                                 frame)
                vertices.append((first_ids, props))
                counters[1] += len(first_ids)
                counters[2] += len(props)
                return
            if single_edge:
                # the dominant MATCH-endpoints…CREATE-one-edge shape
                from_ref = frame.get(first_step.from_symbol)
                if isinstance(from_ref, VertexAccessor):
                    from_ref = from_ref.vertex
                elif not isinstance(from_ref, Vertex):
                    raise QueryException(
                        "CREATE edge endpoint is not a node")
                to_ref = frame.get(first_step.to_symbol)
                if isinstance(to_ref, VertexAccessor):
                    to_ref = to_ref.vertex
                elif not isinstance(to_ref, Vertex):
                    raise QueryException(
                        "CREATE edge endpoint is not a node")
                if first_compiled == ():
                    props = None     # no property map: share the no-op
                else:
                    props = prop_ids(first_compiled,
                                     first_step.edge_properties, frame)
                    counters[2] += len(props)
                if first_step.direction == "in":
                    from_ref, to_ref = to_ref, from_ref
                edges.append((first_ids, from_ref, to_ref, props))
                return
            refs: dict[str, object] = {}
            for step, ids, compiled in resolved:
                if isinstance(step, BatchNodeStep):
                    props = prop_ids(compiled, step.properties, frame)
                    refs[step.symbol] = len(vertices)
                    vertices.append((ids, props))
                    counters[1] += len(ids)
                    counters[2] += len(props)
                else:
                    from_ref = refs.get(step.from_symbol)
                    if from_ref is None:
                        from_ref = frame.get(step.from_symbol)
                        if isinstance(from_ref, VertexAccessor):
                            from_ref = from_ref.vertex
                        elif not isinstance(from_ref, Vertex):
                            raise QueryException(
                                "CREATE edge endpoint is not a node")
                    to_ref = refs.get(step.to_symbol)
                    if to_ref is None:
                        to_ref = frame.get(step.to_symbol)
                        if isinstance(to_ref, VertexAccessor):
                            to_ref = to_ref.vertex
                        elif not isinstance(to_ref, Vertex):
                            raise QueryException(
                                "CREATE edge endpoint is not a node")
                    props = prop_ids(compiled, step.edge_properties, frame)
                    counters[2] += len(props)
                    if step.direction == "in":
                        from_ref, to_ref = to_ref, from_ref
                    edges.append((ids, from_ref, to_ref, props))

        self._drive_rows(ctx, process_row)
        acc.batch_insert(vertices, edges)
        ctx.stats["nodes_created"] += len(vertices)
        ctx.stats["relationships_created"] += len(edges)
        ctx.stats["labels_added"] += counters[1]
        ctx.stats["properties_set"] += counters[2]
        return
        yield  # pragma: no cover — marks cursor() as a generator

    def _drive_rows(self, ctx, process_row):
        """Feed frames to process_row: the folded point-lookup pipeline
        when usable, else the generic input subtree (minus a redundant top
        Eager barrier — the batch path defers every write past input
        exhaustion, which is exactly the guarantee Eager provides)."""
        if self.pipeline is not None and ctx.accessor.fine_grained is None:
            resolved = self._resolve_pipeline(ctx)
            if resolved == "empty":
                return
            if resolved is not None:
                self._pipeline_run(ctx, resolved, process_row)
                return
        source = self.input
        if isinstance(source, Eager):
            source = source.input
        for frame in source.cursor(ctx):
            process_row(frame)

    def _resolve_pipeline(self, ctx):
        """Map stage names to ids; None = fall back to the generic source
        (an equality scan without its composite index), "empty" = an
        unknown label/property name can match nothing."""
        storage = ctx.storage
        out = []
        for stage in self.pipeline:
            if stage[0] == "unwind":
                out.append(stage)
                continue
            _tag, sym, label, props, exprs = stage
            lid = storage.label_mapper.maybe_name_to_id(label)
            pids = tuple(storage.property_mapper.maybe_name_to_id(p)
                         for p in props)
            if lid is None or any(p is None for p in pids):
                return "empty"
            slot = storage.indices.label_property._index.get((lid, pids))
            if slot is None:
                return None
            out.append(("scan", sym, lid, pids, exprs, slot["eq"]))
        return out

    def _steps_reference(self, names) -> bool:
        """True when any step property expression references one of
        `names` (then frames must carry full accessors, not raw
        vertices)."""
        for step in self.steps:
            props = step.properties if isinstance(step, BatchNodeStep) \
                else step.edge_properties
            if props is None:
                continue
            exprs = props.values() if isinstance(props, dict) else [props]
            for e in exprs:
                if _expr_references(e, names):
                    return True
        return False

    def _pipeline_run(self, ctx, stages, emit):
        from ...storage.mvcc import state_is_current
        evaluator = ctx.evaluator
        view = ctx.view
        acc = ctx.accessor
        txn = acc.txn
        n_stages = len(stages)
        # bind raw Vertex objects for scan symbols no step expression
        # reads back — skips one accessor allocation per matched row
        scan_syms = {s[1] for s in stages if s[0] == "scan"}
        raw_bind = not self._steps_reference(scan_syms)

        def compiled(exprs):
            return [(_compile_value_fn(e, ctx.parameters), e)
                    for e in exprs]

        stages = [
            ("unwind", compiled([stage[1]])[0], stage[2])
            if stage[0] == "unwind" else
            ("scan", stage[1], stage[2], stage[3], compiled(stage[4]),
             stage[5])
            for stage in stages]

        def flat_run():
            """Fully-inlined loop for THE bulk-load shape — one UNWIND
            followed only by equality scans — avoiding a Python frame per
            stage per row. Multi-candidate or composite-key rows fall back
            to the generic expand() from the stage that needs it."""
            from ...storage.common import (TRANSACTION_ID_START,
                                           IsolationLevel)
            _t0, (ufn, uexpr), usym = stages[0]
            scan_stages = stages[1:]
            txn_id = txn.id
            # effective_start_ts is constant during execution under
            # snapshot isolation (the default) — hoist it; other levels
            # keep the per-candidate call
            si_mode = txn.isolation is IsolationLevel.SNAPSHOT_ISOLATION \
                and view is View.NEW
            start_ts = txn.effective_start_ts() if si_mode else 0
            for base_frame in self.pipeline_base.cursor(ctx):
                frame = base_frame
                lst = ufn(frame) if ufn is not None \
                    else evaluator.eval(uexpr, frame)
                if lst is None:
                    continue
                if not isinstance(lst, (list, tuple)):
                    raise TypeException("UNWIND requires a list")
                for item in lst:
                    frame[usym] = item
                    ok = True
                    si = 1
                    for stage in scan_stages:
                        _t, sym, lid, pids, exprs, eq = stage
                        if len(exprs) != 1:
                            ok = None      # composite key: generic path
                            break
                        fn, e = exprs[0]
                        v0 = fn(frame) if fn is not None \
                            else evaluator.eval(e, frame)
                        if v0 is None:
                            ok = False
                            break
                        bucket = eq.get((order_key(v0),))
                        if not bucket:
                            ok = False
                            break
                        if len(bucket) != 1:
                            ok = None      # cartesian: generic path
                            break
                        vertex = bucket[0]
                        lock = vertex.lock
                        lock.acquire()
                        if si_mode:
                            d = vertex.delta
                            current = d is None or \
                                (ts := d.commit_info.timestamp) == txn_id \
                                or (ts < TRANSACTION_ID_START
                                    and ts <= start_ts)
                        else:
                            current = state_is_current(vertex, txn, view)
                        if current:
                            bad = (vertex.deleted
                                   or lid not in vertex.labels
                                   or vertex.properties.get(pids[0]) != v0)
                            lock.release()
                        else:
                            lock.release()
                            st = acc._vertex_state(vertex, view, False)
                            bad = (not st.exists or st.deleted
                                   or lid not in st.labels
                                   or st.properties.get(pids[0]) != v0)
                        if bad:
                            ok = False
                            break
                        frame[sym] = vertex if raw_bind \
                            else VertexAccessor(vertex, acc)
                        si += 1
                    if ok:
                        emit(frame)
                    elif ok is None:
                        expand(frame, si)
                frame.pop(usym, None)

        def expand(frame, si):
            if si == n_stages:
                emit(frame)
                return
            stage = stages[si]
            if stage[0] == "unwind":
                _t, (fn, expr), sym = stage
                value = fn(frame) if fn is not None \
                    else evaluator.eval(expr, frame)
                if value is None:
                    return
                if not isinstance(value, (list, tuple)):
                    raise TypeException("UNWIND requires a list")
                nxt = si + 1
                for item in value:
                    frame[sym] = item
                    expand(frame, nxt)
                frame.pop(sym, None)
                return
            _t, sym, lid, pids, exprs, eq = stage
            if len(exprs) == 1:
                fn, e = exprs[0]
                v0 = fn(frame) if fn is not None \
                    else evaluator.eval(e, frame)
                if v0 is None:
                    return  # = null never matches
                values = (v0,)
                candidates = eq.get((order_key(v0),))
            else:
                values = [fn(frame) if fn is not None
                          else evaluator.eval(e, frame) for fn, e in exprs]
                if None in values:
                    return
                candidates = eq.get(tuple(order_key(v) for v in values))
            if candidates is None:
                return
            nxt = si + 1
            for vertex in candidates:
                # settled fast check: when the reader's view equals the
                # live fields, validate against them directly — no
                # MaterializedState allocation or dict/set copies
                lock = vertex.lock
                lock.acquire()
                if state_is_current(vertex, txn, view):
                    try:
                        if vertex.deleted or lid not in vertex.labels:
                            continue
                        props = vertex.properties
                        skip = False
                        for p, v in zip(pids, values):
                            if props.get(p) != v:
                                skip = True
                                break
                        if skip:
                            continue
                    finally:
                        lock.release()
                else:
                    lock.release()
                    st = acc._vertex_state(vertex, view, False)
                    if not st.exists or st.deleted or lid not in st.labels:
                        continue
                    props = st.properties
                    skip = False
                    for p, v in zip(pids, values):
                        if props.get(p) != v:
                            skip = True
                            break
                    if skip:
                        continue
                frame[sym] = vertex if raw_bind \
                    else VertexAccessor(vertex, acc)
                expand(frame, nxt)
            frame.pop(sym, None)

        if n_stages and stages[0][0] == "unwind" \
                and all(s[0] == "scan" for s in stages[1:]):
            flat_run()
            return
        for base_frame in self.pipeline_base.cursor(ctx):
            expand(base_frame, 0)

    def _row_fallback(self, ctx):
        """Per-row creates with identical semantics, for engines without
        batch_insert (the on-disk engine)."""
        storage = ctx.storage
        for frame in self.input.cursor(ctx):
            ctx.check_abort()
            env = dict(frame)
            for step in self.steps:
                if isinstance(step, BatchNodeStep):
                    va = ctx.accessor.create_vertex()
                    ctx.stats["nodes_created"] += 1
                    for label in step.labels:
                        va.add_label(storage.label_mapper.name_to_id(label))
                        ctx.stats["labels_added"] += 1
                    for key, value in _eval_prop_map(
                            ctx, step.properties, frame).items():
                        if value is not None:
                            va.set_property(
                                storage.property_mapper.name_to_id(key),
                                value)
                            ctx.stats["properties_set"] += 1
                    env[step.symbol] = va
                else:
                    from_v = env.get(step.from_symbol)
                    to_v = env.get(step.to_symbol)
                    if not isinstance(from_v, VertexAccessor) or \
                            not isinstance(to_v, VertexAccessor):
                        raise QueryException(
                            "CREATE edge endpoint is not a node")
                    tid = storage.edge_type_mapper.name_to_id(step.edge_type)
                    if step.direction == "in":
                        ea = ctx.accessor.create_edge(to_v, from_v, tid)
                    else:
                        ea = ctx.accessor.create_edge(from_v, to_v, tid)
                    ctx.stats["relationships_created"] += 1
                    for key, value in _eval_prop_map(
                            ctx, step.edge_properties, frame).items():
                        if value is not None:
                            ea.set_property(
                                storage.property_mapper.name_to_id(key),
                                value)
                            ctx.stats["properties_set"] += 1
                    env[step.edge_symbol] = ea
        return
        yield  # pragma: no cover


@dataclass
class Accumulate(LogicalOperator):
    """Materialize all input rows before streaming (write barrier between
    updating clauses and RETURN — reference: Accumulate operator)."""
    input: LogicalOperator

    def cursor(self, ctx):
        rows = []
        for frame in self.input.cursor(ctx):
            ctx.memory.add_value(frame)
            rows.append(frame)
        yield from rows
