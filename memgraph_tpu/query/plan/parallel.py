"""Intra-query parallel execution: columnar scan+filter+aggregate.

TPU-first analog of the reference's parallel operators
(/root/reference/src/query/plan/operator.hpp:1925-2273 — ScanAllParallel,
AggregateParallelBase, ParallelMerge — and the plan rewriter in
plan/rewrite/parallel_rewrite.hpp). Instead of sharding the Volcano
iterator across a thread pool, an eligible
    Produce <- Aggregate <- Filter* <- ScanAll[ByLabel] <- Once
tail is collapsed into ONE operator that evaluates the filters and
aggregates as whole-column vectorized kernels over a cached columnar
snapshot (ops/columnar.py). Anything the columnar engine cannot express
falls back to the original row-at-a-time subplan at runtime — semantics
are identical by construction, the rewrite is purely an execution
strategy.

Eligibility (matched at plan time):
  - Aggregate with no GROUP BY keys, aggregations in
    count(*)/count/sum/min/max/avg, non-DISTINCT, over a property of the
    scanned symbol;
  - filters that AND-decompose into `sym.prop <op> literal/parameter`
    (op in =, <>, <, <=, >, >=) or a redundant label test on the scan's
    own label.

Cypher three-valued logic is preserved: a predicate over an absent
property is NULL -> row excluded; cross-type equality is false; ordering
comparisons across types are NULL (both exclude); count/sum over zero
rows are 0, min/max/avg are NULL.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, fields
from typing import Optional

import numpy as np

from ...ops.columnar import COLUMNAR_CACHE
from ..frontend import ast as A
from . import operators as Op

_CMP_OPS = {"=", "<>", "<", "<=", ">", ">="}
_AGG_KINDS = {"count", "sum", "min", "max", "avg"}

# below this row count the row-at-a-time path is cheaper than a column
# sweep (and than a device dispatch, once offloaded); hints force through
MIN_ROWS = int(os.environ.get("MEMGRAPH_TPU_PARALLEL_MIN_ROWS", 1024))


class _Unsupported(Exception):
    pass


@dataclass
class ParallelScanAggregate(Op.LogicalOperator):
    """Single-operator columnar scan+filter+aggregate with row fallback."""
    input: Op.LogicalOperator          # Once
    fallback: Op.LogicalOperator       # the original Aggregate subplan
    symbol: str
    label: Optional[str]
    predicates: list                   # [(prop, op, rhs A.Expr), ...]
    aggregations: list                 # [(kind, prop|None, out name), ...]
    group_by: list = None              # [(prop, out name), ...] | None
    hinted: bool = False

    def cursor(self, ctx):
        try:
            if self.group_by:
                rows = self._columnar_groups(ctx)
            else:
                rows = [self._columnar_row(ctx)]
        except _Unsupported:
            yield from self.fallback.cursor(ctx)
            return
        yield from rows

    # -- columnar path ----------------------------------------------------

    def _snapshot_base(self, ctx, extra_props=()):
        """Columnar snapshot + base validity mask (None = every row),
        BEFORE predicates — the compiled lane (query/plan/lane.py)
        shares this and fuses the predicate masks into its device
        program instead of applying them host-side."""
        props = tuple(sorted(
            {p for p, _, _ in self.predicates}
            | {p for _, p, _ in self.aggregations if p is not None}
            | set(extra_props)))
        snap = COLUMNAR_CACHE.get(ctx.accessor, self.label, props,
                                  ctx.view, abort_check=ctx.check_abort)
        ctx.check_abort()
        if snap.n < MIN_ROWS and not self.hinted:
            raise _Unsupported
        return snap, None

    def _snapshot_and_mask(self, ctx, extra_props=()):
        """Shared preamble: columnar snapshot + predicate mask."""
        snap, base = self._snapshot_base(ctx, extra_props)
        mask = np.ones(snap.n, dtype=bool) if base is None \
            else base.copy()
        for prop, op, rhs_expr in self.predicates:
            mask &= _pred_mask(ctx, snap, prop, op, rhs_expr)
        return snap, mask

    def _columnar_row(self, ctx) -> dict:
        snap, mask = self._snapshot_and_mask(ctx)
        out: dict = {}
        for kind, prop, name in self.aggregations:
            out[name] = self._aggregate(snap, mask, kind, prop)
        return out

    def _columnar_groups(self, ctx) -> list:
        """Grouped aggregation: np.unique-keyed groups in FIRST-SEEN
        order (matching the hash aggregation's emission order)."""
        snap, mask = self._snapshot_and_mask(
            ctx, extra_props=[p for p, _ in self.group_by])
        idx = np.flatnonzero(mask)
        if idx.size == 0:
            return []                   # grouped agg over 0 rows: no rows

        # composite group key: per-key (presence, value) columns. Mixed
        # or exotic columns fall back; big ints would merge under the
        # composite's float64 promotion; mixed numerics lose the
        # original per-row value type the row path emits.
        key_cols = []
        decoders = []
        for prop, _name in self.group_by:
            col = snap.columns.get(prop)
            if col is None or col.kind == "other":
                if col is not None and not col.present.any():
                    key_cols.append(np.zeros(idx.size, dtype=np.int8))
                    decoders.append(("null", None))
                    continue
                raise _Unsupported
            if (col.kind == "int" and col.big) or                     (col.kind == "float" and col.mixed):
                raise _Unsupported
            present = col.present[idx]
            vals = np.where(present, col.values[idx], 0)
            key_cols.append(np.where(present, 1, 0).astype(np.int8))
            key_cols.append(vals)
            decoders.append((col.kind, col))
        combo = np.stack(key_cols, axis=1)
        _, first_pos, inverse = np.unique(
            combo, axis=0, return_index=True, return_inverse=True)
        n_groups = first_pos.size
        # emission order = first appearance of each group
        emit_order = np.argsort(first_pos, kind="stable")

        out_rows = [dict() for _ in range(n_groups)]
        # group key values (decoded back to engine values)
        ki = 0
        for (kind, col), (prop, name) in zip(decoders, self.group_by):
            if kind == "null":
                for g in range(n_groups):
                    out_rows[g][name] = None
                ki += 1
                continue
            pres_col = key_cols[ki]
            val_col = key_cols[ki + 1]
            ki += 2
            decode = _vocab_decode(col) if kind == "str" else None
            for g in range(n_groups):
                row0 = first_pos[g]
                if not pres_col[row0]:
                    out_rows[g][name] = None
                elif kind == "str":
                    out_rows[g][name] = decode[val_col[row0]]
                elif kind == "bool":
                    out_rows[g][name] = bool(val_col[row0])
                elif kind == "int":
                    out_rows[g][name] = int(val_col[row0])
                else:
                    out_rows[g][name] = float(val_col[row0])

        for kind, prop, name in self.aggregations:
            if kind == "count" and prop is None:
                counts = np.bincount(inverse, minlength=n_groups)
                for g in range(n_groups):
                    out_rows[g][name] = int(counts[g])
                continue
            col = snap.columns[prop]
            present = col.present[idx]
            if kind == "count":
                # needs only presence: works for EVERY column kind
                counts = np.bincount(inverse[present],
                                     minlength=n_groups)
                for g in range(n_groups):
                    out_rows[g][name] = int(counts[g])
                continue
            if col.kind not in ("int", "float"):
                raise _Unsupported
            if col.kind == "int" and col.big:
                raise _Unsupported
            vals = col.values[idx]
            sel = present
            counts = np.bincount(inverse[sel], minlength=n_groups)
            if kind in ("min", "max"):
                fvals = vals.astype(np.float64)
                fill = np.inf if kind == "min" else -np.inf
                acc = np.full(n_groups, fill)
                ufn = np.minimum if kind == "min" else np.maximum
                ufn.at(acc, inverse[sel], fvals[sel])
                for g in range(n_groups):
                    if counts[g] == 0:
                        out_rows[g][name] = None
                    elif col.kind == "int":
                        out_rows[g][name] = int(acc[g])
                    else:
                        out_rows[g][name] = float(acc[g])
                continue
            if col.kind == "int":
                # EXACT int accumulation (np.add.at on int64); the row
                # path sums arbitrary-precision python ints, so guard
                # potential int64 wrap the same way the ungrouped path
                # guards float drift
                sel_vals = vals[sel]
                if sel_vals.size and int(np.abs(sel_vals).max()) >                         (2**62) // max(int(counts.max()), 1):
                    sums = [0] * n_groups
                    for gi, v in zip(inverse[sel], sel_vals):
                        sums[gi] += int(v)
                else:
                    acc = np.zeros(n_groups, dtype=np.int64)
                    np.add.at(acc, inverse[sel], sel_vals)
                    sums = acc
            else:
                sums = np.bincount(inverse[sel],
                                   weights=vals[sel].astype(np.float64),
                                   minlength=n_groups)
            for g in range(n_groups):
                if kind == "sum":
                    out_rows[g][name] = (int(sums[g])
                                         if col.kind == "int"
                                         else float(sums[g]))
                else:                   # avg
                    out_rows[g][name] = (float(sums[g] / counts[g])
                                         if counts[g] else None)
        return [out_rows[g] for g in emit_order]

    def _aggregate(self, snap, mask, kind, prop):
        if kind == "count" and prop is None:
            return int(mask.sum())
        col = snap.columns[prop]
        sel = mask & col.present
        if kind == "count":
            return int(sel.sum())
        if col.kind not in ("int", "float"):
            raise _Unsupported      # sum/min/max/avg over non-numerics
        vals = col.values[sel]
        if kind == "sum":
            if vals.size == 0:
                return 0
            if col.kind == "int":
                # int64 accumulation can wrap; the row path sums exact
                # Python ints. Guard: re-sum exactly when magnitudes
                # could overflow.
                if int(np.abs(vals).max()) > (2**62) // max(vals.size, 1):
                    return sum(int(v) for v in vals)
                return int(vals.sum())
            return float(vals.sum())
        if vals.size == 0:
            return None             # min/max/avg over no rows
        if kind == "min":
            m = vals.min()
        elif kind == "max":
            m = vals.max()
        else:
            return float(vals.mean())
        return int(m) if col.kind == "int" else float(m)



def _gid_rows(sorted_gids: np.ndarray, order: np.ndarray,
              query: np.ndarray) -> np.ndarray:
    """Vectorized gid -> row lookup: returns row indices into the
    original (unsorted) gid array, -1 where absent."""
    if len(sorted_gids) == 0:   # empty endpoint snapshot: nothing matches
        return np.full(len(query), -1, dtype=np.int64)
    pos = np.searchsorted(sorted_gids, query)
    pos_c = np.clip(pos, 0, len(sorted_gids) - 1)
    hit = sorted_gids[pos_c] == query
    return np.where(hit, order[pos_c], -1)


def _gather_column(col, rows: np.ndarray, valid: np.ndarray):
    """Column indexed at `rows` (edge-aligned): rows<0 or ~valid are
    absent. Shares vocab and exactness flags with the source column."""
    from ...ops.columnar import Column
    ok = valid & (rows >= 0)
    rows_c = np.clip(rows, 0, max(len(col.present) - 1, 0))
    if len(col.present) == 0:
        return Column(col.kind, None if col.values is None
                      else col.values[:0], np.zeros(len(rows), dtype=bool),
                      col.vocab, col.big, col.mixed)
    present = ok & col.present[rows_c]
    values = None if col.values is None else col.values[rows_c]
    return Column(col.kind, values, present, col.vocab, col.big, col.mixed)


@dataclass
class ParallelExpandAggregate(ParallelScanAggregate):
    """Columnar collapse of a single-hop expand+aggregate tail:

        Aggregate <- Filter* <- Expand <- Filter* <- ScanAll[ByLabel] <- Once

    One row per visible edge (oriented by `direction`); endpoint
    properties are gathered from the label-restricted vertex snapshots
    via vectorized gid lookups, so predicates/aggregations/group-keys
    run as the same whole-column kernels as ParallelScanAggregate —
    property keys are role-qualified: "n0.x" (scan node), "n1.x"
    (expanded node), "e.x" (edge). Inherits the grouped/ungrouped
    aggregation kernels unchanged.

    Reference analog: the parallel Expand+Aggregate pipelines the
    enterprise rewriter builds (plan/rewrite/parallel_rewrite.hpp); here
    the edge table IS the parallel axis, matching how the MXU kernels
    treat edges (ops/spmv_mxu.py).
    """
    b_label: Optional[str] = None      # LabelsTest on the expanded node
    direction: str = "out"
    edge_types: Optional[list] = None

    def _snapshot_and_mask(self, ctx, extra_props=()):
        snap, valid = self._snapshot_base(ctx, extra_props)
        mask = valid.copy()
        for key, op, rhs_expr in self.predicates:
            mask &= _pred_mask(ctx, snap, key, op, rhs_expr)
        return snap, mask

    def _snapshot_base(self, ctx, extra_props=()):
        """Edge-aligned columnar snapshot + orientation validity mask,
        BEFORE predicates (shared with the compiled lane)."""
        from ...ops.columnar import ColumnarSnapshot
        role_props: dict = {"n0": set(), "n1": set(), "e": set()}
        for key, _, _ in self.predicates:
            role, _, prop = key.partition(".")
            role_props[role].add(prop)
        for _, key, _ in self.aggregations:
            if key is not None:
                role, _, prop = key.partition(".")
                role_props[role].add(prop)
        for key in extra_props:
            role, _, prop = key.partition(".")
            role_props[role].add(prop)

        acc = ctx.accessor
        edges = COLUMNAR_CACHE.get_edges(
            acc, tuple(sorted(role_props["e"])), ctx.view,
            abort_check=ctx.check_abort)
        ctx.check_abort()
        if edges.n < MIN_ROWS and not self.hinted:
            raise _Unsupported
        a_snap = COLUMNAR_CACHE.get(acc, self.label,
                                    tuple(sorted(role_props["n0"])),
                                    ctx.view, abort_check=ctx.check_abort)
        b_snap = COLUMNAR_CACHE.get(acc, self.b_label,
                                    tuple(sorted(role_props["n1"])),
                                    ctx.view, abort_check=ctx.check_abort)
        ctx.check_abort()

        type_mask = np.ones(edges.n, dtype=bool)
        if self.edge_types:
            ids = [tid for tid in
                   (ctx.storage.edge_type_mapper.maybe_name_to_id(t)
                    for t in self.edge_types) if tid is not None]
            type_mask = np.isin(edges.type_ids,
                                np.asarray(ids, dtype=np.int32))

        # orient rows: n0 = the scanned side, n1 = the expanded side
        if self.direction == "out":
            orientations = [(edges.src, edges.dst, None)]
        elif self.direction == "in":
            orientations = [(edges.dst, edges.src, None)]
        else:   # both: each edge row twice (u->v and v->u), a self-loop
            # only once — matching the row path's expand-both semantics
            not_loop = edges.src != edges.dst
            orientations = [(edges.src, edges.dst, None),
                            (edges.dst, edges.src, not_loop)]

        a_order = np.argsort(a_snap.gids, kind="stable")
        a_sorted = a_snap.gids[a_order]
        b_order = np.argsort(b_snap.gids, kind="stable")
        b_sorted = b_snap.gids[b_order]

        parts = []       # (edge_row_idx, a_rows, b_rows, valid)
        for n0_gids, n1_gids, extra_mask in orientations:
            a_rows = _gid_rows(a_sorted, a_order, n0_gids)
            b_rows = _gid_rows(b_sorted, b_order, n1_gids)
            valid = type_mask & (a_rows >= 0) & (b_rows >= 0)
            if extra_mask is not None:
                valid = valid & extra_mask
            parts.append((np.arange(edges.n), a_rows, b_rows, valid))
        erow = np.concatenate([p[0] for p in parts])
        a_rows = np.concatenate([p[1] for p in parts])
        b_rows = np.concatenate([p[2] for p in parts])
        valid = np.concatenate([p[3] for p in parts])

        snap = ColumnarSnapshot(n=len(erow), gids=edges.gids[erow])
        for prop in role_props["n0"]:
            snap.columns[f"n0.{prop}"] = _gather_column(
                a_snap.columns[prop], a_rows, valid)
        for prop in role_props["n1"]:
            snap.columns[f"n1.{prop}"] = _gather_column(
                b_snap.columns[prop], b_rows, valid)
        for prop in role_props["e"]:
            snap.columns[f"e.{prop}"] = _gather_column(
                edges.columns[prop], erow, valid)
        return snap, valid


def _pred_mask(ctx, snap, prop, op, rhs_expr) -> np.ndarray:
    rhs = ctx.evaluator.eval(rhs_expr, {})
    col = snap.columns[prop]
    n = snap.n
    if rhs is None:
        return np.zeros(n, dtype=bool)       # NULL comparison -> NULL
    if col.kind == "other":
        if not col.present.any():
            # vacuous column: no present value, every row excluded
            return np.zeros(n, dtype=bool)
        raise _Unsupported
    if isinstance(rhs, bool):
        if col.kind != "bool":
            return _type_mismatch(col, op, n)
        rhs_v: object = 1 if rhs else 0
    elif isinstance(rhs, (int, float)):
        if col.kind not in ("int", "float"):
            return _type_mismatch(col, op, n)
        # cross-dtype compare happens in float64; beyond 2^53 that
        # diverges from the row path's exact int-vs-float compare
        if col.kind == "int" and isinstance(rhs, float) and col.big:
            raise _Unsupported
        if col.kind == "float" and isinstance(rhs, int) \
                and not -2**53 <= rhs <= 2**53:
            raise _Unsupported
        rhs_v = rhs
    elif isinstance(rhs, str):
        if col.kind != "str":
            return _type_mismatch(col, op, n)
        if op not in ("=", "<>"):
            raise _Unsupported  # lexicographic order not dict-coded
        code = col.vocab.get(rhs)
        if code is None:
            return (np.zeros(n, dtype=bool) if op == "=" else
                    col.present.copy())
        eq = (col.values == code) & col.present
        return eq if op == "=" else (~eq & col.present)
    else:
        raise _Unsupported                   # list/map/temporal rhs
    v = col.values
    if op == "=":
        m = v == rhs_v
    elif op == "<>":
        m = v != rhs_v
    elif op == "<":
        m = v < rhs_v
    elif op == "<=":
        m = v <= rhs_v
    elif op == ">":
        m = v > rhs_v
    else:
        m = v >= rhs_v
    return m & col.present

def _vocab_decode(col):
    """code -> string array for a dict-coded str column."""
    decode = np.empty(len(col.vocab), dtype=object)
    for s, code in col.vocab.items():
        decode[code] = s
    return decode


def _type_mismatch(col, op, n) -> np.ndarray:
    # Cypher: cross-type equality is false, <> is true (for non-null
    # values); ordering across types is NULL. All exclude on =/</...;
    # <> keeps every present row.
    if op == "<>":
        return col.present.copy()
    return np.zeros(n, dtype=bool)
# -------------------------------------------------------------------------
# plan rewrite
# -------------------------------------------------------------------------

def _split_and(expr):
    if isinstance(expr, A.Binary) and expr.op == "AND":
        return _split_and(expr.left) + _split_and(expr.right)
    return [expr]


def _as_predicate(cond, sym: str, label: Optional[str]):
    """Return (prop, op, rhs_expr) if `cond` is columnar-expressible on
    `sym`, None otherwise."""
    if isinstance(cond, A.LabelsTest) and \
            isinstance(cond.expr, A.Identifier) and cond.expr.name == sym \
            and label is not None and cond.labels == [label]:
        return ()  # redundant with the label scan: drop
    if not isinstance(cond, A.Binary) or cond.op not in _CMP_OPS:
        return None
    lhs, rhs, op = cond.left, cond.right, cond.op
    if not _is_prop_of(lhs, sym):
        if not _is_prop_of(rhs, sym):
            return None
        lhs, rhs = rhs, lhs
        op = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}.get(op, op)
    if not _is_const(rhs):
        return None
    return (lhs.prop, op, rhs)


def _is_const(e) -> bool:
    if isinstance(e, (A.Literal, A.Parameter)):
        return True
    return (isinstance(e, A.Unary) and e.op in ("-", "+")
            and isinstance(e.expr, A.Literal))


def _is_prop_of(e, sym: str) -> bool:
    return (isinstance(e, A.PropertyLookup)
            and isinstance(e.expr, A.Identifier) and e.expr.name == sym)


def _match_tail(agg: Op.Aggregate, hinted: bool):
    """Match Aggregate <- Filter* <- ScanAll[ByLabel] <- Once (with or
    without sym.prop GROUP BY keys)."""
    if agg.remember:
        return None
    aggregations = []
    for spec in agg.aggregations:
        kind, expr, distinct = spec[0], spec[1], spec[2]
        name = spec[3]
        if kind not in _AGG_KINDS or distinct:
            return None
        if len(spec) > 4 and spec[4] is not None:
            return None
        if expr is None:
            if kind != "count":
                return None
            aggregations.append((kind, None, name))
            continue
        if kind == "count" and isinstance(expr, A.Identifier):
            # count(n) over a scanned symbol == count(*): n is never null
            aggregations.append((kind, None, name))
            continue
        if not isinstance(expr, A.PropertyLookup) or \
                not isinstance(expr.expr, A.Identifier):
            return None
        aggregations.append((kind, expr.prop, name))

    filters = []
    node = agg.input
    while isinstance(node, Op.Filter):
        filters.append(node.expr)
        node = node.input
    if isinstance(node, Op.ScanAllByLabel):
        sym, label = node.symbol, node.label
    elif isinstance(node, Op.ScanAll):
        sym, label = node.symbol, None
    else:
        return None
    if not isinstance(node.input, Op.Once):
        return None
    # every aggregated expression must target the scanned symbol
    for spec in agg.aggregations:
        expr = spec[1]
        if expr is None:
            continue
        if isinstance(expr, A.Identifier):
            if expr.name != sym:
                return None
        elif expr.expr.name != sym:
            return None

    group_by = []
    for expr, name in agg.group_by:
        if not (isinstance(expr, A.PropertyLookup)
                and isinstance(expr.expr, A.Identifier)
                and expr.expr.name == sym):
            return None
        group_by.append((expr.prop, name))

    predicates = []
    for f in filters:
        for cond in _split_and(f):
            pred = _as_predicate(cond, sym, label)
            if pred is None:
                return None
            if pred == ():
                continue
            predicates.append(pred)
    return ParallelScanAggregate(
        input=Op.Once(), fallback=agg, symbol=sym, label=label,
        predicates=predicates, aggregations=aggregations,
        group_by=group_by, hinted=hinted)


def _match_expand_tail(agg: Op.Aggregate, hinted: bool):
    """Match Aggregate <- Filter* <- Expand <- Filter* <-
    ScanAll[ByLabel] <- Once (single hop, fresh to-symbol) and rewrite
    to ParallelExpandAggregate with role-qualified property keys."""
    if agg.remember:
        return None

    # walk the tail first so symbols are known for predicate targeting
    upper_filters = []
    node = agg.input
    while isinstance(node, Op.Filter):
        upper_filters.append(node.expr)
        node = node.input
    if not isinstance(node, Op.Expand) or type(node) is not Op.Expand:
        return None
    expand = node
    if expand.direction not in ("out", "in", "both"):
        return None
    if expand.from_symbol == expand.to_symbol:
        return None       # (a)-[]->(a): src==dst constraint not expressed
    if expand.prev_edge_symbols:
        return None
    lower_filters = []
    node = expand.input
    while isinstance(node, Op.Filter):
        lower_filters.append(node.expr)
        node = node.input
    if isinstance(node, Op.ScanAllByLabel):
        a_label = node.label
    elif isinstance(node, Op.ScanAll):
        a_label = None
    else:
        return None
    if node.symbol != expand.from_symbol or \
            not isinstance(node.input, Op.Once):
        return None
    roles = {expand.from_symbol: "n0", expand.to_symbol: "n1",
             expand.edge_symbol: "e"}

    def qualify(sym, prop):
        return f"{roles[sym]}.{prop}"

    aggregations = []
    for spec in agg.aggregations:
        kind, expr, distinct, name = spec[0], spec[1], spec[2], spec[3]
        if kind not in _AGG_KINDS or distinct:
            return None
        if len(spec) > 4 and spec[4] is not None:
            return None
        if expr is None:
            if kind != "count":
                return None
            aggregations.append((kind, None, name))
        elif kind == "count" and isinstance(expr, A.Identifier) \
                and expr.name in roles:
            # count(a)/count(r)/count(b): none can be null in an expand row
            aggregations.append((kind, None, name))
        elif isinstance(expr, A.PropertyLookup) and \
                isinstance(expr.expr, A.Identifier) and \
                expr.expr.name in roles:
            aggregations.append((kind, qualify(expr.expr.name, expr.prop),
                                 name))
        else:
            return None

    group_by = []
    for expr, name in agg.group_by:
        if not (isinstance(expr, A.PropertyLookup)
                and isinstance(expr.expr, A.Identifier)
                and expr.expr.name in roles):
            return None
        group_by.append((qualify(expr.expr.name, expr.prop), name))

    b_label = None
    predicates = []
    for f in upper_filters + lower_filters:
        for cond in _split_and(f):
            # label tests: scan label redundant; ONE single-label test on
            # the expanded node becomes the b-side snapshot restriction
            if isinstance(cond, A.LabelsTest) and \
                    isinstance(cond.expr, A.Identifier):
                sym = cond.expr.name
                if sym == expand.from_symbol and a_label is not None \
                        and cond.labels == [a_label]:
                    continue
                if sym == expand.to_symbol and len(cond.labels) == 1 \
                        and b_label is None:
                    b_label = cond.labels[0]
                    continue
                return None
            matched = False
            for sym in roles:
                pred = _as_predicate(cond, sym, None)
                if pred is not None and pred != ():
                    predicates.append((qualify(sym, pred[0]), pred[1],
                                       pred[2]))
                    matched = True
                    break
            if not matched:
                return None
    return ParallelExpandAggregate(
        input=Op.Once(), fallback=agg, symbol=expand.from_symbol,
        label=a_label, predicates=predicates, aggregations=aggregations,
        group_by=group_by, hinted=hinted, b_label=b_label,
        direction=expand.direction, edge_types=list(expand.edge_types))


@dataclass
class ParallelOrderedScan(Op.LogicalOperator):
    """Columnar ORDER BY over a scan tail: filters + sort keys evaluated
    as whole-column numpy kernels (argsort/lexsort) instead of per-row
    python comparisons — the OrderBy analog of ParallelScanAggregate
    (reference: operator.hpp:1925-2273 parallel operators). Yields SCAN
    frames in final order; the original Produce sits above unchanged.
    Falls back to the row-at-a-time OrderBy on anything the columnar
    engine cannot express (mixed-type columns, temporal keys, ...)."""
    input: Op.LogicalOperator          # Once
    fallback: Op.LogicalOperator       # OrderBy over the original tail
    symbol: str
    label: Optional[str]
    predicates: list
    keys: list                         # [(prop name, ascending)]
    hinted: bool = False

    def cursor(self, ctx):
        try:
            order, gids = self._columnar_order(ctx)
        except _Unsupported:
            yield from self.fallback.cursor(ctx)
            return
        find = ctx.accessor.find_vertex
        for i in order:
            ctx.check_abort()
            va = find(int(gids[i]), ctx.view)
            if va is not None:
                yield {self.symbol: va}

    def _columnar_order(self, ctx):
        props = tuple(sorted({p for p, _, _ in self.predicates}
                             | {p for p, _ in self.keys}))
        snap = COLUMNAR_CACHE.get(ctx.accessor, self.label, props,
                                  ctx.view, abort_check=ctx.check_abort)
        ctx.check_abort()
        if snap.n < MIN_ROWS and not self.hinted:
            raise _Unsupported
        mask = np.ones(snap.n, dtype=bool)
        for prop, op, rhs_expr in self.predicates:
            mask &= _pred_mask(ctx, snap, prop, op, rhs_expr)
        idx = np.flatnonzero(mask)
        # np.lexsort: LAST key is primary -> feed reversed; each sort
        # item contributes (value_key, null_rank) with null_rank primary
        # within the item (openCypher: nulls last ascending, so first
        # under DESC reversal). Stable — tie order matches the row path.
        lex_keys = []
        for prop, asc in reversed(self.keys):
            col = snap.columns.get(prop)
            if col is None or (col.kind == "other"
                               and col.present.any()):
                raise _Unsupported
            if col.kind == "other":        # all-null column: constant key
                continue
            present = col.present[idx]
            nan_rank = np.zeros(len(idx), dtype=np.int8)
            if col.kind == "str":
                decode = np.concatenate([_vocab_decode(col),
                                         np.asarray([""], dtype=object)])
                codes = np.where(present, col.values[idx],
                                 len(col.vocab))
                strings = decode[codes].astype(str)
                uniq, ranks = np.unique(strings, return_inverse=True)
                vals = ranks.astype(np.int64)
            else:
                if col.kind == "int" and col.big:
                    # |v| > 2^53: float64 would merge distinct keys (the
                    # predicate path opts out for the same reason)
                    raise _Unsupported
                vals = col.values[idx].astype(np.float64)
                # openCypher orderability ranks NaN after +inf; negation
                # alone cannot move NaN, so rank it explicitly
                nan = np.isnan(vals)
                if nan.any():
                    vals = np.where(nan, 0.0, vals)
                    nan_rank = (np.where(nan, 1, 0) if asc
                                else np.where(nan, 0, 1)).astype(np.int8)
            if not asc:
                vals = -vals
            null_rank = (np.where(present, 0, 1) if asc
                         else np.where(present, 1, 0))
            lex_keys.append(vals)
            lex_keys.append(nan_rank)
            lex_keys.append(null_rank)     # primary within this item
        if not lex_keys:
            return np.arange(len(idx)), snap.gids[idx]
        order = np.lexsort(lex_keys)
        return order, snap.gids[idx]


def _match_orderby(ob: "Op.OrderBy", hinted: bool):
    """Match OrderBy <- Produce <- Filter* <- ScanAll[ByLabel] <- Once
    with every sort key a property of the scanned symbol."""
    produce = ob.input
    if not isinstance(produce, Op.Produce):
        return None
    filters = []
    node = produce.input
    while isinstance(node, Op.Filter):
        filters.append(node.expr)
        node = node.input
    if isinstance(node, Op.ScanAllByLabel):
        sym, label = node.symbol, node.label
    elif isinstance(node, Op.ScanAll):
        sym, label = node.symbol, None
    else:
        return None
    if not isinstance(node.input, Op.Once):
        return None
    # sort keys arrive either as sym.prop lookups or as projected ALIASES
    # of such lookups (plan_projection rewrites ORDER BY p.age -> age)
    alias_to_prop = {}
    for expr, name in produce.items:
        if isinstance(expr, A.PropertyLookup) and \
                isinstance(expr.expr, A.Identifier) and \
                expr.expr.name == sym:
            alias_to_prop[name] = expr.prop
    keys = []
    fallback_items = []
    for expr, asc in ob.items:
        if isinstance(expr, A.PropertyLookup) and \
                isinstance(expr.expr, A.Identifier) and \
                expr.expr.name == sym:
            prop = expr.prop
        elif isinstance(expr, A.Identifier) and expr.name in alias_to_prop:
            prop = alias_to_prop[expr.name]
        else:
            return None
        keys.append((prop, asc))
        # the fallback sorts PRE-projection frames: keys as sym.prop
        fallback_items.append(
            (A.PropertyLookup(A.Identifier(sym), prop), asc))
    predicates = []
    for f in filters:
        for cond in _split_and(f):
            pred = _as_predicate(cond, sym, label)
            if pred is None:
                return None
            if pred == ():
                continue
            predicates.append(pred)
    # fallback: row OrderBy over the ORIGINAL (unprojected) tail — the
    # Produce above re-projects either way
    fallback = Op.OrderBy(input=produce.input, items=fallback_items)
    scan = ParallelOrderedScan(
        input=Op.Once(), fallback=fallback, symbol=sym, label=label,
        predicates=predicates, keys=keys, hinted=hinted)
    return Op.Produce(input=scan, items=produce.items)


def parallel_rewrite(plan, hinted: bool = False):
    """Walk the plan, replacing eligible Aggregate and OrderBy tails in
    place. Reference analog: plan/rewrite/parallel_rewrite.hpp."""
    if os.environ.get("MEMGRAPH_TPU_DISABLE_PARALLEL"):
        return plan
    if isinstance(plan, Op.Aggregate):
        repl = _match_tail(plan, hinted)
        if repl is None:
            repl = _match_expand_tail(plan, hinted)
        if repl is not None:
            return repl
    if isinstance(plan, Op.OrderBy):
        repl = _match_orderby(plan, hinted)
        if repl is not None:
            return repl
    if not hasattr(plan, "__dataclass_fields__"):
        return plan
    for f in fields(plan):
        v = getattr(plan, f.name)
        if isinstance(v, Op.LogicalOperator):
            setattr(plan, f.name, parallel_rewrite(v, hinted))
    return plan
