"""Anchored pattern matching for pattern predicates (exists(...)).

Builds a throwaway subplan for the pattern with already-bound frame symbols
as anchors, and streams matches. Used by Evaluator._eval_PatternExpr.
"""

from __future__ import annotations

from ..frontend import ast as A


def match_pattern_anchored(eval_ctx, pattern: A.Pattern, frame: dict):
    from .operators import Argument, ExecutionContext
    from .planner import Planner
    import copy

    storage = eval_ctx.storage
    planner = Planner(storage)
    bound = {k for k, v in frame.items()
             if not k.startswith("__") and v is not None}
    pattern = copy.deepcopy(pattern)
    plan = planner.plan_pattern(pattern, Argument(), set(bound), [], [])

    ctx = ExecutionContext(eval_ctx.accessor, eval_ctx.parameters,
                           eval_ctx.view)
    ctx._argument_frame = {k: v for k, v in frame.items()
                           if not k.startswith("__")}
    yield from plan.cursor(ctx)
