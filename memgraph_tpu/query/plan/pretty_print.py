"""EXPLAIN plan rendering (reference: query/plan/pretty_print.cpp)."""

from __future__ import annotations

from .operators import LogicalOperator


def _describe(op: LogicalOperator) -> str:
    name = op.name()
    extras = []
    for attr in ("symbol", "label", "properties", "prop", "from_symbol",
                 "to_symbol", "edge_symbol", "direction", "edge_types",
                 "edge_type", "proc_name"):
        v = getattr(op, attr, None)
        if v is None or callable(v):
            continue
        if isinstance(v, (list, tuple)) and not v:
            continue
        if attr == "proc_name" and isinstance(v, str):
            extras.append(v)
        elif isinstance(v, str):
            extras.append(f"{attr}={v}")
        elif isinstance(v, (list, tuple)) and all(isinstance(x, str)
                                                  for x in v):
            extras.append(f"{attr}={'|'.join(v)}")
    if extras:
        return f"{name} ({', '.join(extras)})"
    return name


def plan_to_rows(plan: LogicalOperator) -> list[str]:
    rows: list[str] = []

    def walk(op, depth):
        if op is None:
            return
        rows.append("| " * depth + "* " + _describe(op))
        for child in op.children():
            walk(child, depth + 1)

    walk(plan, 0)
    return rows
