"""PROFILE instrumentation: per-operator pulls, rows, time, memory, and
device attribution.

Counterpart of the reference's ScopedProfile/ProfilingStats
(/root/reference/src/query/plan/profile.cpp, scoped_profile.hpp): every
operator cursor is wrapped with counters + a timer; results render as
the profile tree (OPERATOR, ACTUAL HITS, ROWS, RELATIVE TIME, ABSOLUTE
TIME, PEAK MEM).

PROFILE v2 (r14, mgstat):

* ``attach_profiling`` no longer ``copy.deepcopy``-s the plan. Each
  operator NODE is shallow-copied (expressions, symbols and every other
  referenced object stay shared) and its child links are rewired to
  profiled wrappers — so profiling a plan-cache-hit query costs O(plan
  nodes) pointer work instead of a deep clone of the whole tree, and
  the CACHED plan object is never mutated (the regression test proves a
  PROFILE run neither poisons the cache nor changes results).

* the collector tracks, per operator: ``hits`` (cursor pulls, including
  the exhausting one), ``rows`` (frames produced), inclusive ``time``,
  and ``peak_mem`` — a sampled ``approx_size`` estimate of the largest
  frame the operator emitted (first frames + every 16th, so wide rows
  are caught without paying a size walk per frame).

* ``profile_rows`` appends DEVICE ATTRIBUTION rows when the query's
  stage accumulator (observability/stats.py) saw device work: kernel
  dispatch, transfer, compile and iterate seconds — so ``PROFILE`` on
  an analytics-routed query shows where the HBM-seconds went even when
  the kernel ran in the resident server process.
"""

from __future__ import annotations

import copy
import time

from .operators import LogicalOperator

#: every attribute that may hold a child operator (kept in sync with
#: profile_rows' walk and the planner's tree shapes)
CHILD_ATTRS = ("input", "subplan", "match_plan", "create_plan",
               "update_plan", "left", "right")

#: frame-size sampling cadence: the first _MEM_SAMPLE_HEAD frames are
#: always measured, then every _MEM_SAMPLE_EVERY-th
_MEM_SAMPLE_HEAD = 4
_MEM_SAMPLE_EVERY = 16


class ProfileCollector:
    def __init__(self) -> None:
        self.stats: dict[int, dict] = {}

    def entry(self, op_id: int, name: str) -> dict:
        if op_id not in self.stats:
            self.stats[op_id] = {"name": name, "hits": 0, "rows": 0,
                                 "time": 0.0, "peak_mem": 0}
        return self.stats[op_id]


class ProfiledOp(LogicalOperator):
    """Cursor wrapper around ONE (shallow-copied) operator node."""

    def __init__(self, inner: LogicalOperator, collector: ProfileCollector):
        self.inner = inner
        self.collector = collector

    def __getattr__(self, name):
        # operators occasionally read child attributes (symbols, flags);
        # delegate so a wrapped child is indistinguishable from the
        # bare operator for everything except cursor()
        if name in ("inner", "collector"):
            raise AttributeError(name)
        return getattr(self.inner, name)

    def name(self) -> str:
        return self.inner.name()

    def children(self):
        return [c for c in (getattr(self.inner, attr, None)
                            for attr in CHILD_ATTRS)
                if isinstance(c, LogicalOperator)]

    def cursor(self, ctx):
        from ...utils.memory_tracker import approx_size
        entry = self.collector.entry(id(self), self.inner.name())
        it = self.inner.cursor(ctx)
        rows = 0
        while True:
            t0 = time.perf_counter()
            try:
                frame = next(it)
            except StopIteration:
                entry["time"] += time.perf_counter() - t0
                entry["hits"] += 1
                return
            entry["time"] += time.perf_counter() - t0
            entry["hits"] += 1
            entry["rows"] += 1
            if rows < _MEM_SAMPLE_HEAD or rows % _MEM_SAMPLE_EVERY == 0:
                size = approx_size(frame)
                if size > entry["peak_mem"]:
                    entry["peak_mem"] = size
            rows += 1
            yield frame


def attach_profiling(plan: LogicalOperator):
    """Wrap every operator for profiling WITHOUT cloning the plan deeply.

    Returns (wrapped_plan, collector). Each node is ``copy.copy``-ed (a
    shallow, O(fields) pointer copy — expressions and symbols stay
    shared with the cached plan) and its child attributes are rewired
    to wrapped children; the original tree is never touched, so a
    cached plan can be profiled concurrently with unprofiled runs.

    Self-time accounting: the wrapper measures inclusive time;
    rendering subtracts children's inclusive time to show self time.
    """
    collector = ProfileCollector()

    def wrap(op):
        if not isinstance(op, LogicalOperator):
            return op
        clone = copy.copy(op)
        for attr in CHILD_ATTRS:
            child = getattr(clone, attr, None)
            if isinstance(child, LogicalOperator):
                setattr(clone, attr, wrap(child))
        return ProfiledOp(clone, collector)

    return wrap(plan), collector


#: render order — tests key on [0]=operator and [1]=hits
PROFILE_COLUMNS = ["OPERATOR", "ACTUAL HITS", "ROWS", "RELATIVE TIME",
                   "ABSOLUTE TIME", "PEAK MEM (BYTES)"]


def profile_rows(plan, collector: ProfileCollector, total_time: float,
                 stages: dict | None = None):
    """Render the profile tree (plus device attribution) as rows."""
    def walk(op, depth):
        if isinstance(op, ProfiledOp):
            stats = collector.stats.get(
                id(op), {"name": op.inner.name(), "hits": 0, "rows": 0,
                         "time": 0.0, "peak_mem": 0})
            children = op.children()
        else:
            stats = {"name": op.name(), "hits": 0, "rows": 0,
                     "time": 0.0, "peak_mem": 0}
            children = [c for c in (getattr(op, attr, None)
                                    for attr in CHILD_ATTRS)
                        if isinstance(c, LogicalOperator)]
        child_time = sum(collector.stats.get(id(c), {}).get("time", 0.0)
                         for c in children)
        self_time = max(stats["time"] - child_time, 0.0)
        rel = (self_time / total_time * 100.0) if total_time > 0 else 0.0
        indent = "| " * depth
        yield [f"{indent}* {stats['name']}", stats["hits"], stats["rows"],
               f"{rel:.6f} %", f"{self_time * 1000:.6f} ms",
               stats["peak_mem"]]
        for child in children:
            yield from walk(child, depth + 1)

    yield from walk(plan, 0)

    # device attribution: where the query's HBM-seconds went, from the
    # stage accumulator (kernel replies merge their server-side splits
    # into it, so a kernel-server-routed dispatch attributes here too)
    for stage in sorted(stages or {}):
        slot = stages[stage]
        seconds = float(slot.get("seconds", 0.0))
        rel = (seconds / total_time * 100.0) if total_time > 0 else 0.0
        yield [f">> device: {stage}", int(slot.get("count", 0)), 0,
               f"{rel:.6f} %", f"{seconds * 1000:.6f} ms", 0]
