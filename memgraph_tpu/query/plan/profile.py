"""PROFILE instrumentation: per-operator pull counts and timings.

Counterpart of the reference's ScopedProfile/ProfilingStats
(/root/reference/src/query/plan/profile.cpp, scoped_profile.hpp): every
operator cursor is wrapped with a counter + timer; results render as the
profile tree (OPERATOR, ACTUAL HITS, RELATIVE TIME, ABSOLUTE TIME).
"""

from __future__ import annotations

import copy
import time

from .operators import LogicalOperator


class ProfileCollector:
    def __init__(self) -> None:
        self.stats: dict[int, dict] = {}

    def entry(self, op_id: int, name: str) -> dict:
        if op_id not in self.stats:
            self.stats[op_id] = {"name": name, "hits": 0, "time": 0.0}
        return self.stats[op_id]


class ProfiledOp(LogicalOperator):
    def __init__(self, inner: LogicalOperator, collector: ProfileCollector):
        self.inner = inner
        self.collector = collector
        self.input = getattr(inner, "input", None)

    def name(self) -> str:
        return self.inner.name()

    def children(self):
        return self.inner.children()

    def cursor(self, ctx):
        entry = self.collector.entry(id(self.inner), self.inner.name())
        it = self.inner.cursor(ctx)
        while True:
            t0 = time.perf_counter()
            try:
                frame = next(it)
            except StopIteration:
                entry["time"] += time.perf_counter() - t0
                return
            entry["time"] += time.perf_counter() - t0
            entry["hits"] += 1
            yield frame


def attach_profiling(plan: LogicalOperator):
    """Deep-copy the plan and wrap every operator. Returns (plan, collector).

    Self-time accounting: the wrapper measures inclusive time; rendering
    subtracts children's inclusive time to show self time.
    """
    collector = ProfileCollector()
    plan = copy.deepcopy(plan)

    def wrap(op):
        if op is None:
            return None
        for attr in ("input", "subplan", "match_plan", "create_plan",
                     "update_plan", "left", "right"):
            child = getattr(op, attr, None)
            if isinstance(child, LogicalOperator):
                setattr(op, attr, wrap(child))
        return ProfiledOp(op, collector)

    return wrap(plan), collector


def profile_rows(plan, collector: ProfileCollector, total_time: float):
    """Render the profile tree as rows."""
    def walk(op, depth):
        if isinstance(op, ProfiledOp):
            inner = op.inner
        else:
            inner = op
        stats = collector.stats.get(id(inner),
                                    {"name": inner.name(), "hits": 0,
                                     "time": 0.0})
        child_time = 0.0
        children = []
        for attr in ("input", "subplan", "match_plan", "create_plan",
                     "update_plan", "left", "right"):
            child = getattr(inner, attr, None)
            if isinstance(child, LogicalOperator):
                children.append(child)
        for child in children:
            cin = child.inner if isinstance(child, ProfiledOp) else child
            cstats = collector.stats.get(id(cin))
            if cstats:
                child_time += cstats["time"]
        self_time = max(stats["time"] - child_time, 0.0)
        rel = (self_time / total_time * 100.0) if total_time > 0 else 0.0
        indent = "| " * depth
        yield [f"{indent}* {stats['name']}", stats["hits"],
               f"{rel:.6f} %", f"{self_time * 1000:.6f} ms"]
        for child in children:
            yield from walk(child, depth + 1)

    yield from walk(plan, 0)
