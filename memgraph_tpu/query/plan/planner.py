"""Rule-based query planner: clause chain → operator tree.

Counterpart of the reference's RuleBasedPlanner + rewrite passes
(/root/reference/src/query/plan/rule_based_planner.cpp,
plan/rewrite/index_lookup.hpp): pattern matching compiles to
Scan→Expand→Filter chains, with index-backed scan selection driven by
pattern property maps, WHERE equality/range predicates, and index
statistics (approx counts) for choosing the cheapest start.
"""

from __future__ import annotations

import itertools
from typing import Optional

from ...exceptions import SemanticException
from ..frontend import ast as A
from ..frontend.semantic import (check_expr_scope,
                                  check_no_aggregates,
                                  check_static_types)
from . import operators as Op

_ANON = itertools.count()


def _anon(prefix="anon"):
    return f"__{prefix}{next(_ANON)}__"


def collect_aggregations(expr: A.Expr, out: list) -> None:
    """Find aggregate FunctionCall/CountStar nodes within an expression."""
    if isinstance(expr, A.CountStar):
        out.append(expr)
        return
    if isinstance(expr, A.FunctionCall) and \
            expr.name in Op.AGGREGATE_FUNCTIONS:
        out.append(expr)
        return
    for child in _children_exprs(expr):
        collect_aggregations(child, out)


def _children_exprs(expr):
    if isinstance(expr, A.Unary):
        return [expr.expr]
    if isinstance(expr, A.Binary):
        return [expr.left, expr.right]
    if isinstance(expr, (A.PropertyLookup, A.LabelsTest, A.IsNull)):
        return [expr.expr]
    if isinstance(expr, A.Subscript):
        return [expr.expr, expr.index]
    if isinstance(expr, A.Slice):
        return [e for e in (expr.expr, expr.lo, expr.hi) if e is not None]
    if isinstance(expr, A.ListLiteral):
        return expr.items
    if isinstance(expr, A.MapLiteral):
        return list(expr.items.values())
    if isinstance(expr, A.FunctionCall):
        return expr.args
    if isinstance(expr, A.CaseExpr):
        out = [e for e in (expr.test, expr.default) if e is not None]
        for c, r in expr.whens:
            out.extend((c, r))
        return out
    if isinstance(expr, A.ListComprehension):
        return [e for e in (expr.list_expr, expr.where, expr.projection)
                if e is not None]
    if isinstance(expr, A.Quantifier):
        return [expr.list_expr, expr.where]
    if isinstance(expr, A.Reduce):
        return [expr.init, expr.list_expr, expr.expr]
    return []


def expr_symbols(expr: A.Expr, out: set) -> set:
    """Free identifiers referenced by an expression (over-approximate)."""
    if isinstance(expr, A.Identifier):
        out.add(expr.name)
    if isinstance(expr, (A.PatternExpr, A.PatternComprehension)):
        # pattern variables anchor on outer bindings when those exist, so
        # a predicate mentioning them must not be applied before they are
        # bound (over-approximation: fresh existential vars are included
        # too — harmless, leftover predicates apply at end of MATCH)
        for el in expr.pattern.elements:
            v = getattr(el, "variable", None)
            if v:
                out.add(v)
            props = getattr(el, "properties", None)
            if isinstance(props, dict):
                for p in props.values():
                    expr_symbols(p, out)
        if isinstance(expr, A.PatternComprehension):
            if expr.where is not None:
                expr_symbols(expr.where, out)
            expr_symbols(expr.projection, out)
    for child in _children_exprs(expr):
        expr_symbols(child, out)
    return out


def _check_storable_literal(expr) -> None:
    """SET n.p = <literal> with a statically-invalid property type —
    a list containing maps — is a compile-time TypeError (TCK
    MiscellaneousErrorAcceptance: InvalidPropertyType)."""
    if isinstance(expr, A.ListLiteral):
        for item in expr.items:
            if isinstance(item, A.MapLiteral):
                from ...exceptions import TypeException
                raise TypeException(
                    "InvalidPropertyType: a list of maps cannot be "
                    "stored as a property")
            _check_storable_literal(item)


def _split_and(expr: Optional[A.Expr]) -> list:
    if expr is None:
        return []
    if isinstance(expr, A.Binary) and expr.op == "AND":
        return _split_and(expr.left) + _split_and(expr.right)
    return [expr]


class Planner:
    """Plans one SingleQuery clause chain."""

    def __init__(self, storage, config=None) -> None:
        self.storage = storage
        self.config = config

    # --- public -------------------------------------------------------------

    def plan_query(self, query: A.CypherQuery):
        plan, columns = self.plan_single(query.query)
        if query.unions and len({ua for ua, _ in query.unions}) > 1:
            raise SemanticException(
                "InvalidClauseComposition: mixing UNION and UNION ALL "
                "in one query is not allowed")
        for union_all, sub in query.unions:
            sub_plan, sub_cols = self.plan_single(sub)
            if [c for c in sub_cols] != [c for c in columns]:
                raise SemanticException(
                    "UNION queries must return the same column names")
            plan = Op.Union(plan, sub_plan, columns, distinct=not union_all)
        if query.commit_frequency is not None:
            # root PeriodicCommit wraps the whole plan (reference:
            # rule_based_planner.hpp:504); combining it with CALL {} IN
            # TRANSACTIONS — at any subquery nesting depth — is the
            # reference's "only once" semantic error
            # (symbol_generator.cpp:177)
            def _has_batched_apply(op):
                if op is None:
                    return False
                if isinstance(op, Op.Apply) and op.batch_rows:
                    return True
                return any(_has_batched_apply(c) for c in op.children())
            if _has_batched_apply(plan):
                raise SemanticException(
                    "You can specify periodic commit only once during "
                    "a query!")
            plan = Op.PeriodicCommit(plan, query.commit_frequency)
        elif not query.unions and not columns:
            # bulk-write fast lane: write-only root-level create chains
            # route through storage.batch_insert (query/plan/bulk.py)
            from .bulk import bulk_rewrite
            plan = bulk_rewrite(plan, self.storage, self.config)
        return plan, columns

    def plan_single(self, single: A.SingleQuery, leaf=None,
                    initial_bound=None):
        plan: Op.LogicalOperator = leaf if leaf is not None else Op.Once()
        bound: set[str] = set(initial_bound or ())
        columns: list[str] = []
        clauses = single.clauses
        has_update = False
        produced = False
        parallel_hint = False   # USING PARALLEL EXECUTION, this query only

        # clause-at-a-time visibility: a reading clause after an updating
        # one (and vice versa) gets an Eager barrier so scans never
        # interleave with mutations (TCK CreateAcceptance "Combine MATCH,
        # WITH and CREATE"; reference: Accumulate + advance_command)
        read_seen = False
        write_seen = False
        _READING = (A.Match,)
        _WRITING = (A.Create, A.Merge, A.SetClause, A.Remove, A.Delete,
                    A.Foreach)
        kinds: dict[str, str] = {}   # variable -> node|edge|path|value
        prev_optional = False

        for ci, clause in enumerate(clauses):
            if isinstance(clause, A.Match):
                if prev_optional and not clause.optional:
                    raise SemanticException(
                        "InvalidClauseComposition: MATCH cannot follow "
                        "OPTIONAL MATCH (use a WITH between them)")
                prev_optional = clause.optional
                if clause.parallel:
                    parallel_hint = True
                self._validate_match(clause, bound, kinds)
            write_seen_before = write_seen   # for MERGE's read-side barrier
            if isinstance(clause, _READING) and write_seen:
                plan = Op.Eager(plan)
                write_seen = False  # barrier absorbs prior writes
            elif isinstance(clause, _WRITING) and read_seen:
                plan = Op.Eager(plan)
                read_seen = False   # consecutive writes share one barrier
            if isinstance(clause, _READING):
                read_seen = True
            if isinstance(clause, _WRITING):
                write_seen = True
            if isinstance(clause, A.Match):
                plan = self.plan_match(clause, plan, bound)
            elif isinstance(clause, A.Create):
                has_update = True
                plan = self.plan_create(clause, plan, bound)
            elif isinstance(clause, A.Merge):
                has_update = True
                if write_seen_before:
                    # MERGE READS its match side: PRIOR writes (e.g. a
                    # DELETE) must be fully applied first, or the match
                    # subplan sees not-yet-deleted entities (TCK
                    # MergeNodeAcceptance "not able to match on deleted")
                    plan = Op.Eager(plan)
                plan = self.plan_merge(clause, plan, bound)
            elif isinstance(clause, A.SetClause):
                has_update = True
                for item in clause.items:
                    check_expr_scope(item.target, bound, "SET")
                    if isinstance(item.value, A.Expr):
                        check_expr_scope(item.value, bound, "SET")
                        check_static_types(item.value, kinds)
                        _check_storable_literal(item.value)
                plan = self.plan_set_items(clause.items, plan, bound)
            elif isinstance(clause, A.Remove):
                has_update = True
                for item in clause.items:
                    check_expr_scope(item.target, bound, "REMOVE")
                plan = self.plan_remove(clause, plan)
            elif isinstance(clause, A.Delete):
                has_update = True
                for expr in clause.exprs:
                    if isinstance(expr, A.LabelsTest):
                        raise SemanticException(
                            "InvalidDelete: DELETE takes an entity, not a "
                            "label expression — use REMOVE for labels")
                    if isinstance(expr, (A.Literal, A.Binary, A.Unary,
                                         A.MapLiteral)):
                        raise SemanticException(
                            "InvalidArgumentType: DELETE requires a node, "
                            "relationship or path expression")
                    check_expr_scope(expr, bound, "DELETE")
                plan = Op.Delete(plan, clause.exprs, clause.detach)
            elif isinstance(clause, A.Unwind):
                check_expr_scope(clause.expr, bound, "UNWIND")
                plan = Op.Unwind(plan, clause.expr, clause.variable)
                bound.add(clause.variable)
            elif isinstance(clause, A.CallSubquery):
                sub_plan, sub_cols = self.plan_single(
                    clause.query, leaf=Op.Argument(), initial_bound=bound)
                if _single_has_update(clause.query):
                    has_update = True
                plan = Op.Apply(plan, sub_plan, sub_cols,
                                clause.batch_rows)
                bound.update(sub_cols)
            elif isinstance(clause, A.CallProcedure):
                standalone = len(clauses) == 1
                plan = self.plan_call(clause, plan, bound,
                                      standalone=standalone)
                if ci == len(clauses) - 1 and not clause.yield_dash and (
                        clause.yields or clause.yield_star or standalone):
                    # terminal CALL: surface the yielded columns (standalone
                    # CALL without YIELD surfaces every result field —
                    # TCK ProcedureCallAcceptance "Standalone call ...")
                    names = [a or f for f, a in clause.yields] \
                        if clause.yields else self._call_fields(clause)
                    items = [(A.Identifier(n), n) for n in names]
                    if names:
                        plan = Op.Produce(plan, items)
                        columns = names
                    produced = True
            elif isinstance(clause, A.With):
                # items see the PRE-projection scope; WHERE and ORDER BY
                # see the POST-projection scope (an alias may shadow a
                # node variable with e.g. a list)
                self._check_body_types(clause.body, kinds)
                new_kinds = self._project_kinds(clause.body, kinds)
                check_static_types(clause.where, new_kinds)
                for si in clause.body.order_by:
                    check_static_types(getattr(si, "expr", None),
                                       new_kinds)
                plan, columns = self.plan_projection(
                    clause.body, plan, bound, has_update, is_with=True,
                    where=clause.where)
                has_update = False
                prev_optional = False
                kinds = new_kinds
                bound = set(columns)
            elif isinstance(clause, A.Return):
                self._check_body_types(clause.body, kinds)
                post_kinds = self._project_kinds(clause.body, kinds)
                for si in clause.body.order_by:
                    check_static_types(getattr(si, "expr", None),
                                       post_kinds)
                plan, columns = self.plan_projection(
                    clause.body, plan, bound, has_update, is_with=False)
                produced = True
            elif isinstance(clause, A.Foreach):
                has_update = True
                plan = self.plan_foreach(clause, plan, bound)
            elif isinstance(clause, A.LoadCsv):
                plan = Op.LoadCsvOp(plan, clause.file, clause.variable,
                                    clause.with_header, clause.ignore_bad,
                                    clause.delimiter, clause.quote)
                bound.add(clause.variable)
            elif isinstance(clause, A.LoadJsonl):
                plan = Op.LoadJsonlOp(plan, clause.file, clause.variable)
                bound.add(clause.variable)
            elif isinstance(clause, A.LoadParquet):
                plan = Op.LoadParquetOp(plan, clause.file, clause.variable)
                bound.add(clause.variable)
            else:
                raise SemanticException(
                    f"unsupported clause {type(clause).__name__}")

        if not produced and not has_update and not any(
                isinstance(c, A.CallProcedure) for c in clauses):
            raise SemanticException("query must end with RETURN or an update")
        if not produced:
            # write-only query: WITH projections along the way must not
            # leak as result columns — such queries stream zero records
            columns = []
        from .parallel import parallel_rewrite
        plan = parallel_rewrite(plan, hinted=parallel_hint)
        # compiled read lane: lower the columnar tails (and the 1-2 hop
        # count shapes the columnar collapse does not claim) onto the
        # device programs in ops/pipeline.py (query/plan/lane.py)
        from .lane import lane_rewrite
        plan = lane_rewrite(plan, hinted=parallel_hint)
        return plan, columns

    def _call_fields(self, clause: A.CallProcedure) -> list[str]:
        from ..procedures.registry import global_registry
        proc = global_registry.find(clause.name)
        if proc is None:
            raise SemanticException(f"unknown procedure: {clause.name}")
        return [f for f, _ in proc.results]

    # --- MATCH --------------------------------------------------------------

    def _check_body_types(self, body: A.ReturnBody, kinds: dict) -> None:
        for expr, _alias, _verbatim in body.items:
            check_static_types(expr, kinds)

    @staticmethod
    def _project_kinds(body: A.ReturnBody, kinds: dict) -> dict:
        """Variable kinds AFTER a WITH/RETURN projection: a passed-through
        identifier keeps its kind, a statically-known non-entity expression
        becomes 'value' (so `WITH [n] AS users MATCH (users)` is a
        VariableTypeConflict), anything else is unknown (unchecked)."""
        new_kinds: dict[str, str] = {}
        for expr, alias, _verbatim in body.items:
            name = alias or (_verbatim if _verbatim else _expr_name(expr))
            if isinstance(expr, A.Identifier):
                k = kinds.get(expr.name)
                if k:
                    new_kinds[name] = k
            elif isinstance(expr, (A.ListLiteral, A.MapLiteral,
                                   A.ListComprehension,
                                   A.PatternComprehension)) or (
                    isinstance(expr, A.Literal)
                    and expr.value is not None) or (
                    isinstance(expr, A.FunctionCall)
                    and expr.name in ("collect", "count", "sum",
                                      "avg", "stdev", "stdevp",
                                      "percentiledisc",
                                      "percentilecont")):
                new_kinds[name] = "value"
        if body.star:
            # every currently-visible variable stays visible under `*`
            # (kinds only ever holds in-scope variables)
            for sym, k in kinds.items():
                new_kinds.setdefault(sym, k)
        return new_kinds

    def _validate_match(self, match: A.Match, bound: set,
                        kinds: dict) -> None:
        """Compile-time MATCH validity (TCK SemanticErrorAcceptance /
        MiscellaneousErrorAcceptance): variable kind conflicts, relationship
        uniqueness within a clause, parameter property maps, WHERE scope."""
        clause_vars: set = set()
        clause_edge_vars: set = set()
        for pattern in match.patterns:
            if pattern.variable:
                if pattern.variable in bound or pattern.variable \
                        in clause_vars:
                    raise SemanticException(
                        f"VariableAlreadyBound: path variable "
                        f"{pattern.variable} cannot be rebound")
                kinds[pattern.variable] = "path"
                clause_vars.add(pattern.variable)
            nodes = pattern.elements[0::2]
            edges = pattern.elements[1::2]
            for node in nodes:
                v = node.variable
                if v:
                    if kinds.get(v) in ("edge", "path", "value"):
                        raise SemanticException(
                            f"VariableTypeConflict: {v} is a "
                            f"{kinds[v]}, used here as a node")
                    kinds.setdefault(v, "node")
                    clause_vars.add(v)
                if isinstance(node.properties, A.Parameter):
                    raise SemanticException(
                        "InvalidParameterUse: a parameter property map "
                        "is not allowed in MATCH")
            for edge in edges:
                v = edge.variable
                if v:
                    if v in clause_edge_vars:
                        raise SemanticException(
                            f"RelationshipUniquenessViolation: "
                            f"relationship variable {v} is used more than "
                            f"once in this MATCH")
                    # a var-length slot legally binds a LIST of
                    # relationships (`MATCH ()-[rs*]->()` with rs
                    # projected from collect/[r1, r2]) — only fixed-length
                    # slots conflict with non-edge kinds
                    if not edge.var_length and \
                            kinds.get(v) in ("node", "path", "value"):
                        raise SemanticException(
                            f"VariableTypeConflict: {v} is a "
                            f"{kinds[v]}, used here as a relationship")
                    if not edge.var_length:
                        kinds.setdefault(v, "edge")
                    else:
                        # binds a LIST of relationships: single-rel use
                        # (r.prop) is a compile-time InvalidArgumentType
                        kinds.setdefault(v, "edge_list")
                    clause_edge_vars.add(v)
                    clause_vars.add(v)
                if isinstance(edge.properties, A.Parameter):
                    raise SemanticException(
                        "InvalidParameterUse: a parameter property map "
                        "is not allowed in MATCH")
        scope = bound | clause_vars
        for pattern in match.patterns:
            for item in pattern.elements:
                props = getattr(item, "properties", None)
                if isinstance(props, dict):
                    for p in props.values():
                        check_expr_scope(p, scope, "pattern properties")
                        check_no_aggregates(p, "pattern properties")
        if match.where is not None:
            check_expr_scope(match.where, scope, "WHERE")
            check_no_aggregates(match.where, "WHERE")
            check_static_types(match.where, kinds)

    def plan_match(self, match: A.Match, plan, bound: set):
        where_parts = _split_and(match.where)
        self._index_hints = {h.variable: h for h in
                             getattr(match, "index_hints", [])}
        if getattr(match, "hops_limit", None):
            plan = Op.SetHopsLimit(plan, match.hops_limit)
        if match.optional:
            sub_bound = set(bound)
            subplan = self.plan_pattern_chain(
                match.patterns, Op.Argument(), sub_bound, where_parts,
                outer_bound=bound)
            new_syms = sorted(sub_bound - bound)
            plan = Op.Optional_(plan, subplan, new_syms)
            bound.update(sub_bound)
            return plan
        plan = self.plan_pattern_chain(match.patterns, plan, bound,
                                       where_parts, outer_bound=None)
        return plan

    def plan_pattern_chain(self, patterns, plan, bound: set, where_parts,
                           outer_bound):
        pending = list(where_parts)
        edge_syms_in_match: list[str] = []
        for pattern in patterns:
            plan = self.plan_pattern(pattern, plan, bound, pending,
                                     edge_syms_in_match)
        # leftover predicates apply once everything is bound
        for pred in pending:
            plan = Op.Filter(plan, pred)
        return plan

    def plan_pattern(self, pattern: A.Pattern, plan, bound: set, pending,
                     edge_syms_in_match):
        elements = pattern.elements
        nodes = elements[0::2]
        edges = elements[1::2]
        # name anonymous symbols
        node_syms = []
        for node in nodes:
            sym = node.variable or _anon("node")
            node.variable = sym
            node_syms.append(sym)
        edge_syms = []
        for edge in edges:
            sym = edge.variable or _anon("edge")
            edge.variable = sym
            edge_syms.append(sym)

        # choose a start node among unbound ones (index-driven)
        start_idx = self._choose_start(nodes, bound, pending)
        plan = self._plan_node_scan(nodes[start_idx], plan, bound, pending)

        # expand left and right from the start
        # process edges in order: right side first (start→end), then left
        for i in range(start_idx, len(edges)):
            plan = self._plan_expand(edges[i], nodes[i], nodes[i + 1],
                                     "fwd", plan, bound, pending,
                                     edge_syms_in_match)
        for i in range(start_idx - 1, -1, -1):
            plan = self._plan_expand(edges[i], nodes[i], nodes[i + 1],
                                     "bwd", plan, bound, pending,
                                     edge_syms_in_match)

        if pattern.variable:
            syms = []
            for i, node in enumerate(nodes):
                syms.append(node.variable)
                if i < len(edges):
                    syms.append(edges[i].variable)
            # interleave properly: node, edge, node, ...
            interleaved = []
            for i in range(len(edges)):
                interleaved.append(nodes[i].variable)
                interleaved.append(edges[i].variable)
            interleaved.append(nodes[-1].variable)
            plan = Op.ConstructNamedPath(plan, pattern.variable, interleaved)
            bound.add(pattern.variable)
        return plan

    def _choose_start(self, nodes, bound: set, pending) -> int:
        # already-bound node → cheapest start (no scan at all)
        for i, node in enumerate(nodes):
            if node.variable in bound:
                return i
        best = (float("inf"), 0)
        for i, node in enumerate(nodes):
            cost = self._scan_cost(node, pending)
            if cost < best[0]:
                best = (cost, i)
        return best[1]

    def _scan_cost(self, node: A.NodePattern, pending) -> float:
        indices = self.storage.indices
        mapper = self.storage.label_mapper
        pmapper = self.storage.property_mapper
        total = max(len(self.storage._vertices), 1)
        best = float(total) * 2  # ScanAll penalty
        for label in node.labels:
            lid = mapper.maybe_name_to_id(label)
            if lid is None:
                return 0.0  # label unknown → zero results
            eq_props = self._equality_props(node, pending)
            for (ilabel, iprops) in indices.label_property.relevant_to(lid):
                if all(pmapper.id_to_name(p) in eq_props for p in iprops):
                    # ANALYZE GRAPH statistics predict an equality
                    # lookup's result size exactly: the average group
                    # size per distinct key (reference:
                    # cost_estimator.hpp using
                    # label_property_index_stats avg_group_size);
                    # without stats, fall back to the count heuristic
                    stats = indices.analyze_stats.get((ilabel, iprops))
                    if stats and stats.get("num_groups"):
                        best = min(best, float(stats["avg_group_size"]))
                    else:
                        best = min(best,
                                   indices.label_property.approx_count(
                                       ilabel, iprops)
                                   / max(len(iprops), 1))
            if indices.label.has(lid):
                best = min(best, float(indices.label.approx_count(lid)))
            else:
                best = min(best, float(total))
        return best

    def _equality_props(self, node: A.NodePattern, pending) -> set:
        """Property names fixed by the pattern map or WHERE n.p = <expr>."""
        out = set()
        if isinstance(node.properties, dict):
            out.update(node.properties.keys())
        for pred in pending:
            if isinstance(pred, A.Binary) and pred.op == "=":
                for lhs, rhs in ((pred.left, pred.right),
                                 (pred.right, pred.left)):
                    if (isinstance(lhs, A.PropertyLookup)
                            and isinstance(lhs.expr, A.Identifier)
                            and lhs.expr.name == node.variable):
                        out.add(lhs.prop)
        return out

    def _plan_node_scan(self, node: A.NodePattern, plan, bound: set, pending):
        sym = node.variable
        if sym in bound:
            return self._apply_node_filters(node, plan, bound, pending,
                                            skip_scan_filters=False)
        indices = self.storage.indices
        mapper = self.storage.label_mapper
        pmapper = self.storage.property_mapper
        scan = None
        used_label = None
        used_props: set = set()
        hint = getattr(self, "_index_hints", {}).get(sym)

        eq_map = {}  # prop name -> value expr
        if isinstance(node.properties, dict):
            eq_map.update(node.properties)
        where_eq = {}
        range_preds = {}
        for pred in pending:
            if isinstance(pred, A.Binary) and pred.op in (
                    "=", "<", ">", "<=", ">="):
                for lhs, rhs, op in ((pred.left, pred.right, pred.op),
                                     (pred.right, pred.left,
                                      _flip(pred.op))):
                    if (isinstance(lhs, A.PropertyLookup)
                            and isinstance(lhs.expr, A.Identifier)
                            and lhs.expr.name == sym
                            and not (expr_symbols(rhs, set()) - bound)):
                        if op == "=":
                            where_eq.setdefault(lhs.prop, (rhs, pred))
                        else:
                            range_preds.setdefault(lhs.prop, []).append(
                                (op, rhs, pred))

        label_order = list(node.labels)
        if hint is not None and hint.label in label_order:
            label_order.remove(hint.label)
            label_order.insert(0, hint.label)
        for label in label_order:
            lid = mapper.maybe_name_to_id(label)
            if lid is None:
                continue
            # equality composite index: most selective first — by
            # ANALYZE GRAPH avg_group_size when stats exist, else by
            # specificity (longest prefix)
            def _expected_rows(key):
                stats = indices.analyze_stats.get(key)
                if stats and stats.get("num_groups"):
                    return float(stats["avg_group_size"])
                # no stats (e.g. index created after ANALYZE): fall back
                # to the live count heuristic so a fresh selective index
                # still competes with stale-analyzed ones
                return (indices.label_property.approx_count(*key)
                        / max(len(key[1]), 1))
            keys = sorted(indices.label_property.relevant_to(lid),
                          key=lambda k: (_expected_rows(k), -len(k[1])))
            if hint is not None and hint.label == label and hint.properties:
                hint_pids = tuple(pmapper.maybe_name_to_id(pr)
                                  for pr in hint.properties)
                keys.sort(key=lambda k: 0 if k[1] == hint_pids else 1)
            for (ilabel, iprops) in keys:
                names = [pmapper.id_to_name(p) for p in iprops]
                if all(n in eq_map or n in where_eq for n in names):
                    exprs = []
                    consumed = []
                    for n in names:
                        if n in eq_map:
                            exprs.append(eq_map[n])
                        else:
                            rhs, pred = where_eq[n]
                            exprs.append(rhs)
                            consumed.append(pred)
                    scan = Op.ScanAllByLabelPropertyValue(
                        plan, sym, label, names, exprs)
                    for pred in consumed:
                        if pred in pending:
                            pending.remove(pred)
                    used_label = label
                    used_props = set(names) & set(eq_map)
                    break
                if len(iprops) == 1 and names[0] in range_preds:
                    lo = hi = None
                    lo_inc = hi_inc = True
                    consumed = []
                    for op, rhs, pred in range_preds[names[0]]:
                        if op in (">", ">="):
                            lo, lo_inc = rhs, op == ">="
                        else:
                            hi, hi_inc = rhs, op == "<="
                        consumed.append(pred)
                    scan = Op.ScanAllByLabelPropertyRange(
                        plan, sym, label, names[0], lo, hi, lo_inc, hi_inc)
                    for pred in consumed:
                        if pred in pending:
                            pending.remove(pred)
                    used_label = label
                    break
            if scan is not None:
                break
            if indices.label.has(lid):
                scan = Op.ScanAllByLabel(plan, sym, label)
                used_label = label
                break
        if scan is None:
            if node.labels:
                scan = Op.ScanAllByLabel(plan, sym, node.labels[0])
                used_label = node.labels[0]
            else:
                scan = Op.ScanAll(plan, sym)
        bound.add(sym)
        return self._apply_node_filters(node, scan, bound, pending,
                                        used_label=used_label,
                                        used_props=used_props)

    def _apply_node_filters(self, node: A.NodePattern, plan, bound: set,
                            pending, used_label=None, used_props=(),
                            skip_scan_filters=True):
        sym = node.variable
        ident = A.Identifier(sym)
        remaining_labels = [l for l in node.labels if l != used_label]
        if remaining_labels:
            plan = Op.Filter(plan, A.LabelsTest(ident, remaining_labels))
        if isinstance(node.properties, dict):
            for key, expr in node.properties.items():
                if key in used_props:
                    continue
                plan = Op.Filter(plan, A.Binary(
                    "=", A.PropertyLookup(ident, key), expr))
        elif isinstance(node.properties, A.Parameter):
            plan = Op.Filter(plan, _param_props_predicate(sym,
                                                          node.properties))
        # apply any pending predicate that is now fully bound
        plan = self._apply_ready_predicates(plan, bound, pending)
        return plan

    def _apply_ready_predicates(self, plan, bound: set, pending):
        ready = []
        for pred in pending:
            syms = expr_symbols(pred, set())
            if syms and syms <= bound:
                ready.append(pred)
        for pred in ready:
            pending.remove(pred)
            plan = Op.Filter(plan, pred)
        return plan

    def _plan_expand(self, edge: A.EdgePattern, left_node, right_node,
                     chain_dir, plan, bound: set, pending,
                     edge_syms_in_match):
        if chain_dir == "fwd":
            from_node, to_node = left_node, right_node
            direction = edge.direction
        else:
            from_node, to_node = right_node, left_node
            direction = {"out": "in", "in": "out",
                         "both": "both"}[edge.direction]
        from_sym = from_node.variable
        to_sym = to_node.variable
        edge_sym = edge.variable

        if edge.algo == "kshortest":
            if to_sym not in bound:
                # Yen's needs a bound target: scan it first
                plan = self._plan_node_scan(to_node, plan, bound, pending)
            k = edge.max_hops.value if edge.max_hops else 1
            plan = Op.ExpandKShortest(plan, from_sym, edge_sym, to_sym,
                                      direction, edge.types, k,
                                      edge.weight_lambda,
                                      edge.filter_lambda, edge.total_weight)
            if edge.total_weight:
                bound.add(edge.total_weight)
        elif edge.algo:
            max_h = edge.max_hops.value if edge.max_hops else -1
            plan = Op.ExpandShortest(plan, from_sym, edge_sym, to_sym,
                                     direction, edge.types, edge.algo,
                                     max_h, edge.weight_lambda,
                                     edge.filter_lambda, edge.total_weight)
            if edge.total_weight:
                bound.add(edge.total_weight)
        elif edge.var_length:
            min_h = edge.min_hops.value if edge.min_hops else 1
            max_h = edge.max_hops.value if edge.max_hops else -1
            plan = Op.ExpandVariable(plan, from_sym, edge_sym, to_sym,
                                     direction, edge.types, min_h, max_h,
                                     list(edge_syms_in_match),
                                     edge.filter_lambda)
        else:
            plan = Op.Expand(plan, from_sym, edge_sym, to_sym, direction,
                             edge.types, list(edge_syms_in_match))
        edge_syms_in_match.append(edge_sym)
        bound.add(edge_sym)
        bound.add(to_sym)
        # edge property filters
        if isinstance(edge.properties, dict) and not edge.var_length:
            ident = A.Identifier(edge_sym)
            for key, expr in edge.properties.items():
                plan = Op.Filter(plan, A.Binary(
                    "=", A.PropertyLookup(ident, key), expr))
        elif isinstance(edge.properties, dict) and edge.var_length:
            # a property map on a var-length edge applies to EVERY edge of
            # the path (TCK: `-[:WORKED_WITH* {year: 1988}]->`)
            var = _anon("vlprop")
            for key, expr in edge.properties.items():
                plan = Op.Filter(plan, A.Quantifier(
                    "ALL", var, A.Identifier(edge_sym),
                    A.Binary("=", A.PropertyLookup(A.Identifier(var), key),
                             expr)))
        # labels/properties on the endpoint filter whether it was newly
        # bound here or bound by an earlier clause — in the latter case
        # they are constraints, not binders (TCK: `(a)-[:T]->(b:Label)`
        # with b already bound)
        plan = self._apply_node_filters(to_node, plan, bound, pending)
        return plan

    # --- CREATE / MERGE -----------------------------------------------------

    def _validate_create_pattern(self, pattern: A.Pattern, bound: set,
                                 new_in_clause: set, what: str = "CREATE"):
        """openCypher CREATE/MERGE validity (TCK SemanticErrorAcceptance):
        a bound variable may be reused only as a bare path endpoint — any
        labels or properties on it are VariableAlreadyBound; var-length
        edges cannot be created; whole-pattern property scope is checked
        by the caller."""
        elements = pattern.elements
        nodes = elements[0::2]
        edges = elements[1::2]
        # property expressions may reference vars from earlier patterns of
        # the same clause: CREATE (a {v: 1}), (b {v: a.v})
        clause_vars = {n.variable for n in nodes if n.variable} \
            | {e.variable for e in edges if e.variable} | new_in_clause
        seen = set(new_in_clause)
        for node in nodes:
            v = node.variable
            if v and (v in bound or v in seen) \
                    and (node.labels or node.properties is not None):
                # an EMPTY map `(n {})` also counts as re-declaring
                # (TCK LabelsAcceptance "already bound 5")
                raise SemanticException(
                    f"VariableAlreadyBound: {v} is already declared — "
                    f"{what} may reuse it only as a bare endpoint")
            if what == "CREATE" and len(elements) == 1 and v and v in bound:
                raise SemanticException(
                    f"VariableAlreadyBound: {what} ({v}) — the variable "
                    f"is already declared")
            if v:
                seen.add(v)
            props = node.properties
            if isinstance(props, dict):
                for p in props.values():
                    check_expr_scope(p, bound | clause_vars, what)
        for edge in edges:
            if edge.var_length:
                raise SemanticException(
                    f"CreatingVarLength: variable-length relationships "
                    f"cannot be used in {what}")
            v = edge.variable
            if v and (v in bound or v in seen):
                raise SemanticException(
                    f"VariableAlreadyBound: relationship variable {v} is "
                    f"already declared")
            if isinstance(edge.properties, dict):
                for p in edge.properties.values():
                    check_expr_scope(p, bound | clause_vars, what)
        new_in_clause.update(clause_vars)

    def plan_create(self, create: A.Create, plan, bound: set):
        new_in_clause: set = set()
        for pattern in create.patterns:
            self._validate_create_pattern(pattern, bound, new_in_clause)
        for pattern in create.patterns:
            plan = self._plan_create_pattern(pattern, plan, bound)
        return plan

    def _plan_create_pattern(self, pattern: A.Pattern, plan, bound: set):
        elements = pattern.elements
        nodes = elements[0::2]
        edges = elements[1::2]
        for node in nodes:
            node.variable = node.variable or _anon("node")
        for edge in edges:
            edge.variable = edge.variable or _anon("edge")

        first = nodes[0]
        if first.variable not in bound:
            plan = Op.CreateNode(plan, first.variable, first.labels,
                                 first.properties)
            bound.add(first.variable)
        for i, edge in enumerate(edges):
            if edge.direction == "both":
                raise SemanticException(
                    "CREATE requires a directed relationship")
            if not edge.types or len(edge.types) != 1:
                raise SemanticException(
                    "CREATE requires exactly one relationship type")
            to_node = nodes[i + 1]
            create_to = to_node.variable not in bound
            plan = Op.CreateExpand(
                plan, nodes[i].variable, edge.variable, to_node.variable,
                edge.direction, edge.types[0], edge.properties,
                create_to, to_node.labels, to_node.properties)
            bound.add(edge.variable)
            bound.add(to_node.variable)
        if pattern.variable:
            interleaved = []
            for i in range(len(edges)):
                interleaved.append(nodes[i].variable)
                interleaved.append(edges[i].variable)
            interleaved.append(nodes[-1].variable)
            plan = Op.ConstructNamedPath(plan, pattern.variable, interleaved)
            bound.add(pattern.variable)
        return plan

    def plan_merge(self, merge: A.Merge, plan, bound: set):
        pattern = merge.pattern
        self._validate_create_pattern(pattern, bound, set(), what="MERGE")
        # a LITERAL null property can never match nor be created —
        # compile-time error (TCK MiscellaneousErrorAcceptance
        # "merging node/relationship with null property")
        pat_vars = {el.variable for el in pattern.elements if el.variable}
        for el in pattern.elements:
            props = getattr(el, "properties", None)
            if isinstance(props, dict):
                for key, pexpr in props.items():
                    if isinstance(pexpr, A.Literal) and pexpr.value is None:
                        raise SemanticException(
                            f"MergeReadOwnWrites: cannot merge with null "
                            f"property value for {key!r}")
        # match side
        match_bound = set(bound)
        match_plan = self.plan_pattern(pattern, Op.Argument(), match_bound,
                                       [], [])
        for item in merge.on_match:
            check_expr_scope(item.target, bound | pat_vars, "ON MATCH SET")
            if isinstance(item.value, A.Expr):
                check_expr_scope(item.value, bound | pat_vars,
                                 "ON MATCH SET")
            match_plan = self.plan_set_items([item], match_plan, match_bound)
        # create side — an undirected MERGE relationship matches both
        # orientations but CREATES outgoing (TCK MergeRelationshipAcceptance
        # "Use outgoing direction when unspecified")
        import copy
        create_pattern = copy.deepcopy(pattern)
        for el in create_pattern.elements[1::2]:
            if el.direction == "both":
                el.direction = "out"
        create_bound = set(bound)
        create_plan = self._plan_create_pattern(create_pattern, Op.Argument(),
                                                create_bound)
        for item in merge.on_create:
            check_expr_scope(item.target, bound | pat_vars, "ON CREATE SET")
            if isinstance(item.value, A.Expr):
                check_expr_scope(item.value, bound | pat_vars,
                                 "ON CREATE SET")
            create_plan = self.plan_set_items([item], create_plan,
                                              create_bound)
        bound.update(match_bound | create_bound)
        return Op.Merge(plan, match_plan, create_plan)

    def plan_set_items(self, items, plan, bound: set):
        for item in items:
            if item.kind == "prop":
                plan = Op.SetProperty(plan, item.target, item.value)
            elif item.kind == "var_assign":
                plan = Op.SetProperties(plan, item.target.name, item.value,
                                        update=False)
            elif item.kind == "var_update":
                if not isinstance(item.target, A.Identifier):
                    raise SemanticException("+= requires a variable target")
                plan = Op.SetProperties(plan, item.target.name, item.value,
                                        update=True)
            elif item.kind == "label":
                if not isinstance(item.target, A.Identifier):
                    raise SemanticException("SET label requires a variable")
                plan = Op.SetLabels(plan, item.target.name, item.value)
            else:
                raise SemanticException(f"unknown SET item {item.kind}")
        return plan

    def plan_remove(self, remove: A.Remove, plan):
        for item in remove.items:
            if item.kind == "prop":
                plan = Op.RemoveProperty(plan, item.target)
            else:
                if not isinstance(item.target, A.Identifier):
                    raise SemanticException("REMOVE label requires a variable")
                plan = Op.RemoveLabels(plan, item.target.name, item.labels)
        return plan

    def plan_foreach(self, clause: A.Foreach, plan, bound: set):
        sub_bound = set(bound) | {clause.variable}
        update_plan: Op.LogicalOperator = Op.Argument()
        for upd in clause.updates:
            if isinstance(upd, A.Create):
                update_plan = self.plan_create(upd, update_plan, sub_bound)
            elif isinstance(upd, A.Merge):
                update_plan = self.plan_merge(upd, update_plan, sub_bound)
            elif isinstance(upd, A.SetClause):
                update_plan = self.plan_set_items(upd.items, update_plan,
                                                  sub_bound)
            elif isinstance(upd, A.Remove):
                update_plan = self.plan_remove(upd, update_plan)
            elif isinstance(upd, A.Delete):
                update_plan = Op.Delete(update_plan, upd.exprs, upd.detach)
            elif isinstance(upd, A.Foreach):
                update_plan = self.plan_foreach(upd, update_plan, sub_bound)
            else:
                raise SemanticException(
                    "FOREACH allows only update clauses")
        return Op.Foreach(plan, clause.variable, clause.expr, update_plan)

    # --- CALL ---------------------------------------------------------------

    def plan_call(self, clause: A.CallProcedure, plan, bound: set,
                  standalone: bool = False):
        from ..procedures.registry import global_registry
        proc = global_registry.find(clause.name)
        if proc is None:
            raise SemanticException(f"unknown procedure: {clause.name}")
        args = clause.args
        if args is None:
            # no parens: standalone CALL binds declared args from query
            # parameters by name; in-query CALL must pass them explicitly
            # (reference: InvalidArgumentPassingMode)
            if proc.args and not standalone:
                raise SemanticException(
                    f"in-query CALL to {clause.name} requires explicit "
                    f"arguments — implicit (parameter) passing is only "
                    f"allowed for standalone CALL")
            args = [A.Parameter(name) for name, _ in proc.args]
        else:
            n_req, n_max = len(proc.args), len(proc.args) + len(proc.opt_args)
            if not (n_req <= len(args) <= n_max):
                raise SemanticException(
                    f"procedure {clause.name} expects "
                    f"{n_req if n_req == n_max else f'{n_req}..{n_max}'} "
                    f"arguments, got {len(args)}")
            for expr, (aname, atype) in zip(args, proc.args):
                if isinstance(expr, A.Literal) and not _literal_matches_type(
                        expr.value, atype):
                    raise SemanticException(
                        f"procedure {clause.name} argument {aname!r} "
                        f"expects {atype}, got literal {expr.value!r}")
        for expr in args:
            aggs: list = []
            collect_aggregations(expr, aggs)
            if aggs:
                raise SemanticException(
                    f"CALL {clause.name}: aggregation functions are not "
                    f"allowed in procedure arguments")
        known_fields = {f for f, _ in proc.results}
        if clause.yields:
            for f, _ in clause.yields:
                if f not in known_fields:
                    raise SemanticException(
                        f"procedure {clause.name} does not yield {f!r}")
            yields = clause.yields
        elif clause.yield_dash:
            yields = []
        else:
            if not standalone and proc.results:
                raise SemanticException(
                    f"in-query CALL to {clause.name} must YIELD its output "
                    f"(or YIELD - to discard it)")
            yields = [(f, None) for f, _ in proc.results]
        result_fields = [f for f, _ in yields]
        output_symbols = [a or f for f, a in yields]
        for sym in output_symbols:
            if sym in bound:
                raise SemanticException(
                    f"variable {sym!r} is already bound — YIELD must not "
                    f"shadow an existing variable")
        plan = Op.CallProcedureOp(plan, clause.name, args,
                                  result_fields, output_symbols,
                                  memory_limit=clause.memory_limit)
        bound.update(output_symbols)
        if clause.where is not None:
            plan = Op.Filter(plan, clause.where)
        return plan

    # --- RETURN / WITH ------------------------------------------------------

    def plan_projection(self, body: A.ReturnBody, plan, bound: set,
                        has_update: bool, is_with: bool,
                        where: Optional[A.Expr] = None):
        items: list[tuple[A.Expr, str]] = []
        if body.star:
            visible = [s for s in bound if not s.startswith("__")]
            if not visible and not body.items and not is_with:
                raise SemanticException(
                    "NoVariablesInScope: RETURN * with no variables in "
                    "scope")
            for sym in sorted(visible):
                items.append((A.Identifier(sym), sym))
        for expr, alias, verbatim in body.items:
            if is_with and alias is None and not isinstance(expr,
                                                            A.Identifier):
                raise SemanticException(
                    "NoExpressionAlias: expressions in WITH must be "
                    "aliased (use AS)")
            name = alias or verbatim or _expr_name(expr)
            items.append((expr, name))
        for expr, _ in items:
            check_expr_scope(expr, bound, "projection")
        columns = [name for _, name in items]
        if len(set(columns)) != len(columns):
            raise SemanticException("duplicate column names in projection")

        # aggregation split
        agg_specs = []
        group_items: list[tuple[A.Expr, str]] = []
        final_items: list[tuple[A.Expr, str]] = []
        any_agg = False
        for expr, name in items:
            aggs: list = []
            collect_aggregations(expr, aggs)
            if aggs:
                any_agg = True
        if any_agg:
            rewritten = []
            for expr, name in items:
                aggs = []
                collect_aggregations(expr, aggs)
                if not aggs:
                    group_items.append((expr, name))
                    rewritten.append((A.Identifier(name), name))
                else:
                    new_expr = self._rewrite_aggs(expr, agg_specs,
                                                  group_items,
                                                  outer=frozenset(bound))
                    rewritten.append((new_expr, name))
            final_items = rewritten
        if has_update:
            plan = Op.Accumulate(plan)

        if any_agg:
            group_named = [(e, n) for (e, n) in group_items]
            remember = sorted(bound)
            plan = Op.Aggregate(plan, group_named, agg_specs, remember=[])
            inner_items = final_items
        else:
            inner_items = items

        if body.order_by or body.skip is not None or body.limit is not None \
                or body.distinct or is_with or where is not None or True:
            plan = Op.Produce(plan, inner_items)
        if body.distinct:
            plan = Op.Distinct(plan, columns)
        if body.order_by:
            # scope: projected columns, plus the pre-projection variables
            # unless DISTINCT/aggregation made them unavailable
            # (TCK ReturnAcceptance: "ORDER BY of a column introduced in
            # RETURN" vs UndefinedVariable after DISTINCT)
            # ORDER BY may reference projection/grouping expressions that no
            # longer exist as symbols post-aggregation: rewrite any sort
            # subexpression structurally equal to a projected item to its
            # column name (dataclass equality compares AST structure)
            def rewrite_sort(expr):
                for item_expr, name in items:
                    if expr == item_expr:
                        return A.Identifier(name)
                import copy
                clone = copy.copy(expr)
                if isinstance(expr, A.Unary):
                    clone.expr = rewrite_sort(expr.expr)
                elif isinstance(expr, A.Binary):
                    clone.left = rewrite_sort(expr.left)
                    clone.right = rewrite_sort(expr.right)
                elif isinstance(expr, A.PropertyLookup):
                    clone.expr = rewrite_sort(expr.expr)
                elif isinstance(expr, A.FunctionCall):
                    clone.args = [rewrite_sort(a) for a in expr.args]
                elif isinstance(expr, A.ListLiteral):
                    clone.items = [rewrite_sort(a) for a in expr.items]
                elif isinstance(expr, A.MapLiteral):
                    clone.items = {k: rewrite_sort(v)
                                   for k, v in expr.items.items()}
                return clone

            sort_items = [(rewrite_sort(s.expr), s.ascending)
                          for s in body.order_by]
            # scope: projected columns, plus the pre-projection variables
            # unless DISTINCT/aggregation consumed them (TCK: ORDER BY
            # a.age after RETURN DISTINCT a.name is UndefinedVariable)
            sort_scope = set(columns)
            if not body.distinct and not any_agg:
                sort_scope |= bound
            for (sexpr, _), s in zip(sort_items, body.order_by):
                if not any_agg:
                    aggs = []
                    collect_aggregations(s.expr, aggs)
                    if aggs:
                        raise SemanticException(
                            "InvalidAggregation: aggregation in ORDER BY "
                            "requires an aggregating projection")
                check_expr_scope(sexpr, sort_scope, "ORDER BY")
            plan = Op.OrderBy(plan, sort_items)
        if body.skip is not None:
            plan = Op.Skip(plan, body.skip)
        if body.limit is not None:
            # negative LITERAL fails at compile; a negative PARAMETER is
            # clamped at runtime (TCK OrderByAcceptance pair)
            lim = body.limit
            if (isinstance(lim, A.Unary) and lim.op == "-"
                    and isinstance(lim.expr, A.Literal)) or (
                    isinstance(lim, A.Literal)
                    and isinstance(lim.value, int) and lim.value < 0):
                raise SemanticException(
                    "NegativeIntegerArgument: LIMIT must be a "
                    "non-negative integer")
            plan = Op.Limit(plan, body.limit)
        if where is not None:
            plan = Op.Filter(plan, where)
        if is_with:
            # WITH closes the variable scope: only projected columns may
            # leak downstream — stale frame keys from before the WITH must
            # not make later pattern variables look bound (TCK
            # WithAcceptance "A simple pattern with one bound endpoint")
            plan = Op.ScopeBarrier(plan, columns)
        return plan, columns

    def _rewrite_aggs(self, expr: A.Expr, agg_specs: list,
                      group_items: list | None = None,
                      locals_: frozenset = frozenset(),
                      outer: frozenset = frozenset()) -> A.Expr:
        """Replace aggregate calls with references to Aggregate outputs and
        non-aggregate identifiers with implicit grouping keys.

        `locals_` carries comprehension/reduce-bound variables: references
        to them are NOT grouping keys — they are bound at evaluation time
        (TCK ListComprehension: `[x IN collect(p) | head(nodes(x))]`)."""
        if isinstance(expr, A.CountStar):
            name = _anon("agg")
            agg_specs.append(("count", None, False, name))
            return A.Identifier(name)
        if isinstance(expr, A.FunctionCall) and \
                expr.name in Op.AGGREGATE_FUNCTIONS:
            name = _anon("agg")
            arg = expr.args[0] if expr.args else None
            if len(expr.args) > 1:
                # e.g. percentileDisc(x, p): extra args ride in slot 4
                agg_specs.append((expr.name, arg, expr.distinct, name,
                                  expr.args[1]))
            else:
                agg_specs.append((expr.name, arg, expr.distinct, name))
            return A.Identifier(name)
        if group_items is not None and isinstance(
                expr, (A.Identifier, A.PropertyLookup)) \
                and not (expr_symbols(expr, set()) & locals_):
            # a non-aggregate variable reference inside an aggregating
            # item becomes an implicit grouping key (`RETURN {foo: a.name,
            # kids: collect(...)}` groups by a.name — TCK
            # AggregationAcceptance "aggregates inside non-aggregate
            # expressions")
            for g_expr, g_name in group_items:
                if g_expr == expr:
                    return A.Identifier(g_name)
            name = _anon("group")
            group_items.append((expr, name))
            return A.Identifier(name)
        # rebuild children
        import copy

        def rw(e, extra_locals=()):
            return self._rewrite_aggs(e, agg_specs, group_items,
                                      locals_ | frozenset(extra_locals),
                                      outer)

        clone = copy.copy(expr)
        if isinstance(expr, A.Unary):
            clone.expr = rw(expr.expr)
        elif isinstance(expr, A.IsNull):
            clone.expr = rw(expr.expr)
        elif isinstance(expr, (A.PatternExpr, A.PatternComprehension)):
            # pattern-introduced variables are locals; variables bound
            # OUTSIDE the pattern (anchors) must become grouping keys so
            # the pattern can re-anchor post-aggregation (`RETURN
            # size([(a)-->(b) | b]) + count(*)` groups by a)
            pat_vars = set()
            for el in expr.pattern.elements:
                if getattr(el, "variable", None):
                    pat_vars.add(el.variable)
            if expr.pattern.variable:        # named path: [p = (a)--() | p]
                pat_vars.add(expr.pattern.variable)
            if group_items is not None:
                # only pattern vars bound OUTSIDE the pattern are anchors;
                # the rest are fresh per-match locals
                for var in sorted((pat_vars & outer) - locals_):
                    ident = A.Identifier(var)
                    if not any(g_expr == ident for g_expr, _ in group_items):
                        group_items.append((ident, var))
            # property-map expressions inside the pattern may reference
            # outer variables — those must become grouping keys too
            clone.pattern = copy.deepcopy(expr.pattern)
            for el in clone.pattern.elements:
                props = getattr(el, "properties", None)
                if isinstance(props, dict):
                    for key in list(props):
                        props[key] = rw(props[key], tuple(pat_vars))
            if isinstance(expr, A.PatternComprehension):
                if expr.where is not None:
                    clone.where = rw(expr.where, tuple(pat_vars))
                clone.projection = rw(expr.projection, tuple(pat_vars))
        elif isinstance(expr, A.Binary):
            clone.left = rw(expr.left)
            clone.right = rw(expr.right)
        elif isinstance(expr, A.FunctionCall):
            clone.args = [rw(a) for a in expr.args]
        elif isinstance(expr, A.PropertyLookup):
            clone.expr = rw(expr.expr)
        elif isinstance(expr, A.ListLiteral):
            clone.items = [rw(a) for a in expr.items]
        elif isinstance(expr, A.MapLiteral):
            clone.items = {k: rw(v) for k, v in expr.items.items()}
        elif isinstance(expr, A.Subscript):
            clone.expr = rw(expr.expr)
            clone.index = rw(expr.index)
        elif isinstance(expr, A.Slice):
            clone.expr = rw(expr.expr)
            clone.lo = rw(expr.lo) if expr.lo is not None else None
            clone.hi = rw(expr.hi) if expr.hi is not None else None
        elif isinstance(expr, A.CaseExpr):
            clone.test = rw(expr.test) if expr.test is not None else None
            clone.whens = [(rw(c), rw(r)) for c, r in expr.whens]
            clone.default = (rw(expr.default)
                             if expr.default is not None else None)
        elif isinstance(expr, A.ListComprehension):
            clone.list_expr = rw(expr.list_expr)
            # aggregates may only feed the source list; aggregating inside
            # the filter/projection is invalid (TCK SemanticErrorAcceptance
            # "Failing when using aggregation in list comprehension")
            for part in (expr.where, expr.projection):
                if part is not None:
                    aggs: list = []
                    collect_aggregations(part, aggs)
                    if aggs:
                        raise SemanticException(
                            "InvalidAggregation: aggregation inside a list "
                            "comprehension is not allowed")
            if expr.where is not None:
                clone.where = rw(expr.where, (expr.var,))
            if expr.projection is not None:
                clone.projection = rw(expr.projection, (expr.var,))
        elif isinstance(expr, A.Quantifier):
            clone.list_expr = rw(expr.list_expr)
            clone.where = rw(expr.where, (expr.var,))
        elif isinstance(expr, A.Reduce):
            clone.init = rw(expr.init)
            clone.list_expr = rw(expr.list_expr)
            clone.expr = rw(expr.expr, (expr.acc, expr.var))
        return clone


def _literal_matches_type(value, type_decl: str) -> bool:
    """Compile-time literal-vs-declared-type check for procedure args.

    Type syntax follows the reference's mgp type names (mg_procedure.h
    mgp_type): INTEGER, FLOAT, NUMBER, STRING, BOOLEAN, MAP, LIST OF T,
    ANY, NODE, RELATIONSHIP, PATH; a '?' suffix means nullable.
    """
    t = type_decl.strip().upper()
    nullable = t.endswith("?")
    if nullable:
        t = t[:-1]
    if value is None:
        return nullable
    if t.startswith("LIST"):
        return isinstance(value, (list, tuple))
    def _numeric(v):
        # INTEGER/FLOAT/NUMBER coerce freely between int and float
        # (TCK: "argument of type INTEGER accepts value of type FLOAT")
        return isinstance(v, (int, float)) and not isinstance(v, bool)

    checkers = {
        "INTEGER": _numeric,
        "FLOAT": _numeric,
        "NUMBER": _numeric,
        "STRING": lambda v: isinstance(v, str),
        "BOOLEAN": lambda v: isinstance(v, bool),
        "MAP": lambda v: isinstance(v, dict),
    }
    check = checkers.get(t)
    return True if check is None else check(value)


def _single_has_update(single: A.SingleQuery) -> bool:
    return any(isinstance(c, (A.Create, A.Merge, A.SetClause, A.Remove,
                              A.Delete, A.Foreach)) for c in single.clauses)


def _flip(op: str) -> str:
    return {"=": "=", "<": ">", ">": "<", "<=": ">=", ">=": "<="}[op]


def _expr_name(expr: A.Expr) -> str:
    if isinstance(expr, A.Identifier):
        return expr.name
    if isinstance(expr, A.PropertyLookup):
        return f"{_expr_name(expr.expr)}.{expr.prop}"
    if isinstance(expr, A.CountStar):
        return "count(*)"
    if isinstance(expr, A.FunctionCall):
        return f"{expr.name}({', '.join(_expr_name(a) for a in expr.args)})"
    if isinstance(expr, A.Literal):
        return repr(expr.value)
    if isinstance(expr, A.Parameter):
        return f"${expr.name}"
    if isinstance(expr, A.Subscript):
        return f"{_expr_name(expr.expr)}[{_expr_name(expr.index)}]"
    if isinstance(expr, A.Binary):
        return f"{_expr_name(expr.left)} {expr.op} {_expr_name(expr.right)}"
    if isinstance(expr, A.Unary):
        return f"{expr.op} {_expr_name(expr.expr)}"
    if isinstance(expr, A.Slice):
        lo = _expr_name(expr.lo) if expr.lo is not None else ""
        hi = _expr_name(expr.hi) if expr.hi is not None else ""
        return f"{_expr_name(expr.expr)}[{lo}..{hi}]"
    if isinstance(expr, A.LabelsTest):
        return f"{_expr_name(expr.expr)}:{':'.join(expr.labels)}"
    if isinstance(expr, A.IsNull):
        return (f"{_expr_name(expr.expr)} IS "
                f"{'NOT ' if expr.negated else ''}NULL")
    if isinstance(expr, A.ListLiteral):
        return "[" + ", ".join(_expr_name(i) for i in expr.items) + "]"
    if isinstance(expr, A.MapLiteral):
        return "{" + ", ".join(f"{k}: {_expr_name(v)}"
                               for k, v in expr.items.items()) + "}"
    return "expression"


def _param_props_predicate(sym: str, param: A.Parameter) -> A.Expr:
    # n matches {k: v, ...} parameter map: all entries equal
    # implemented as a function-less AND chain at eval time via a custom
    # expression — reuse quantifier over keys is overkill; build Binary AND
    # over map items is impossible without knowing keys, so compare maps:
    # properties(n) "contains" param — evaluate as subset via ALL quantifier.
    return A.Quantifier(
        "ALL", "__k__",
        A.FunctionCall("keys", [param]),
        A.Binary("=",
                 A.Subscript(A.Identifier(sym), A.Identifier("__k__")),
                 A.Subscript(param, A.Identifier("__k__"))))
