"""Tree-walking expression evaluator over a frame of bound symbols.

Counterpart of the reference's ExpressionEvaluator
(/root/reference/src/query/interpret/eval.hpp): evaluates AST expressions
against a dict frame, with openCypher null propagation, property access on
graph objects, list/map operations, quantifiers, CASE, and the builtin
function library (functions.py).
"""

from __future__ import annotations

import re

from ..exceptions import EntityNotFound, SemanticException, TypeException
from ..storage.common import View
from ..storage.storage import EdgeAccessor, VertexAccessor
from .frontend import ast as A
from . import values as V
from .values import Path


class EvalContext:
    """Evaluation environment: storage accessor, parameters, view."""

    def __init__(self, accessor, parameters=None, view: View = View.NEW,
                 functions=None):
        self.accessor = accessor
        self.parameters = parameters or {}
        self.view = view
        if functions is None:
            from .functions import FUNCTIONS
            functions = FUNCTIONS
        self.functions = functions

    @property
    def storage(self):
        return self.accessor.storage


class Evaluator:
    def __init__(self, ctx: EvalContext) -> None:
        self.ctx = ctx

    def eval(self, expr: A.Expr, frame: dict):
        method = getattr(self, "_eval_" + type(expr).__name__, None)
        if method is None:
            raise SemanticException(
                f"unsupported expression: {type(expr).__name__}")
        return method(expr, frame)

    # --- leaves -------------------------------------------------------------

    def _eval_Literal(self, e: A.Literal, frame):
        return e.value

    def _eval_Parameter(self, e: A.Parameter, frame):
        if e.name not in self.ctx.parameters:
            raise SemanticException(f"parameter ${e.name} not provided")
        return self.ctx.parameters[e.name]

    def _eval_Identifier(self, e: A.Identifier, frame):
        if e.name not in frame:
            raise SemanticException(f"unbound variable: {e.name}")
        return frame[e.name]

    # --- structure access ---------------------------------------------------

    def _eval_PropertyLookup(self, e: A.PropertyLookup, frame):
        obj = self.eval(e.expr, frame)
        return self.get_property(obj, e.prop)

    def get_property(self, obj, prop: str):
        if obj is None:
            return None
        if isinstance(obj, dict):
            return obj.get(prop)
        if isinstance(obj, VertexAccessor) or isinstance(obj, EdgeAccessor):
            st = self.checked_state(obj)
            pid = self.ctx.storage.property_mapper.maybe_name_to_id(prop)
            if pid is None:
                return None
            return st.properties.get(pid)
        # temporal/point component access (d.year, p.x, ...)
        attr = getattr(type(obj), prop, None)
        if attr is not None and isinstance(attr, property):
            return getattr(obj, prop)
        if hasattr(obj, prop) and not callable(getattr(obj, prop)):
            return getattr(obj, prop)
        raise TypeException(
            f"property access on {V.type_name(obj)} is not supported")

    def checked_state(self, obj):
        """Materialized accessor state; raises on a deleted entity
        (TCK DeletedEntityAccess; reference: ExpressionEvaluator raises
        on property/label access of deleted objects, eval.hpp)."""
        if isinstance(obj, VertexAccessor):
            # property/label reads: skip the O(degree) adjacency copy
            st = obj._state(self.ctx.view, need_edges=False)
        else:
            st = obj._state(self.ctx.view)
        if not st.exists or st.deleted:
            kind = ("node" if isinstance(obj, VertexAccessor)
                    else "relationship")
            raise EntityNotFound(
                f"cannot access properties of a deleted {kind}")
        return st

    def _eval_LabelsTest(self, e: A.LabelsTest, frame):
        obj = self.eval(e.expr, frame)
        if obj is None:
            return None
        if not isinstance(obj, VertexAccessor):
            raise TypeException("labels test on a non-node value")
        mapper = self.ctx.storage.label_mapper
        for name in e.labels:
            lid = mapper.maybe_name_to_id(name)
            if lid is None or not obj.has_label(lid, self.ctx.view):
                return False
        return True

    def _eval_IsNull(self, e: A.IsNull, frame):
        v = self.eval(e.expr, frame)
        return (v is not None) if e.negated else (v is None)

    def _eval_Subscript(self, e: A.Subscript, frame):
        obj = self.eval(e.expr, frame)
        idx = self.eval(e.index, frame)
        if obj is None or idx is None:
            return None
        if isinstance(obj, (list, tuple)):
            if not isinstance(idx, int) or isinstance(idx, bool):
                raise TypeException("list index must be an integer")
            if idx < -len(obj) or idx >= len(obj):
                return None
            return obj[idx]
        if isinstance(obj, dict):
            if not isinstance(idx, str):
                raise TypeException("map key must be a string")
            return obj.get(idx)
        if isinstance(obj, (VertexAccessor, EdgeAccessor)):
            if not isinstance(idx, str):
                raise TypeException("property key must be a string")
            return self.get_property(obj, idx)
        raise TypeException(f"subscript on {V.type_name(obj)}")

    def _eval_Slice(self, e: A.Slice, frame):
        obj = self.eval(e.expr, frame)
        if obj is None:
            return None
        if not isinstance(obj, (list, tuple)):
            raise TypeException("slice on a non-list value")
        lo = self.eval(e.lo, frame) if e.lo is not None else 0
        hi = self.eval(e.hi, frame) if e.hi is not None else len(obj)
        if lo is None or hi is None:
            return None
        return list(obj[lo:hi])

    def _eval_ListLiteral(self, e: A.ListLiteral, frame):
        return [self.eval(item, frame) for item in e.items]

    def _eval_MapLiteral(self, e: A.MapLiteral, frame):
        return {k: self.eval(v, frame) for k, v in e.items.items()}

    # --- operators ----------------------------------------------------------

    def _eval_Unary(self, e: A.Unary, frame):
        v = self.eval(e.expr, frame)
        if e.op == "NOT":
            return V.ternary_not(v)
        if v is None:
            return None
        if e.op == "-":
            if V.is_numeric(v):
                return -v
            from ..utils.temporal import Duration
            if isinstance(v, Duration):
                return -v
            raise TypeException(f"cannot negate {V.type_name(v)}")
        if e.op == "+":
            if V.is_numeric(v):
                return v
            raise TypeException(f"invalid unary '+' on {V.type_name(v)}")
        raise SemanticException(f"unknown unary op {e.op}")

    def _eval_Binary(self, e: A.Binary, frame):
        op = e.op
        if op == "AND":
            return V.ternary_and(self.eval(e.left, frame),
                                 self.eval(e.right, frame))
        if op == "OR":
            return V.ternary_or(self.eval(e.left, frame),
                                self.eval(e.right, frame))
        if op == "XOR":
            return V.ternary_xor(self.eval(e.left, frame),
                                 self.eval(e.right, frame))
        a = self.eval(e.left, frame)
        b = self.eval(e.right, frame)
        if op == "+":
            return V.cypher_add(a, b)
        if op == "-":
            return V.cypher_sub(a, b)
        if op == "*":
            return V.cypher_mul(a, b)
        if op == "/":
            return V.cypher_div(a, b)
        if op == "%":
            return V.cypher_mod(a, b)
        if op == "^":
            return V.cypher_pow(a, b)
        if op == "=":
            return V.cypher_eq(a, b)
        if op == "<>":
            r = V.cypher_eq(a, b)
            return None if r is None else not r
        if op == "<":
            return V.cypher_lt(a, b)
        if op == ">":
            return V.cypher_lt(b, a)
        if op == "<=":
            lt = V.cypher_lt(a, b)
            if lt is True:
                return True
            eq = V.cypher_eq(a, b)
            if lt is None or eq is None:
                return None
            return bool(eq)
        if op == ">=":
            lt = V.cypher_lt(b, a)
            if lt is True:
                return True
            eq = V.cypher_eq(a, b)
            if lt is None or eq is None:
                return None
            return bool(eq)
        if op == "IN":
            return self._eval_in(a, b)
        if op == "STARTS WITH":
            return self._string_pred(a, b, str.startswith)
        if op == "ENDS WITH":
            return self._string_pred(a, b, str.endswith)
        if op == "CONTAINS":
            return self._string_pred(a, b, str.__contains__)
        if op == "=~":
            if a is None or b is None:
                return None
            if not isinstance(a, str) or not isinstance(b, str):
                raise TypeException("regex match requires strings")
            return re.fullmatch(b, a) is not None
        raise SemanticException(f"unknown operator {op}")

    @staticmethod
    def _string_pred(a, b, fn):
        # non-string operands yield null, not an error (TCK
        # StartsWithAcceptance "Handling non-string operands")
        if not isinstance(a, str) or not isinstance(b, str):
            return None
        return fn(a, b)

    @staticmethod
    def _eval_in(a, b):
        if b is None:
            return None
        if not isinstance(b, (list, tuple)):
            raise TypeException("IN requires a list")
        if a is None:
            return None if b else False
        saw_null = False
        for item in b:
            r = V.cypher_eq(a, item)
            if r is True:
                return True
            if r is None:
                saw_null = True
        return None if saw_null else False

    # --- functions / higher-order -------------------------------------------

    def _eval_FunctionCall(self, e: A.FunctionCall, frame):
        fn = self.ctx.functions.get(e.name)
        if fn is None:
            raise SemanticException(f"unknown function: {e.name}()")
        args = [self.eval(a, frame) for a in e.args]
        return fn(self, args)

    def _eval_CountStar(self, e, frame):
        raise SemanticException("count(*) is only valid in RETURN/WITH")

    def _eval_CaseExpr(self, e: A.CaseExpr, frame):
        if e.test is not None:
            test = self.eval(e.test, frame)
            for cond, result in e.whens:
                if V.cypher_eq(test, self.eval(cond, frame)) is True:
                    return self.eval(result, frame)
        else:
            for cond, result in e.whens:
                if self.eval(cond, frame) is True:
                    return self.eval(result, frame)
        return self.eval(e.default, frame) if e.default is not None else None

    def _eval_ListComprehension(self, e: A.ListComprehension, frame):
        lst = self.eval(e.list_expr, frame)
        if lst is None:
            return None
        if not isinstance(lst, (list, tuple)):
            raise TypeException("list comprehension requires a list")
        out = []
        inner = dict(frame)
        for item in lst:
            inner[e.var] = item
            if e.where is not None and self.eval(e.where, inner) is not True:
                continue
            out.append(self.eval(e.projection, inner)
                       if e.projection is not None else item)
        return out

    def _eval_Quantifier(self, e: A.Quantifier, frame):
        lst = self.eval(e.list_expr, frame)
        if lst is None:
            return None
        if not isinstance(lst, (list, tuple)):
            raise TypeException(f"{e.kind} requires a list")
        inner = dict(frame)
        results = []
        for item in lst:
            inner[e.var] = item
            results.append(self.eval(e.where, inner))
        trues = sum(1 for r in results if r is True)
        nulls = sum(1 for r in results if r is None)
        n = len(results)
        if e.kind == "ALL":
            if trues == n:
                return True
            return None if trues + nulls == n else False
        if e.kind == "ANY":
            if trues > 0:
                return True
            return None if nulls > 0 else False
        if e.kind == "NONE":
            if trues > 0:
                return False
            return None if nulls > 0 else True
        if e.kind == "SINGLE":
            if nulls:
                return None
            return trues == 1
        raise SemanticException(f"unknown quantifier {e.kind}")

    def _eval_Reduce(self, e: A.Reduce, frame):
        lst = self.eval(e.list_expr, frame)
        if lst is None:
            return None
        if not isinstance(lst, (list, tuple)):
            raise TypeException("reduce requires a list")
        acc = self.eval(e.init, frame)
        inner = dict(frame)
        for item in lst:
            inner[e.acc] = acc
            inner[e.var] = item
            acc = self.eval(e.expr, inner)
        return acc

    def _eval_PatternExpr(self, e: A.PatternExpr, frame):
        """exists((n)-[...]->(m)) — run a mini-match anchored on bound vars."""
        from .plan.pattern_match import match_pattern_anchored
        for _ in match_pattern_anchored(self.ctx, e.pattern, frame):
            return True
        return False

    def _eval_EnumLiteral(self, e: A.EnumLiteral, frame):
        # positions are immutable (no redefinition, ALTER only appends), so a
        # literal resolves once per (AST node, storage) and is memoized
        storage = self.ctx.storage
        memo = e.resolved
        if memo is not None and memo[0]() is storage:
            return memo[1]
        import weakref
        from ..storage.enums import enum_registry
        value = enum_registry(storage).value(e.enum_name, e.value_name)
        e.resolved = (weakref.ref(storage), value)
        return value

    def _eval_PatternComprehension(self, e: A.PatternComprehension, frame):
        """[(n)-->(m) WHERE pred | expr] — collect projections per match."""
        from .plan.pattern_match import match_pattern_anchored
        out = []
        for match_frame in match_pattern_anchored(self.ctx, e.pattern, frame):
            inner = dict(frame)
            inner.update(match_frame)
            if e.where is not None and self.eval(e.where, inner) is not True:
                continue
            out.append(self.eval(e.projection, inner))
        return out
