"""Event triggers: statements executed on commit (reference: query/trigger.hpp).

Phases BEFORE COMMIT (same transaction, can mutate) and AFTER COMMIT
(separate transaction). Event filters: CREATE/UPDATE/DELETE x VERTICES/EDGES
(or any). Predefined context variables exposed to trigger statements:
createdVertices, createdEdges, deletedVertices, deletedEdges,
updatedVertices, updatedEdges — mirroring the reference's trigger context
(trigger_context.cpp).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

from ..storage.delta import DeltaAction


@dataclass
class Trigger:
    name: str
    event: str | None       # e.g. "CREATE", "DELETE VERTICES", None = any
    phase: str              # "BEFORE" | "AFTER"
    statement: str


class TriggerStore:
    def __init__(self, interpreter_context) -> None:
        self.ictx = interpreter_context
        self._lock = threading.Lock()
        self._triggers: dict[str, Trigger] = {}
        self._firing = threading.local()  # recursion guard
        self._kv = getattr(interpreter_context, "kvstore", None)
        if self._kv is not None:
            self._restore()
        interpreter_context.storage.on_commit_hooks.append(self._on_commit)

    def _restore(self) -> None:
        """Reload persisted triggers (reference: RestoreTriggers,
        memgraph.cpp:926)."""
        import json
        for key, raw in self._kv.items_with_prefix("trigger:"):
            data = json.loads(raw.decode("utf-8"))
            self._triggers[data["name"]] = Trigger(
                data["name"], data.get("event"), data.get("phase", "AFTER"),
                data["statement"])

    def _persist(self, trigger: Trigger) -> None:
        if self._kv is not None:
            import json
            self._kv.put(f"trigger:{trigger.name}", json.dumps({
                "name": trigger.name, "event": trigger.event,
                "phase": trigger.phase, "statement": trigger.statement}))

    def create(self, name, event, phase, statement) -> None:
        from ..exceptions import QueryException
        if not statement:
            raise QueryException("trigger statement must not be empty")
        with self._lock:
            if name in self._triggers:
                raise QueryException(f"trigger {name!r} already exists")
            trigger = Trigger(name, event, phase or "AFTER", statement)
            self._triggers[name] = trigger
            self._persist(trigger)

    def drop(self, name) -> None:
        from ..exceptions import QueryException
        with self._lock:
            if name not in self._triggers:
                raise QueryException(f"trigger {name!r} does not exist")
            del self._triggers[name]
            if self._kv is not None:
                self._kv.delete(f"trigger:{name}")

    def all(self):
        with self._lock:
            return sorted(self._triggers.values(), key=lambda t: t.name)

    # --- firing -------------------------------------------------------------

    def _on_commit(self, txn, commit_ts) -> None:
        if getattr(self._firing, "active", False):
            return  # changes made BY a trigger do not re-fire triggers
        with self._lock:
            triggers = list(self._triggers.values())
        if not triggers:
            return
        context = self._build_context(txn)
        if context is None:
            return
        from .interpreter import Interpreter
        from ..observability.metrics import global_metrics
        self._firing.active = True
        try:
            for trig in triggers:
                if not self._event_matches(trig.event, context):
                    continue
                interp = Interpreter(self.ictx, system=True)
                try:
                    interp.execute(trig.statement, parameters=context)
                    global_metrics.increment("trigger.fired_total")
                except Exception:
                    # AFTER-commit trigger failures must not corrupt the
                    # session, but they must never be silent either:
                    # loud log with the trigger name + a counted error
                    # (alerting surface — a broken trigger statement
                    # otherwise drops every firing on the floor)
                    import logging
                    global_metrics.increment("trigger.errors_total")
                    logging.getLogger(__name__).exception(
                        "trigger %s failed (statement %r)",
                        trig.name, trig.statement)
        finally:
            self._firing.active = False

    def _build_context(self, txn):
        created_v, deleted_v, updated_v = [], [], []
        created_e, deleted_e, updated_e = [], [], []
        seen_updated = set()
        for delta in txn.deltas:
            obj = delta.obj
            from ..storage.objects import Vertex
            is_vertex = isinstance(obj, Vertex)
            a = delta.action
            if a is DeltaAction.DELETE_OBJECT:
                (created_v if is_vertex else created_e).append(obj)
            elif a is DeltaAction.RECREATE_OBJECT:
                (deleted_v if is_vertex else deleted_e).append(obj)
            elif a in (DeltaAction.SET_PROPERTY, DeltaAction.ADD_LABEL,
                       DeltaAction.REMOVE_LABEL):
                if id(obj) not in seen_updated:
                    seen_updated.add(id(obj))
                    (updated_v if is_vertex else updated_e).append(obj)
        if not any((created_v, created_e, deleted_v, deleted_e, updated_v,
                    updated_e)):
            return None
        # expose gids (trigger statements can MATCH by id)
        return {
            "createdVertices": [v.gid for v in created_v],
            "createdEdges": [e.gid for e in created_e],
            "deletedVertices": [v.gid for v in deleted_v],
            "deletedEdges": [e.gid for e in deleted_e],
            "updatedVertices": [v.gid for v in updated_v],
            "updatedEdges": [e.gid for e in updated_e],
        }

    @staticmethod
    def _event_matches(event, context) -> bool:
        if not event:
            return True
        ev = event.upper()
        checks = {
            "CREATE": context["createdVertices"] or context["createdEdges"],
            "DELETE": context["deletedVertices"] or context["deletedEdges"],
            "UPDATE": context["updatedVertices"] or context["updatedEdges"],
        }
        for kind, nonempty in checks.items():
            if kind in ev and nonempty:
                if "VERTICES" in ev:
                    key = {"CREATE": "createdVertices",
                           "DELETE": "deletedVertices",
                           "UPDATE": "updatedVertices"}[kind]
                    return bool(context[key])
                if "EDGES" in ev:
                    key = {"CREATE": "createdEdges",
                           "DELETE": "deletedEdges",
                           "UPDATE": "updatedEdges"}[kind]
                    return bool(context[key])
                return True
        return False


import weakref

_STORES: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_STORES_LOCK = threading.Lock()


def global_trigger_store(interpreter_context) -> TriggerStore:
    with _STORES_LOCK:
        store = _STORES.get(interpreter_context)
        if store is None:
            store = TriggerStore(interpreter_context)
            _STORES[interpreter_context] = store
        return store
