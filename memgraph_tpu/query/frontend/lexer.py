"""openCypher tokenizer.

Hand-written (the environment has no parser-generator runtime; the reference
uses ANTLR4 — /root/reference/src/query/frontend/opencypher/grammar/).
Covers the full lexical surface needed by the parser: identifiers, backtick
escapes, keywords (case-insensitive), numbers (int/float/hex/octal/
scientific), single/double-quoted strings with escapes, parameters, all
operators/punctuation, and both comment styles.
"""

from __future__ import annotations

from dataclasses import dataclass

from ...exceptions import SyntaxException

KEYWORDS = {
    "MATCH", "OPTIONAL", "WHERE", "RETURN", "CREATE", "MERGE", "SET",
    "REMOVE", "DELETE", "DETACH", "WITH", "UNWIND", "AS", "ORDER", "BY",
    "SKIP", "LIMIT", "ASC", "ASCENDING", "DESC", "DESCENDING", "DISTINCT",
    "AND", "OR", "XOR", "NOT", "IN", "STARTS", "ENDS", "CONTAINS", "IS",
    "NULL", "TRUE", "FALSE", "CASE", "WHEN", "THEN", "ELSE", "END", "ON",
    "CALL", "YIELD", "UNION", "ALL", "ANY", "NONE", "SINGLE", "EXISTS",
    "INDEX", "DROP", "CONSTRAINT", "ASSERT", "UNIQUE", "BEGIN", "COMMIT",
    "ROLLBACK", "EXPLAIN", "PROFILE", "SHOW", "INFO", "STORAGE", "DATABASE",
    "TRANSACTIONS", "TERMINATE", "FOREACH", "LOAD", "CSV", "FROM", "HEADER",
    "NO", "ROW", "FIELDTERMINATOR", "COALESCE", "COUNT", "EDGE", "TYPED",
    "SNAPSHOT", "RECOVER", "DUMP", "ANALYZE", "GRAPH", "FREE", "MEMORY",
    "QUERY", "UNLIMITED", "PROCEDURE",
    "ISOLATION", "LEVEL", "NEXT", "READ", "COMMITTED", "UNCOMMITTED",
    "GLOBAL", "SESSION", "TRANSACTION", "STATS", "TRIGGER", "TRIGGERS",
    "AFTER", "BEFORE", "EXECUTE", "CREATED", "UPDATED", "DELETED", "VERTICES",
    "EDGES", "MODE", "ANALYTICAL", "TRANSACTIONAL", "STREAM", "STREAMS",
    "START", "STOP", "TOPICS", "TRANSFORM", "BATCH_SIZE", "BATCH_INTERVAL",
    "CONSUMER_GROUP", "BOOTSTRAP_SERVERS", "CHECK", "SERVICE_URL", "TTL",
    "AT", "EVERY", "ENABLE", "DISABLE", "USING", "PERIODIC", "HOPS",
    "PARALLEL", "EXECUTION",
    "KEY", "OF", "TYPE", "POINT", "TEXT", "VECTORS", "PASSWORD", "USER",
    "ROLE", "PRIVILEGES", "GRANT", "DENY", "REVOKE", "TO", "FOR", "METRICS",
    "REPLICA", "REPLICAS", "MAIN", "REPLICATION", "REGISTER", "SYNC", "USE", "DATABASES",
    "ASYNC", "STRICT_SYNC", "PORT", "SERVER", "VERSION", "BUILD", "SCHEMA",
    "LABELS", "REQUIRE", "ID",
}


class T:
    """Token types."""
    IDENT = "IDENT"
    KEYWORD = "KEYWORD"
    INT = "INT"
    FLOAT = "FLOAT"
    STRING = "STRING"
    PARAM = "PARAM"          # $name or $0
    EOF = "EOF"
    # punctuation/operators carry their literal text as type
    # e.g. '(', ')', '[', ']', '{', '}', ',', ':', ';', '.', '..',
    # '+', '-', '*', '/', '%', '^', '=', '<>', '<', '>', '<=', '>=',
    # '=~', '|', '->', '<-', '--', '+=', '.."


@dataclass
class Token:
    type: str        # T.IDENT / T.KEYWORD / ... or literal punctuation
    value: object    # text for idents/keywords, parsed value for literals
    pos: int
    line: int
    col: int
    raw: str | None = None   # original source text (keywords keep case)

    def is_kw(self, *names: str) -> bool:
        return self.type == T.KEYWORD and self.value in names

    def __repr__(self) -> str:  # pragma: no cover
        return f"Token({self.type!r}, {self.value!r})"


_PUNCT3 = ()
_PUNCT2 = ("<>", "<=", ">=", "=~", "->", "<-", "--", "+=", "..", "||", "::")
_PUNCT1 = ("(", ")", "[", "]", "{", "}", ",", ":", ";", ".", "+", "-", "*",
           "/", "%", "^", "=", "<", ">", "|", "&")


def tokenize(text: str) -> list[Token]:
    tokens: list[Token] = []
    i = 0
    n = len(text)
    line = 1
    line_start = 0

    def err(msg, pos):
        raise SyntaxException(
            f"line {line}:{pos - line_start + 1} {msg}")

    while i < n:
        c = text[i]
        # whitespace
        if c in " \t\r\n":
            if c == "\n":
                line += 1
                line_start = i + 1
            i += 1
            continue
        # comments
        if text.startswith("//", i):
            j = text.find("\n", i)
            i = n if j < 0 else j
            continue
        if text.startswith("/*", i):
            j = text.find("*/", i + 2)
            if j < 0:
                err("unterminated block comment", i)
            line += text.count("\n", i, j)
            i = j + 2
            continue
        col = i - line_start + 1
        # strings
        if c in "'\"":
            value, j = _scan_string(text, i, err)
            tokens.append(Token(T.STRING, value, i, line, col))
            i = j
            continue
        # backtick-escaped identifier
        if c == "`":
            j = text.find("`", i + 1)
            if j < 0:
                err("unterminated escaped identifier", i)
            tokens.append(Token(T.IDENT, text[i + 1:j], i, line, col))
            i = j + 1
            continue
        # numbers
        if c.isdigit() or (c == "." and i + 1 < n and text[i + 1].isdigit()):
            tok, j = _scan_number(text, i, line, col, err)
            # disambiguate "1..2" (range) from float "1."
            tokens.append(tok)
            i = j
            continue
        # parameters
        if c == "$":
            j = i + 1
            if j < n and text[j] == "`":
                k = text.find("`", j + 1)
                if k < 0:
                    err("unterminated escaped parameter name", i)
                tokens.append(Token(T.PARAM, text[j + 1:k], i, line, col))
                i = k + 1
                continue
            while j < n and (text[j].isalnum() or text[j] == "_"):
                j += 1
            if j == i + 1:
                err("invalid parameter name", i)
            tokens.append(Token(T.PARAM, text[i + 1:j], i, line, col))
            i = j
            continue
        # identifiers / keywords
        if c.isalpha() or c == "_":
            j = i + 1
            while j < n and (text[j].isalnum() or text[j] == "_"):
                j += 1
            word = text[i:j]
            upper = word.upper()
            if upper in KEYWORDS:
                tokens.append(Token(T.KEYWORD, upper, i, line, col, word))
            else:
                tokens.append(Token(T.IDENT, word, i, line, col))
            i = j
            continue
        # punctuation (longest match)
        matched = False
        for p in _PUNCT2:
            if text.startswith(p, i):
                tokens.append(Token(p, p, i, line, col))
                i += len(p)
                matched = True
                break
        if matched:
            continue
        if c in _PUNCT1:
            tokens.append(Token(c, c, i, line, col))
            i += 1
            continue
        err(f"unexpected character {c!r}", i)

    tokens.append(Token(T.EOF, None, n, line, n - line_start + 1))
    return tokens


def _scan_string(text, i, err):
    quote = text[i]
    out = []
    j = i + 1
    n = len(text)
    while j < n:
        c = text[j]
        if c == "\\":
            if j + 1 >= n:
                err("unterminated string", i)
            e = text[j + 1]
            mapping = {"n": "\n", "t": "\t", "r": "\r", "b": "\b", "f": "\f",
                       "'": "'", '"': '"', "\\": "\\", "/": "/"}
            if e in mapping:
                out.append(mapping[e])
                j += 2
            elif e == "u":
                if j + 6 > n:
                    err("bad unicode escape", j)
                out.append(chr(int(text[j + 2:j + 6], 16)))
                j += 6
            elif e == "U":
                if j + 10 > n:
                    err("bad unicode escape", j)
                out.append(chr(int(text[j + 2:j + 10], 16)))
                j += 10
            else:
                out.append(e)
                j += 2
            continue
        if c == quote:
            return "".join(out), j + 1
        out.append(c)
        j += 1
    err("unterminated string", i)


def _scan_number(text, i, line, col, err):
    n = len(text)
    j = i
    if text.startswith("0x", i) or text.startswith("0X", i):
        j = i + 2
        while j < n and text[j] in "0123456789abcdefABCDEF":
            j += 1
        return Token(T.INT, int(text[i:j], 16), i, line, col), j
    is_float = False
    while j < n and text[j].isdigit():
        j += 1
    if j < n and text[j] == "." and not text.startswith("..", j):
        if j + 1 < n and text[j + 1].isdigit():
            is_float = True
            j += 1
            while j < n and text[j].isdigit():
                j += 1
    if j < n and text[j] in "eE":
        k = j + 1
        if k < n and text[k] in "+-":
            k += 1
        if k < n and text[k].isdigit():
            is_float = True
            j = k
            while j < n and text[j].isdigit():
                j += 1
    raw = text[i:j]
    if is_float:
        value = float(raw)
        if value in (float("inf"), float("-inf")):
            # FloatingPointOverflow (TCK SemanticErrorAcceptance):
            # a literal too large for f64 is a compile-time error
            err(f"FloatingPointOverflow: float literal {raw!r} is out of "
                f"range", i)
        return Token(T.FLOAT, value, i, line, col), j
    # leading-zero octal (Cypher legacy)
    if len(raw) > 1 and raw[0] == "0" and all(ch in "01234567" for ch in raw[1:]):
        return Token(T.INT, int(raw, 8), i, line, col), j
    return Token(T.INT, int(raw), i, line, col), j
