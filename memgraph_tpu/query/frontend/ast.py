"""openCypher AST.

Lean dataclass tree mirroring the shape of the reference's AST
(/root/reference/src/query/frontend/ast/ast.hpp, 4.5k lines) at the altitude
this engine needs: expressions, patterns, clauses, queries.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


# --- expressions -------------------------------------------------------------

class Expr:
    __slots__ = ()


@dataclass
class Literal(Expr):
    value: object


@dataclass
class Parameter(Expr):
    name: str


@dataclass
class Identifier(Expr):
    name: str


@dataclass
class PropertyLookup(Expr):
    expr: Expr
    prop: str


@dataclass
class LabelsTest(Expr):
    expr: Expr
    labels: list[str]


@dataclass
class Unary(Expr):
    op: str  # '-', '+', 'NOT'
    expr: Expr


@dataclass
class Binary(Expr):
    op: str  # '+','-','*','/','%','^','=','<>','<','>','<=','>=',
             # 'AND','OR','XOR','IN','STARTS WITH','ENDS WITH','CONTAINS','=~'
    left: Expr
    right: Expr


@dataclass
class IsNull(Expr):
    expr: Expr
    negated: bool


@dataclass
class Subscript(Expr):
    expr: Expr
    index: Expr


@dataclass
class Slice(Expr):
    expr: Expr
    lo: Optional[Expr]
    hi: Optional[Expr]


@dataclass
class ListLiteral(Expr):
    items: list[Expr]


@dataclass
class MapLiteral(Expr):
    items: dict[str, Expr]


@dataclass
class FunctionCall(Expr):
    name: str            # lowercased, may be namespaced "ns.fn"
    args: list[Expr]
    distinct: bool = False


@dataclass
class CountStar(Expr):
    pass


@dataclass
class CaseExpr(Expr):
    test: Optional[Expr]               # CASE <test> WHEN ... (simple form)
    whens: list[tuple[Expr, Expr]]
    default: Optional[Expr]


@dataclass
class ListComprehension(Expr):
    var: str
    list_expr: Expr
    where: Optional[Expr]
    projection: Optional[Expr]


@dataclass
class Quantifier(Expr):
    kind: str  # 'ALL','ANY','NONE','SINGLE'
    var: str
    list_expr: Expr
    where: Expr


@dataclass
class Reduce(Expr):
    acc: str
    init: Expr
    var: str
    list_expr: Expr
    expr: Expr


@dataclass
class PatternExpr(Expr):
    """Pattern used as predicate/expression: exists((n)-[]->(m)))."""
    pattern: "Pattern"
    exists_form: bool = True


@dataclass
class PatternComprehension(Expr):
    """[(n)-[r]->(m) WHERE pred | projection]"""
    pattern: "Pattern"
    where: Optional[Expr]
    projection: Expr


# --- patterns ----------------------------------------------------------------

@dataclass
class NodePattern:
    variable: Optional[str]
    labels: list[str]
    properties: object = None     # dict[str, Expr] | Parameter | None


@dataclass
class Lambda:
    """(edge_var, node_var | expr) — weight/filter lambdas on expansions."""
    edge_var: str
    node_var: str
    expr: Expr


@dataclass
class EdgePattern:
    variable: Optional[str]
    types: list[str]
    direction: str                # 'out' (->), 'in' (<-), 'both' (--)
    properties: object = None
    var_length: bool = False
    min_hops: Optional[Expr] = None
    max_hops: Optional[Expr] = None
    algo: Optional[str] = None    # 'bfs' | 'wshortest' | 'allshortest'
    weight_lambda: Optional[Lambda] = None
    filter_lambda: Optional[Lambda] = None
    total_weight: Optional[str] = None


@dataclass
class Pattern:
    """Alternating [Node, Edge, Node, Edge, Node...] chain."""
    variable: Optional[str]
    elements: list


# --- clauses -----------------------------------------------------------------

class Clause:
    __slots__ = ()


@dataclass
class IndexHint:
    variable: str
    label: str
    properties: list[str]


@dataclass
class Match(Clause):
    patterns: list[Pattern]
    where: Optional[Expr] = None
    optional: bool = False
    index_hints: list = field(default_factory=list)
    hops_limit: Optional[int] = None
    parallel: bool = False       # USING PARALLEL EXECUTION hint


@dataclass
class Create(Clause):
    patterns: list[Pattern]


@dataclass
class Merge(Clause):
    pattern: Pattern
    on_create: list = field(default_factory=list)   # list[SetItem]
    on_match: list = field(default_factory=list)


@dataclass
class SetItem:
    kind: str      # 'prop' (n.p = e), 'var_assign' (n = expr),
                   # 'var_update' (n += expr), 'label' (n:Label:...)
    target: Expr   # PropertyLookup or Identifier
    value: object  # Expr or list[str] for labels


@dataclass
class SetClause(Clause):
    items: list[SetItem]


@dataclass
class RemoveItem:
    kind: str      # 'prop' or 'label'
    target: Expr
    labels: list[str] = field(default_factory=list)


@dataclass
class Remove(Clause):
    items: list[RemoveItem]


@dataclass
class Delete(Clause):
    exprs: list[Expr]
    detach: bool = False


@dataclass
class SortItem:
    expr: Expr
    ascending: bool = True


@dataclass
class ReturnBody:
    distinct: bool
    # (expr, explicit alias | None, verbatim source text | None)
    items: list[tuple[Expr, Optional[str], Optional[str]]]
    star: bool
    order_by: list[SortItem] = field(default_factory=list)
    skip: Optional[Expr] = None
    limit: Optional[Expr] = None


@dataclass
class Return(Clause):
    body: ReturnBody


@dataclass
class With(Clause):
    body: ReturnBody
    where: Optional[Expr] = None


@dataclass
class Unwind(Clause):
    expr: Expr
    variable: str


@dataclass
class CallProcedure(Clause):
    name: str
    args: Optional[list[Expr]]   # None = no parens (implicit/param args)
    yields: list[tuple[str, Optional[str]]]   # (field, alias)
    yield_star: bool = False
    where: Optional[Expr] = None
    yield_dash: bool = False     # CALL proc() YIELD - (explicitly nothing)
    memory_limit: Optional[int] = None   # PROCEDURE MEMORY LIMIT, bytes


@dataclass
class CallSubquery(Clause):
    """CALL { <single query> } [IN TRANSACTIONS OF n ROWS]."""
    query: "SingleQuery"
    batch_rows: Optional[int] = None


@dataclass
class Foreach(Clause):
    variable: str
    expr: Expr
    updates: list[Clause]


@dataclass
class LoadCsv(Clause):
    file: Expr
    variable: str
    with_header: bool = True
    ignore_bad: bool = False
    delimiter: Optional[Expr] = None
    quote: Optional[Expr] = None


@dataclass
class LoadJsonl(Clause):
    file: Expr
    variable: str


@dataclass
class LoadParquet(Clause):
    file: Expr
    variable: str


# --- queries -----------------------------------------------------------------

@dataclass
class SingleQuery:
    clauses: list[Clause]


@dataclass
class CypherQuery:
    query: SingleQuery
    unions: list[tuple[bool, SingleQuery]] = field(default_factory=list)
    # [(all?, query)]
    explain: bool = False
    profile: bool = False
    memory_limit: Optional[int] = None   # QUERY MEMORY LIMIT, bytes
    # USING PERIODIC COMMIT n: int literal or Parameter (reference:
    # MemgraphCypher.g4:413 periodicCommit pre-query directive)
    commit_frequency: Optional[object] = None


# --- administrative / DDL queries -------------------------------------------

@dataclass
class IndexQuery:
    action: str                     # 'create' | 'drop'
    kind: str                       # 'label' | 'label_property' | 'edge_type'
    label: Optional[str]
    properties: list[str] = field(default_factory=list)
    edge_type: Optional[str] = None


@dataclass
class ConstraintQuery:
    action: str                     # 'create' | 'drop'
    kind: str                       # 'exists' | 'unique' | 'type'
    label: str
    properties: list[str]
    data_type: Optional[str] = None


@dataclass
class InfoQuery:
    kind: str   # 'storage' | 'index' | 'constraint' | 'build' | 'metrics'


@dataclass
class TransactionQuery:
    action: str  # 'begin' | 'commit' | 'rollback'
    metadata: Optional[dict] = None


@dataclass
class ShowTransactionsQuery:
    pass


@dataclass
class TerminateTransactionsQuery:
    ids: list[Expr] = field(default_factory=list)


@dataclass
class SnapshotQuery:
    action: str  # 'create' | 'recover' | 'show'
    source: Optional[str] = None   # RECOVER SNAPSHOT FROM "<uri>"


@dataclass
class DumpQuery:
    pass


@dataclass
class AnalyzeGraphQuery:
    action: str = "analyze"  # 'analyze' | 'delete'
    labels: list[str] = field(default_factory=list)


@dataclass
class IsolationLevelQuery:
    level: str
    scope: str  # 'global' | 'session' | 'next'


@dataclass
class StorageModeQuery:
    mode: str   # 'IN_MEMORY_ANALYTICAL' | 'IN_MEMORY_TRANSACTIONAL'


@dataclass
class TriggerQuery:
    action: str                     # 'create' | 'drop' | 'show'
    name: Optional[str] = None
    event: Optional[str] = None     # e.g. 'CREATE' / 'UPDATE' / 'DELETE' / None
    phase: Optional[str] = None     # 'BEFORE' | 'AFTER'
    statement: Optional[str] = None


@dataclass
class SessionTraceQuery:
    enabled: bool


@dataclass
class EnumQuery:
    action: str                 # create | add_value | show
    name: Optional[str] = None
    values: list[str] = field(default_factory=list)


@dataclass
class EnumLiteral(Expr):
    enum_name: str
    value_name: str
    # evaluator's memo: (weakref-to-storage, EnumValue); excluded from
    # structural equality so ORDER BY column rewriting still matches
    resolved: object = field(default=None, compare=False, repr=False)


@dataclass
class SettingQuery:
    action: str                 # set | show_one | show_all
    name: Optional[str] = None
    value: Optional[str] = None


@dataclass
class MultiDatabaseQuery:
    action: str        # create | drop | use | show | suspend | resume
    name: Optional[str] = None


@dataclass
class TenantProfileQuery:
    action: str        # create | alter | drop | show | assign | clear
    name: Optional[str] = None
    limits: Optional[dict] = None      # key -> bytes | None (UNLIMITED)
    database: Optional[str] = None


@dataclass
class UserProfileQuery:
    """Per-user profiles (reference: MemgraphCypher.g4:974-991,
    auth/profiles/user_profiles.cpp)."""
    action: str        # create | update | drop | show | show_for |
    #                    users_for | assign | clear
    name: Optional[str] = None         # profile name
    user: Optional[str] = None
    limits: Optional[dict] = None


@dataclass
class CoordinatorQuery:
    action: str                 # register | unregister | set_main | show
    name: Optional[str] = None
    mgmt_address: Optional[str] = None
    replication_address: Optional[str] = None
    bolt_address: Optional[str] = None


@dataclass
class StreamQuery:
    action: str            # create | drop | start | stop | start_all |
                           # stop_all | show | check
    name: Optional[str] = None
    kind: str = "kafka"    # kafka | pulsar | file
    topics: list[str] = field(default_factory=list)
    transform: Optional[str] = None
    batch_size: int = 100
    batch_interval_ms: int = 100
    bootstrap_servers: str = ""
    service_url: str = ""
    consumer_group: str = ""


@dataclass
class TtlQuery:
    action: str            # enable | disable
    period: Optional[str] = None   # e.g. "1s", "5m"


@dataclass
class ReplicationQuery:
    action: str                 # set_role_main | set_role_replica |
                                # register | drop | show_replicas | show_role
    name: Optional[str] = None
    mode: Optional[str] = None  # SYNC | ASYNC | STRICT_SYNC
    address: Optional[str] = None
    port: Optional[int] = None


@dataclass
class AuthQuery:
    action: str   # create_user | drop_user | set_password | show_users |
                  # create_role | drop_role | set_role | show_roles |
                  # grant | deny | revoke | show_privileges
    user: Optional[str] = None
    password: Optional[object] = None
    role: Optional[str] = None
    privileges: list[str] = field(default_factory=list)
    fg_kind: Optional[str] = None       # labels | edge_types
    fg_items: list[str] = field(default_factory=list)
    fg_level: Optional[str] = None      # READ | UPDATE | CREATE_DELETE | NOTHING
