"""Compile-time semantic validation shared by the planner.

Counterpart of the reference's symbol generator / semantic checks
(/root/reference/src/query/frontend/semantic/symbol_generator.cpp):
unbound-variable detection with correct binder scoping, plus the
openCypher error classes the TCK exercises (VariableAlreadyBound,
InvalidArgumentType for IN, aggregation placement, ...).
"""

from __future__ import annotations

from ...exceptions import SemanticException
from . import ast as A


def check_expr_scope(expr: A.Expr | None, bound: set,
                     where: str = "expression") -> None:
    """Raise SemanticException for identifiers not in scope. `bound` is the
    set of visible variable names; binder expressions (comprehensions,
    reduce, quantifiers, pattern comprehensions) extend it locally."""
    if expr is None:
        return
    if isinstance(expr, A.Identifier):
        if expr.name not in bound:
            raise SemanticException(
                f"UndefinedVariable: {expr.name} is not defined "
                f"(in {where})")
        return
    if isinstance(expr, A.ListComprehension):
        check_expr_scope(expr.list_expr, bound, where)
        inner = bound | {expr.var}
        check_expr_scope(expr.where, inner, where)
        check_expr_scope(expr.projection, inner, where)
        return
    if isinstance(expr, A.Quantifier):
        check_expr_scope(expr.list_expr, bound, where)
        check_expr_scope(expr.where, bound | {expr.var}, where)
        return
    if isinstance(expr, A.Reduce):
        check_expr_scope(expr.init, bound, where)
        check_expr_scope(expr.list_expr, bound, where)
        check_expr_scope(expr.expr, bound | {expr.acc, expr.var}, where)
        return
    if isinstance(expr, (A.PatternExpr, A.PatternComprehension)):
        inner = set(bound)
        if expr.pattern.variable:
            inner.add(expr.pattern.variable)
        for item in expr.pattern.elements:   # [Node, Edge, Node, ...]
            if item.variable:
                inner.add(item.variable)
            props = getattr(item, "properties", None)
            if isinstance(props, dict):
                for v in props.values():
                    check_expr_scope(v, bound, where)
        if isinstance(expr, A.PatternComprehension):
            check_expr_scope(expr.where, inner, where)
            check_expr_scope(expr.projection, inner, where)
        return
    if isinstance(expr, A.Binary) and expr.op == "IN":
        # compile-time: IN with a literal non-list RHS
        # (TCK SemanticErrorAcceptance: InvalidArgumentType)
        rhs = expr.right
        if isinstance(rhs, A.Literal) and rhs.value is not None \
                and not isinstance(rhs.value, (list, tuple)):
            raise SemanticException(
                f"InvalidArgumentType: IN expects a list, "
                f"got {rhs.value!r}")
    for child in _children(expr):
        check_expr_scope(child, bound, where)


def _children(expr):
    from ..plan.planner import _children_exprs
    return _children_exprs(expr)


def _contains_call(expr, name: str) -> bool:
    if isinstance(expr, A.FunctionCall) and expr.name.lower() == name:
        return True
    return any(_contains_call(c, name) for c in _children(expr))


def check_static_types(expr: A.Expr | None, kinds: dict) -> None:
    """Static argument-type errors the TCK requires at COMPILE time
    (SemanticErrorAcceptance / SyntaxErrorAcceptance /
    MiscellaneousErrorAcceptance): functions applied to entity kinds they
    can never accept, property access on a variable-length relationship
    list, unknown function names, and non-deterministic rand() inside
    aggregations. `kinds` is the planner's variable->kind map
    (node|edge|path|edge_list|value)."""
    if expr is None:
        return
    # binders rebind their variable: the outer kind must not leak into
    # the body (e.g. [r IN [{a: 1}] | r.a] where r is a var-length rel)
    if isinstance(expr, (A.ListComprehension, A.Quantifier)):
        check_static_types(expr.list_expr, kinds)
        inner = {k: v for k, v in kinds.items() if k != expr.var}
        check_static_types(getattr(expr, "where", None), inner)
        check_static_types(getattr(expr, "projection", None), inner)
        return
    if isinstance(expr, A.Reduce):
        check_static_types(expr.init, kinds)
        check_static_types(expr.list_expr, kinds)
        inner = {k: v for k, v in kinds.items()
                 if k not in (expr.acc, expr.var)}
        check_static_types(expr.expr, inner)
        return
    if isinstance(expr, A.PatternComprehension):
        # pattern variables are fresh bindings local to the comprehension
        inner = dict(kinds)
        if expr.pattern.variable:
            inner.pop(expr.pattern.variable, None)
        for item in expr.pattern.elements:
            if item.variable:
                inner.pop(item.variable, None)
        check_static_types(expr.where, inner)
        check_static_types(expr.projection, inner)
        return
    if isinstance(expr, A.PropertyLookup) and isinstance(expr.expr,
                                                         A.Identifier):
        if kinds.get(expr.expr.name) == "edge_list":
            raise SemanticException(
                f"InvalidArgumentType: {expr.expr.name} is a variable "
                f"length relationship (a list), not a single relationship")
    if isinstance(expr, A.FunctionCall):
        name = expr.name.lower()
        arg_kind = None
        if expr.args and isinstance(expr.args[0], A.Identifier):
            arg_kind = kinds.get(expr.args[0].name)
        if name == "type" and arg_kind in ("node", "path"):
            raise SemanticException(
                f"InvalidArgumentType: type() expects a relationship, "
                f"got a {arg_kind}")
        if name == "length" and arg_kind in ("node", "edge"):
            raise SemanticException(
                f"InvalidArgumentType: length() expects a path, "
                f"got a {arg_kind}")
        if name == "size" and arg_kind in ("path", "node", "edge"):
            raise SemanticException(
                f"InvalidArgumentType: size() expects a list or string, "
                f"got a {arg_kind}")
        # exists() is intercepted by the parser (never a FunctionCall
        # here); its argument check lives in parser.py
        from ..functions import FUNCTIONS
        from ..plan.operators import AGGREGATE_FUNCTIONS
        if name in AGGREGATE_FUNCTIONS:
            for a in expr.args:
                if _contains_call(a, "rand"):
                    raise SemanticException(
                        "NonConstantExpression: rand() is not allowed "
                        "inside aggregation functions")
        elif name not in FUNCTIONS and "." not in expr.name:
            raise SemanticException(
                f"UnknownFunction: {expr.name}() is not a known function")
    for child in _children(expr):
        check_static_types(child, kinds)


def check_no_aggregates(expr: A.Expr | None, context: str) -> None:
    """Aggregation functions are invalid in WHERE / pattern properties /
    procedure args (TCK: InvalidAggregation)."""
    if expr is None:
        return
    from ..plan.planner import collect_aggregations
    aggs: list = []
    collect_aggregations(expr, aggs)
    if aggs:
        raise SemanticException(
            f"InvalidAggregation: aggregation functions are not allowed "
            f"in {context}")
